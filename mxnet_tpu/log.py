"""``mx.log`` — logging helpers (reference: python/mxnet/log.py)."""
from __future__ import annotations

import logging

__all__ = ["get_logger", "getLogger"]

_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def get_logger(name=None, filename=None, filemode="a", level=logging.WARNING):
    logger = logging.getLogger(name)
    if name is None and filename is None:
        # never hijack the ROOT logger's handlers/level from a library
        # helper (reference log.py configures named loggers only)
        return logger
    # init-once guard (reference log.py _init_done): repeat calls must not
    # stack handlers and double every message
    if not getattr(logger, "_mxtpu_log_init", False):
        if filename:
            handler = logging.FileHandler(filename, filemode)
        else:
            handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(_FMT))
        logger.addHandler(handler)
        logger._mxtpu_log_init = True
    logger.setLevel(level)
    return logger


getLogger = get_logger  # reference alias
