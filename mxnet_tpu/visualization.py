"""``mx.viz`` — network visualization.

Reference: python/mxnet/visualization.py — `plot_network` (graphviz render of
a Symbol) and `print_summary` (layer table with shapes/params).
"""
from __future__ import annotations

import numpy as _np

__all__ = ["plot_network", "print_summary"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-by-layer table (reference: visualization.py print_summary)."""
    from .symbol.symbol import _topo
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    arg_shapes = {}
    out_shapes_map = {}
    if shape:
        arg_sh, _, aux_sh = symbol.infer_shape(**shape)
        arg_shapes = dict(zip(symbol.list_arguments(), arg_sh))
        arg_shapes.update(zip(symbol.list_auxiliary_states(), aux_sh))
        from .symbol.symbol import _infer_shapes_partial
        var_shapes, node_shapes = _infer_shapes_partial(
            symbol, {k: v for k, v in shape.items()})
        out_shapes_map = node_shapes

    def prow(fields):
        line = ""
        for f, pos in zip(fields, positions):
            line = (line + str(f))[:pos].ljust(pos)
        print(line)

    print("=" * line_length)
    prow(["Layer (type)", "Output Shape", "Param #", "Previous Layer"])
    print("=" * line_length)
    total = 0
    for node in _topo(symbol):
        if node.kind != "op":
            continue
        oshape = out_shapes_map.get((id(node), 0), "")
        nparams = 0
        prev = []
        for x in node.inputs:
            if x is None or not hasattr(x, "kind"):
                continue
            if x.kind == "var" and x.name in arg_shapes \
                    and x.name not in (shape or {}):
                # user-supplied inputs (data/label) are not parameters
                shp = arg_shapes.get(x.name)
                if shp:
                    nparams += int(_np.prod(shp))
            elif x.kind != "var":
                prev.append(x.name)
        total += nparams
        prow(["%s (%s)" % (node.name, node.op), oshape, nparams,
              ",".join(prev)])
    print("=" * line_length)
    print("Total params: %d" % total)
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Graphviz Digraph of the Symbol DAG (requires python-graphviz; raises
    ImportError otherwise, matching the reference's optional dep)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("plot_network requires the graphviz package") from e
    from .symbol.symbol import _topo
    node_attrs = node_attrs or {}
    dot = Digraph(name=title)
    attrs = {"shape": "box", "fixedsize": "false", "style": "filled"}
    attrs.update(node_attrs)
    palette = {"FullyConnected": "#fb8072", "Convolution": "#fb8072",
               "BatchNorm": "#bebada", "Activation": "#ffffb3",
               "Pooling": "#80b1d3", "softmax": "#fccde5"}
    for node in _topo(symbol):
        if node.kind == "var":
            if hide_weights and node.name != "data" and \
                    not node.name.endswith("label"):
                continue
            dot.node(node.name, node.name, shape="oval", style="filled",
                     fillcolor="#8dd3c7")
        elif node.kind == "op":
            color = palette.get(node.op, "#b3de69")
            dot.node(node.name, "%s\n%s" % (node.name, node.op),
                     fillcolor=color, **attrs)
            for x in node.inputs:
                if x is None or not hasattr(x, "kind"):
                    continue
                src = x.inputs[0] if x.kind == "slice" else x
                if src.kind == "var" and hide_weights and \
                        src.name != "data" and \
                        not src.name.endswith("label"):
                    continue
                dot.edge(src.name, node.name)
    return dot
