"""``mx.resilience`` — fault-tolerant training primitives.

Reference: the framework this repo reproduces earned its production keep by
SURVIVING things — parameter-server retry semantics (``src/kvstore/``,
ps-lite resender), per-epoch checkpoint callbacks
(``python/mxnet/callback.py do_checkpoint``), and operators guarding
against NaN blowups.  The TPU port gets one coherent module instead of
scattered defensive code:

  * **atomic checkpoint writer** — ``atomic_write`` publishes files via
    tmp + fsync + ``os.replace`` so a crash mid-write can never leave a
    half-written file under the real name; ``write_manifest`` /
    ``verify_checkpoint`` add a CRC32 + schema sidecar so truncation and
    bit rot are *detected*, not discovered as a deep ``EOFError``.
  * **CheckpointManager** — periodic ``maybe_save`` every N steps,
    retention of the last K checkpoints, ``latest()`` discovery, and
    ``restore`` that falls back past a corrupt newest checkpoint to the
    last good one (bumping ``resilience.ckpt_fallbacks``).
  * **preemption-safe shutdown** — ``MXNET_TPU_ON_PREEMPT=save_and_exit``
    installs SIGTERM/SIGINT handlers that only set a flag; the training
    loops (``Module.fit`` / ``SPMDTrainer.step`` / gluon ``Trainer.step``)
    finish the in-flight step, checkpoint, flush the telemetry/trace
    sinks, and exit 0 via ``exit_on_preempt``.
  * **non-finite step guard** — ``MXNET_TPU_NANGUARD=skip|abort`` folds an
    on-device all-finite check over loss+grads into the fused train step
    (``all_finite`` / ``guarded_streak`` / ``select_tree``).  Bad steps
    skip the optimizer update on device and notify the host through a
    ``lax.cond``-gated ``jax.debug.callback`` — the happy path pays no
    host sync.  After K consecutive bad steps the PR-3 watchdog flight
    recorder dumps and the run aborts WITH a checkpoint.
  * **retry with exponential backoff + jitter** — ``call_with_retry`` /
    ``retry`` wrap the io batch fetch, kvstore push/pull and checkpoint
    I/O; retries land on ``resilience.retries[.<kind>]`` counters.
  * **deterministic fault injection** — ``MXNET_TPU_FAULTS=
    io:0.05,ckpt_write:1@step=3,nan:1@step=7`` (seeded by
    ``MXNET_TPU_FAULT_SEED``) makes every path above testable; the chaos
    smoke (tools/check_resilience.py) proves a faulted run converges
    bitwise-identically to an unfaulted one.  PR 7 extends the harness
    into the serving plane: the ``serving_dispatch`` (fail a batch
    dispatch — feeds the mx.serving circuit breaker) and ``serving_slow``
    (delay a dispatch — shed/deadline/stall testing) kinds drive
    tools/check_serving_chaos.py, and ``call_with_retry`` doubles as the
    serving batcher's restart supervisor (kind ``serving_batcher``).

Knobs live in config.py under ``resilience.*``; recovery semantics are
documented in docs/RESILIENCE.md.
"""
from __future__ import annotations

import contextlib
import json
import os
import random as _pyrandom
import re
import signal
import sys
import tempfile
import threading
import time
import zlib
from collections import namedtuple

from .base import MXNetError

__all__ = [
    "CheckpointCorruptError", "NonFiniteStepError", "InjectedFault",
    "atomic_write", "write_manifest", "verify_checkpoint", "manifest_path",
    "CheckpointManager", "CKPT_SCHEMA", "MANIFEST_SCHEMA",
    "configure_preemption", "preempt_requested", "clear_preempt",
    "exit_on_preempt", "flush_sinks",
    "nanguard_mode", "all_finite", "guarded_streak", "select_tree",
    "report_nonfinite", "note_finite", "maybe_abort_nonfinite",
    "nonfinite_stats", "reset_nanguard",
    "call_with_retry", "retry", "configure_retry",
    "configure_faults", "parse_faults", "should_inject", "inject",
    "faults_active", "poison_batch", "FaultRule",
]

#: schema version stamped into SPMDTrainer single-file checkpoints; loaders
#: refuse files from a NEWER schema with CheckpointCorruptError instead of
#: misinterpreting them.
CKPT_SCHEMA = 1
MANIFEST_SCHEMA = 1


class CheckpointCorruptError(MXNetError):
    """A checkpoint file is missing, truncated, fails its CRC, or carries
    an unsupported schema.  CheckpointManager.restore treats this as
    "fall back to the previous checkpoint"."""


class NonFiniteStepError(MXNetError):
    """Raised by the nanguard abort path after K consecutive non-finite
    steps: the flight recorder has dumped and (when a manager is attached)
    a checkpoint of the last-good params was written."""


class InjectedFault(OSError):
    """A deterministic fault from the MXNET_TPU_FAULTS harness.  Subclasses
    OSError so the retry machinery and io error handling treat it exactly
    like the real transient failure it simulates."""


def _telemetry():
    from . import telemetry
    return telemetry


def _log(msg, *args):
    sys.stderr.write("[mxnet_tpu.resilience] " + (msg % args) + "\n")


# =========================================================== atomic writer
@contextlib.contextmanager
def atomic_write(path, mode="wb"):
    """Write ``path`` atomically: the bytes land in a same-directory temp
    file, are fsynced, and only then renamed over the target
    (``os.replace``), with a directory fsync making the rename durable.
    A crash — or an injected ``ckpt_write`` fault — at ANY point leaves
    the previous file intact and no temp debris under the real name::

        with atomic_write("model.params") as f:
            f.write(payload)
    """
    if mode not in ("wb", "w"):
        raise ValueError("atomic_write supports modes 'wb'/'w', got %r"
                         % (mode,))
    path = os.fspath(path)
    dirname = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.", dir=dirname)
    ok = False
    try:
        f = os.fdopen(fd, mode)
        try:
            yield f
            # the simulated crash point: AFTER content was written to the
            # temp file, BEFORE anything was published
            inject("ckpt_write")
            f.flush()
            os.fsync(f.fileno())
        finally:
            f.close()
        os.replace(tmp, path)
        ok = True
        try:  # make the rename itself durable
            dfd = os.open(dirname, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # pragma: no cover — exotic fs without dir fsync
            pass
    finally:
        if not ok:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def manifest_path(path):
    return os.fspath(path) + ".manifest.json"


def _crc32_file(path):
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return crc & 0xFFFFFFFF, size


def write_manifest(path, step=None, world=None):
    """Write the integrity sidecar ``<path>.manifest.json`` (CRC32 + size
    + schema version) for an already-published checkpoint file.

    ``world`` (optional dict, e.g. ``{"process_count": 4, "mesh":
    {"dcn": 2, "dp": 2}}``) stamps the multi-host shape the snapshot was
    coordinated under; ``elastic.CoordinatedCheckpointManager.restore``
    refuses snapshots without it (torn-write guard)."""
    crc, size = _crc32_file(path)
    man = {"schema": MANIFEST_SCHEMA, "file": os.path.basename(path),
           "size": size, "crc32": crc, "ts": round(time.time(), 3)}
    if step is not None:
        man["step"] = int(step)
    if world is not None:
        man["world"] = dict(world)
    with atomic_write(manifest_path(path), "w") as f:
        json.dump(man, f)
    return man


def verify_checkpoint(path, require_manifest=False):
    """Check ``path`` against its manifest sidecar.  Returns the manifest
    dict, or None when no sidecar exists and ``require_manifest`` is False
    (legacy files: the loader's own validation is the only guard).  Raises
    CheckpointCorruptError on a missing file, size/CRC mismatch, or a
    manifest from a newer schema."""
    path = os.fspath(path)
    if not os.path.exists(path):
        raise CheckpointCorruptError("checkpoint missing: %s" % path)
    mp = manifest_path(path)
    if not os.path.exists(mp):
        if require_manifest:
            raise CheckpointCorruptError(
                "checkpoint %s has no manifest sidecar" % path)
        return None
    try:
        with open(mp) as f:
            man = json.load(f)
    except (OSError, ValueError) as exc:
        raise CheckpointCorruptError(
            "unreadable manifest %s (%s)" % (mp, exc)) from exc
    if not isinstance(man, dict) or "crc32" not in man or "size" not in man:
        raise CheckpointCorruptError("malformed manifest %s" % mp)
    if int(man.get("schema", 0)) > MANIFEST_SCHEMA:
        raise CheckpointCorruptError(
            "manifest %s written by a newer schema (%s > %s)"
            % (mp, man.get("schema"), MANIFEST_SCHEMA))
    crc, size = _crc32_file(path)
    if size != int(man["size"]) or crc != int(man["crc32"]):
        raise CheckpointCorruptError(
            "checkpoint %s fails integrity check (size %d vs %s, crc %d "
            "vs %s) — truncated or corrupt" % (path, size, man["size"],
                                               crc, man["crc32"]))
    return man


# ======================================================= CheckpointManager
class CheckpointManager:
    """Periodic, retained, integrity-checked checkpoints in one directory.

    ``saver``/``loader`` callables receive a path — pass bound methods like
    ``trainer.save_checkpoint`` / ``trainer.load_checkpoint`` directly::

        mgr = CheckpointManager(dir, every_n_steps=100, keep=3)
        resumed = mgr.restore(trainer.load_checkpoint)   # None on cold start
        for step, (x, y) in enumerate(batches, (resumed or 0) + 1):
            trainer.step(x, y)
            mgr.maybe_save(step, trainer.save_checkpoint)

    ``restore`` walks newest→oldest, skipping any checkpoint whose manifest
    or content fails validation (CheckpointCorruptError), so a file
    truncated by a crash costs one fallback, never the run.
    """

    def __init__(self, directory, every_n_steps=None, keep=None,
                 prefix="ckpt"):
        from . import config
        self.directory = os.path.abspath(os.fspath(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.every_n_steps = int(
            config.get("resilience.ckpt_every_n_steps")
            if every_n_steps is None else every_n_steps)
        self.keep = int(config.get("resilience.ckpt_keep")
                        if keep is None else keep)
        self.prefix = prefix
        self._pat = re.compile(r"^%s-(\d+)\.ckpt$" % re.escape(prefix))

    def path_for(self, step):
        return os.path.join(self.directory,
                            "%s-%08d.ckpt" % (self.prefix, int(step)))

    def checkpoints(self):
        """[(step, path)] sorted ascending by step."""
        out = []
        for fname in os.listdir(self.directory):
            m = self._pat.match(fname)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, fname)))
        return sorted(out)

    def latest(self):
        """(step, path) of the newest checkpoint that passes verification,
        or None."""
        for step, path in reversed(self.checkpoints()):
            try:
                verify_checkpoint(path)
            except CheckpointCorruptError:
                continue
            return step, path
        return None

    def save(self, step, saver):
        """``saver(path_for(step))`` + manifest, under checkpoint-I/O
        retry; prunes beyond the retention bound afterwards."""
        path = self.path_for(step)

        def write():
            saver(path)
            write_manifest(path, step=step)

        call_with_retry(write, kind="ckpt_write")
        _telemetry().counter("resilience.ckpt_saves").inc()
        self._prune()
        return path

    def maybe_save(self, step, saver):
        """``save`` when ``step`` lands on the every-N cadence (0 = never);
        returns the path or None."""
        n = self.every_n_steps
        if n > 0 and step > 0 and step % n == 0:
            return self.save(step, saver)
        return None

    def restore(self, loader):
        """Load the newest good checkpoint, falling back past corrupt ones;
        returns the restored step or None when nothing was loadable."""
        for step, path in reversed(self.checkpoints()):
            try:
                verify_checkpoint(path)
                loader(path)
            except CheckpointCorruptError as exc:
                _telemetry().counter("resilience.ckpt_fallbacks").inc()
                _log("checkpoint %s unusable (%s); falling back", path, exc)
                continue
            return step
        return None

    def _prune(self):
        cks = self.checkpoints()
        if self.keep <= 0 or len(cks) <= self.keep:
            return
        for _, path in cks[:-self.keep]:
            for victim in (path, manifest_path(path)):
                try:
                    os.unlink(victim)
                except OSError:
                    pass


# ============================================================== preemption
_PREEMPT = {"signum": None, "mode": "", "installed": False, "prev": {}}


def configure_preemption(mode=None):
    """(Un)install the SIGTERM/SIGINT preemption handlers.  Called by the
    ``resilience.on_preempt`` knob hook and at import from
    ``MXNET_TPU_ON_PREEMPT``.  Modes: '' (off) or 'save_and_exit'."""
    from . import config
    if mode is None:
        mode = config.get("resilience.on_preempt")
    mode = (mode or "").strip()
    if mode not in ("", "save_and_exit"):
        raise ValueError("resilience.on_preempt must be '' or "
                         "'save_and_exit', got %r" % (mode,))
    _PREEMPT["mode"] = mode
    want = bool(mode)
    if want == _PREEMPT["installed"]:
        return
    if threading.current_thread() is not threading.main_thread():
        _log("preemption handlers need the main thread; not installed")
        return
    if want:
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                _PREEMPT["prev"][sig] = signal.signal(sig, _on_preempt_signal)
            except (ValueError, OSError):  # pragma: no cover — odd runtime
                _log("could not install handler for signal %s", sig)
        _PREEMPT["installed"] = True
    else:
        for sig, prev in _PREEMPT["prev"].items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        _PREEMPT["prev"].clear()
        _PREEMPT["installed"] = False
        # turning the feature off also forgets any pending request, so a
        # later training loop cannot trip over a stale signal
        _PREEMPT["signum"] = None


def _on_preempt_signal(signum, frame):
    if _PREEMPT["signum"] is not None:
        # second signal: the operator means it — stop waiting for the
        # in-flight step and die the conventional way
        if signum == signal.SIGINT:
            raise KeyboardInterrupt
        raise SystemExit(128 + signum)
    _PREEMPT["signum"] = signum
    _telemetry().counter("resilience.preemptions").inc()
    try:
        from . import tracing
        tracing.record_event("preempt", "signal_%d" % signum)
    except Exception:  # noqa: BLE001 — never let telemetry kill the handler
        pass
    _log("received signal %d: will checkpoint and exit after the "
         "in-flight step", signum)


def preempt_requested():
    """Cheap per-step poll: has a preemption signal arrived?"""
    return _PREEMPT["signum"] is not None


def request_preempt(signum=signal.SIGTERM):
    """Programmatic preemption notice — same effect as receiving SIGTERM.

    Used by ``mx.elastic`` when the cluster agreement says a PEER was
    preempted (every rank must finish the in-flight step and checkpoint
    together) and by the deterministic ``peer_preempt`` fault kind."""
    if _PREEMPT["signum"] is None:
        _PREEMPT["signum"] = int(signum)
        _telemetry().counter("resilience.preemptions").inc()
        try:
            from . import tracing
            tracing.record_event("preempt", "requested_%d" % int(signum))
        except Exception:  # noqa: BLE001 — telemetry must not break this
            pass


def clear_preempt():
    """Reset the preemption flag (tests / in-process chaos harnesses)."""
    _PREEMPT["signum"] = None


def exit_on_preempt(save_fn=None, logger=None):
    """Finish a preemption: run ``save_fn`` (the caller's checkpoint hook),
    flush the telemetry/trace sinks, and exit 0.  No-op (returns False)
    when no signal is pending."""
    if not preempt_requested():
        return False
    if save_fn is not None:
        try:
            save_fn()
        except Exception as exc:  # noqa: BLE001 — exit anyway, but loudly
            _log("preemption checkpoint failed: %s: %s",
                 type(exc).__name__, exc)
    flush_sinks()
    msg = "preemption (signal %s): checkpoint written, exiting cleanly" \
        % _PREEMPT["signum"]
    if logger is not None:
        logger.info(msg)
    else:
        _log("%s", msg)
    raise SystemExit(0)


def flush_sinks():
    """Flush the telemetry JSONL and tracing Chrome sinks to disk — the
    last thing a preempted/aborting process does before exiting."""
    for name in ("telemetry", "tracing"):
        try:
            import importlib
            mod = importlib.import_module("." + name, __package__)
            mod.flush()
        except Exception:  # noqa: BLE001 — flushing is best-effort
            pass


# =========================================================== non-finite guard
_NAN_LOCK = threading.Lock()
_NAN_STATE = {}  # source -> {"streak": int, "total": int}
_NAN_ABORT = {}  # source -> streak that crossed the threshold


def nanguard_mode():
    """'' (off), 'skip', or 'abort' — from the ``resilience.nanguard``
    knob (MXNET_TPU_NANGUARD). Read at trace time by the fused steps; the
    compiled-program caches key on it so flips rebuild the program."""
    from . import config
    mode = str(config.get("resilience.nanguard")).strip().lower()
    if mode in ("", "off", "0", "false"):
        return ""
    if mode not in ("skip", "abort"):
        raise ValueError("MXNET_TPU_NANGUARD must be skip or abort, got %r"
                         % mode)
    return mode


def _nan_threshold(mode):
    if mode == "abort":
        return 1
    from . import config
    return max(1, int(config.get("resilience.nanguard_patience")))


def all_finite(*trees):
    """Traced: one boolean scalar — are ALL floating leaves of the given
    pytrees finite?  Non-float leaves (int state, counters) are ignored."""
    import jax
    import jax.numpy as jnp
    checks = []
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            x = jnp.asarray(leaf)
            if jnp.issubdtype(x.dtype, jnp.inexact):
                checks.append(jnp.all(jnp.isfinite(x)))
    if not checks:
        return jnp.bool_(True)
    return jnp.stack(checks).all()


def guarded_streak(finite, streak, source=None):
    """Traced: fold the consecutive-bad-step streak — 0 after a finite
    step, +1 after a non-finite one.  Deliberately effect-free: an earlier
    design notified the host with ``jax.debug.callback`` inside a
    ``lax.cond``, but merely *carrying* that effect routes every dispatch
    through the runtime's host-callback machinery (~4x step cost on small
    programs, even with the branch never taken).  Instead the host learns
    about bad steps by polling returned streak arrays that have already
    materialized (``watch_streak``), which costs no sync at all."""
    import jax.numpy as jnp
    return jnp.where(finite, jnp.zeros_like(streak), streak + 1)


# returned streak scalars awaiting a no-sync host inspection
_STREAK_PENDING = {}  # source -> list of (jax.Array) in step order
_STREAK_PENDING_MAX = 64  # force-drain bound: ~seconds of lag, tiny memory
# serializes the pop-from-front drain: concurrent pollers on one source
# would otherwise double-pop (dropping an observation on the floor) or
# IndexError on an emptied queue.  append stays lock-free.
_STREAK_DRAIN_LOCK = threading.Lock()


def watch_streak(source, streak):
    """Queue a fused step's returned streak scalar for host inspection.
    Called once per guarded step by the training loops; drains every
    entry whose computation has finished (``is_ready`` — reading those is
    free) and NEVER blocks on in-flight steps, so the async-dispatch
    pipeline stays intact."""
    q = _STREAK_PENDING.setdefault(source, [])
    q.append(streak)
    poll_streaks(source, block=len(q) > _STREAK_PENDING_MAX)


def poll_streaks(source=None, block=False):
    """Drain pending streak observations: each one is a completed step's
    consecutive-bad-step count.  ``block=True`` waits for in-flight steps
    (tests and abort paths use it to force promptness); the default only
    reads arrays that are already on host-reachable memory."""
    sources = [source] if source is not None else list(_STREAK_PENDING)
    for src in sources:
        q = _STREAK_PENDING.get(src)
        while q:
            with _STREAK_DRAIN_LOCK:
                if not q:
                    break
                arr = q[0]
                try:
                    if not block and not arr.is_ready():
                        break
                    v = int(arr)
                except Exception:  # noqa: BLE001 — dead buffer ends watch
                    q.pop(0)
                    continue
                q.pop(0)
            if v > 0:
                report_nonfinite(src, streak=v)
            else:
                note_finite(src)


def select_tree(finite, new, old):
    """Traced: ``new`` where the step was finite, ``old`` otherwise —
    the on-device "skip the optimizer update" select."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(finite, n, o), new, old)


def report_nonfinite(source, streak=None):
    """Record one non-finite step for ``source``: bump
    ``<source>.nonfinite_steps``, feed the flight-recorder ring, and arm
    the abort flag once the streak crosses the mode's threshold.  Host
    paths (eager Module update, gluon Trainer) call this with
    ``streak=None`` and the streak is tracked here."""
    with _NAN_LOCK:
        st = _NAN_STATE.setdefault(source, {"streak": 0, "total": 0})
        st["streak"] = int(streak) if streak is not None \
            else st["streak"] + 1
        st["total"] += 1
        cur = st["streak"]
    _telemetry().counter("%s.nonfinite_steps" % source).inc()
    try:
        from . import tracing
        tracing.record_event("nonfinite", source, streak=cur)
    except Exception:  # noqa: BLE001
        pass
    mode = nanguard_mode()
    if mode and cur >= _nan_threshold(mode):
        _NAN_ABORT[source] = cur
    _log("non-finite step on %s (consecutive: %d)", source, cur)


def note_finite(source):
    """Host-path streak reset (eager loops call this on good steps; the
    fused paths reset the streak on device)."""
    st = _NAN_STATE.get(source)
    if st is not None and st["streak"]:
        with _NAN_LOCK:
            st["streak"] = 0


def nonfinite_stats(source=None):
    with _NAN_LOCK:
        if source is not None:
            return dict(_NAN_STATE.get(source, {"streak": 0, "total": 0}))
        return {k: dict(v) for k, v in _NAN_STATE.items()}


def reset_nanguard():
    with _NAN_LOCK:
        _NAN_STATE.clear()
        _NAN_ABORT.clear()
    _STREAK_PENDING.clear()


def maybe_abort_nonfinite(source, save_fn=None):
    """Checked once per step by the training loops (a dict lookup — free).
    When ``source`` has crossed its consecutive-bad-step threshold: dump
    the PR-3 watchdog flight recorder, checkpoint via ``save_fn``, flush
    sinks, and raise NonFiniteStepError.  Because the device notifies the
    host asynchronously, the abort lands within a step or two of the
    threshold crossing (``poll_streaks(block=True)`` forces it in
    tests)."""
    if _STREAK_PENDING.get(source):
        poll_streaks(source)  # no-sync drain of completed steps
    if source not in _NAN_ABORT:
        return
    streak = _NAN_ABORT.pop(source)
    try:
        # root-cause pass BEFORE the flight-recorder dump so the
        # nanguard_forensics ring event (first non-finite site) lands in
        # the report; replays the held failing batch through the
        # stats-instrumented program (docs/OBSERVABILITY.md)
        from . import numerics as _numerics
        _numerics.run_forensics(source)
    except Exception as exc:  # noqa: BLE001 — forensics must not mask abort
        _log("nanguard forensics failed: %s: %s", type(exc).__name__, exc)
    report = None
    try:
        from . import tracing
        report = tracing.dump_watchdog_report()
    except Exception as exc:  # noqa: BLE001 — the abort must not be lost
        _log("flight-recorder dump failed: %s: %s", type(exc).__name__, exc)
    if save_fn is not None:
        try:
            save_fn()
        except Exception as exc:  # noqa: BLE001
            _log("abort checkpoint failed: %s: %s", type(exc).__name__, exc)
    flush_sinks()
    raise NonFiniteStepError(
        "%d consecutive non-finite steps on %s (nanguard=%s)%s — params "
        "were NOT updated by the bad steps" % (
            streak, source, nanguard_mode() or "abort",
            "; flight recorder: %s" % report if report else ""))


# ================================================ retry / backoff / jitter
_RETRY = {"attempts": 3, "base_s": 0.05, "factor": 2.0, "max_s": 2.0,
          "jitter": 0.5, "rng": _pyrandom.Random(0)}


def configure_retry(attempts=None, base_s=None, seed=None):
    """Refresh the retry policy from the ``resilience.retry_*`` knobs
    (hook-driven so the hot path reads a plain dict, not the knob
    registry)."""
    from . import config
    _RETRY["attempts"] = max(1, int(
        config.get("resilience.retry_attempts")
        if attempts is None else attempts))
    _RETRY["base_s"] = float(config.get("resilience.retry_base_s")
                             if base_s is None else base_s)
    _RETRY["rng"] = _pyrandom.Random(
        config.get("resilience.fault_seed") if seed is None else seed)


def call_with_retry(fn, *args, kind="io", inject_faults=False, **kwargs):
    """Run ``fn`` with exponential backoff + seeded jitter on OSError
    (which includes InjectedFault).  ``inject_faults=True`` draws a
    ``kind`` fault before each attempt — the injection point sits where
    the wire/disk would fail, BEFORE the body mutates anything, so
    retrying an injected fault is always safe.  StopIteration and
    non-OSError exceptions pass straight through.  Each retry bumps
    ``resilience.retries`` and ``resilience.retries.<kind>``."""
    attempts = _RETRY["attempts"]
    delay = _RETRY["base_s"]
    for attempt in range(1, attempts + 1):
        try:
            if inject_faults and _FAULTS:
                inject(kind)
            return fn(*args, **kwargs)
        except OSError as exc:
            if attempt >= attempts:
                raise
            tel = _telemetry()
            tel.counter("resilience.retries").inc()
            tel.counter("resilience.retries.%s" % kind).inc()
            sleep = min(_RETRY["max_s"],
                        delay * (1.0 + _RETRY["jitter"]
                                 * _RETRY["rng"].random()))
            _log("%s failed (%s: %s); retry %d/%d in %.3fs", kind,
                 type(exc).__name__, exc, attempt, attempts - 1, sleep)
            time.sleep(sleep)
            delay *= _RETRY["factor"]


def retry(kind="io", inject_faults=False):
    """Decorator form of ``call_with_retry``::

        @resilience.retry(kind="kvstore")
        def push(...): ...
    """
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return call_with_retry(fn, *args, kind=kind,
                                   inject_faults=inject_faults, **kwargs)
        return wrapper
    return deco


# =========================================================== fault harness
FaultRule = namedtuple("FaultRule", ["kind", "prob", "count", "at_step"])

_FAULTS = {}       # kind -> FaultRule; empty dict == harness off
_FAULT_RNGS = {}   # kind -> seeded random.Random (probability rules)
_FAULT_CALLS = {}  # kind -> opportunity counter (count rules w/o step)


def parse_faults(spec):
    """Parse ``MXNET_TPU_FAULTS``: comma-separated ``kind:rule`` entries.

    * ``kind:P`` with float P in [0, 1] — inject with probability P at
      each opportunity (seeded, deterministic per kind).
    * ``kind:N@step=M`` — inject on exactly N opportunities starting at
      the M-th (1-based).  "Opportunity" is the per-kind call counter
      unless the caller passes an explicit ``step`` (the trainers pass
      their global step for ``nan``, so a resumed run re-injects at the
      same training step, not the same call index).
    """
    rules = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        if ":" not in entry:
            raise ValueError("fault entry %r is not kind:rule" % entry)
        kind, rule = entry.split(":", 1)
        kind = kind.strip()
        if "@" in rule:
            count_s, cond = rule.split("@", 1)
            if not cond.startswith("step="):
                raise ValueError("fault entry %r: expected @step=N" % entry)
            rules[kind] = FaultRule(kind, None, int(count_s),
                                    int(cond[len("step="):]))
        else:
            p = float(rule)
            if not 0.0 <= p <= 1.0:
                raise ValueError(
                    "fault entry %r: probability out of [0,1]" % entry)
            rules[kind] = FaultRule(kind, p, None, None)
    return rules


def configure_faults(spec=None, seed=None):
    """(Re)arm the harness from the ``resilience.faults`` /
    ``resilience.fault_seed`` knobs (or explicit args).  Resets all
    per-kind RNGs and opportunity counters, so two runs configured the
    same inject the same faults."""
    from . import config
    if spec is None:
        spec = config.get("resilience.faults")
    if seed is None:
        seed = int(config.get("resilience.fault_seed"))
    rules = parse_faults(spec)
    _FAULTS.clear()
    _FAULT_RNGS.clear()
    _FAULT_CALLS.clear()
    _FAULTS.update(rules)
    for kind in rules:
        _FAULT_RNGS[kind] = _pyrandom.Random(
            seed ^ zlib.crc32(kind.encode()))
    configure_retry(seed=seed)


def faults_active(kind=None):
    if kind is None:
        return bool(_FAULTS)
    return kind in _FAULTS


def should_inject(kind, step=None):
    """One injection draw for ``kind`` (advances its deterministic
    state).  ``step`` overrides the opportunity counter for @step rules —
    trainers pass their global step so resume doesn't shift the fault."""
    rule = _FAULTS.get(kind)
    if rule is None:
        return False
    _FAULT_CALLS[kind] = _FAULT_CALLS.get(kind, 0) + 1
    if rule.at_step is not None:
        n = step if step is not None else _FAULT_CALLS[kind]
        hit = rule.at_step <= n < rule.at_step + rule.count
    else:
        hit = _FAULT_RNGS[kind].random() < rule.prob
    if hit:
        _telemetry().counter("resilience.injected.%s" % kind).inc()
    return hit


def inject(kind, step=None):
    """Raise InjectedFault when this opportunity draws a ``kind`` fault;
    no-op when the harness is off or the draw misses."""
    if _FAULTS and should_inject(kind, step=step):
        raise InjectedFault("injected %s fault (MXNET_TPU_FAULTS)" % kind)


def poison_batch(data):
    """The ``nan`` fault: multiply a float batch by NaN so the loss and
    every gradient go non-finite (int batches — token ids — pass through
    untouched with a warning, since NaN has no integer encoding)."""
    import jax.numpy as jnp
    arr = jnp.asarray(data)
    if not jnp.issubdtype(arr.dtype, jnp.inexact):
        _log("nan fault requested on non-float batch dtype %s; skipped",
             arr.dtype)
        return data
    return arr * jnp.nan


# ----------------------------------------------------- import-time wiring
# Mirror telemetry/tracing: honor the env knobs at import so a launcher
# exporting MXNET_TPU_FAULTS / MXNET_TPU_ON_PREEMPT / retry knobs gets the
# harness without any code change.  config never imports resilience at
# module scope, so no cycle.
from . import config as _config  # noqa: E402,F401

try:
    configure_faults()
    if _config.get("resilience.on_preempt"):
        configure_preemption()
except KeyError:  # pragma: no cover — config stripped of the knobs
    pass
