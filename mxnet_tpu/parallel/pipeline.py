"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.3: model parallelism
is manual `group2ctx` device placement, src/executor/graph_executor.cc:997 —
cross-device copies inserted between subgraphs).  The TPU-native design
instead shards the LAYER dimension over a 'pp' mesh axis: every device holds
one pipeline stage's parameters, microbatches march through the ring with one
``lax.ppermute`` hop per tick, and the whole schedule — bubbles included —
is a single ``lax.scan`` that XLA compiles and jax.grad differentiates (the
transpose of ppermute is the reverse rotation, so the backward pipeline falls
out of autodiff instead of hand-written send/recv like GPipe runtimes).

Layout contract (inside shard_map over `axis_name`):
  stage_params — THIS device's stage (leading stage axis already split off)
  x            — [n_micro, micro_batch, ...] microbatched input, replicated;
                 only stage 0 reads it
  returns      — [n_micro, micro_batch, ...] final-stage outputs, replicated
                 (broadcast off the last stage with a psum)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.8 moved shard_map out of experimental
    from jax import shard_map as _shard_map_raw
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_raw

__all__ = ["pipeline_apply", "pipeline_sharded", "microbatch",
           "unmicrobatch", "shmap"]


import inspect as _inspect

_SHMAP_KW = ({"check_rep": False}
             if "check_rep" in _inspect.signature(
                 _shard_map_raw).parameters else {})


def shmap(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: the experimental API needs
    check_rep=False for bodies whose collectives confuse its replication
    checker; the jax>=0.8 API dropped the kwarg (its varying-axis inference
    handles these bodies)."""
    return _shard_map_raw(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **_SHMAP_KW)


def microbatch(x, n_micro):
    """[B, ...] -> [n_micro, B // n_micro, ...]."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, n_micro))
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def unmicrobatch(x):
    """[n_micro, mb, ...] -> [n_micro * mb, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def pipeline_apply(stage_fn, stage_params, x, axis_name="pp",
                   vary_axes=None):
    """Run the microbatched `x` through the stage ring.  Call INSIDE
    shard_map.

    stage_fn(stage_params, act) -> act — one pipeline stage.  Activations
    must keep one shape through the pipeline (the usual transformer-block
    contract); the first stage receives the raw microbatch, so embed/head
    asymmetries belong inside stage_fn gated on ``lax.axis_index``.

    vary_axes — mesh axes the activations vary over, for jax>=0.8's
    varying-manual-axes carry typing.  Defaults to the input's axes plus
    `axis_name`; a stage whose body makes outputs vary over MORE axes
    (e.g. an internal expert-parallel all_to_all) must name them here.
    """
    n_micro = x.shape[0]
    n_stage = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
    ticks = n_micro + n_stage - 1

    def tick(carry, t):
        act = carry
        # stage 0 ingests microbatch t (clamped during drain ticks; those
        # outputs are never selected)
        x_t = lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        inp = jnp.where(idx == 0, x_t.astype(act.dtype), act)
        out = stage_fn(stage_params, inp)
        # one ICI hop: my output becomes the next stage's input
        nxt = lax.ppermute(out, axis_name, perm)
        return nxt, out

    act0 = jnp.zeros(x.shape[1:], x.dtype)
    if hasattr(lax, "pcast"):
        # jax>=0.8 tracks varying-manual-axes: the carry starts replicated
        # but turns varying after the first ppermute — mark it up front
        if vary_axes is None:
            xv = getattr(jax.typeof(x), "vma", frozenset()) \
                if hasattr(jax, "typeof") else frozenset()
            vary_axes = tuple(set(xv) | {axis_name})
        act0 = lax.pcast(act0, tuple(vary_axes), to="varying")
    _, outs = lax.scan(tick, act0, jnp.arange(ticks))

    # microbatch j leaves the last stage at tick j + n_stage - 1
    y = lax.dynamic_slice_in_dim(outs, n_stage - 1, n_micro, 0)
    # broadcast the last stage's result to every stage (zeros elsewhere, so
    # the psum is a select); its transpose re-routes cotangents to the last
    # stage only, which is exactly the backward pipeline's entry point.
    return lax.psum(jnp.where(idx == n_stage - 1, y, jnp.zeros_like(y)),
                    axis_name)


def pipeline_sharded(mesh, stage_fn, stacked_params, x, n_micro,
                     axis_name="pp"):
    """shard_map wrapper: `stacked_params` leaves have a leading stage axis
    of size mesh.shape[axis_name] (sharded over it); `x` is a full [B, ...]
    batch.  Returns [B, ...] outputs.
    """
    def local(params, xm):
        # split off this device's stage (leading axis shard of size 1)
        mine = jax.tree_util.tree_map(lambda v: v[0], params)
        return pipeline_apply(stage_fn, mine, xm, axis_name=axis_name)

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    fn = shmap(local, mesh, (pspec, P()), P())
    return unmicrobatch(fn(stacked_params, microbatch(x, n_micro)))
