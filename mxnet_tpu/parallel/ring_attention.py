"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has no sequence parallelism (SURVEY.md §5.7: long sequences are
handled by BucketingModule bucketing, python/mxnet/module/bucketing_module.py:40);
for a TPU-native framework long-context is first-class, so attention shards
its sequence dimension over the 'sp' mesh axis and rotates key/value blocks
around the ring with ``lax.ppermute`` while accumulating a numerically-stable
online softmax (flash-attention style running max / running sum).  Each hop
rides one ICI link, so per-step comm is O(block) and overlaps the matmuls.

Layouts (global logical shapes):
  q, k, v: [batch, heads, seq, head_dim]
  sharding: batch -> 'dp', heads -> 'tp', seq -> 'sp'
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .pipeline import shmap

__all__ = ["ring_attention", "attention", "ring_self_attention_sharded"]

_NEG = -1e30


def _block_attn(q, k, v, scale, mask):
    """One q-block x kv-block partial attention.

    Returns (o_partial, m, l): un-normalized output, row max, row sum.
    q: [..., Sq, D], k/v: [..., Sk, D], mask broadcastable to [..., Sq, Sk].
    """
    s = jnp.einsum("...qd,...kd->...qk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=-1, keepdims=True)
    o = jnp.einsum("...qk,...kd->...qd", e.astype(v.dtype), v)
    return o, m, l


def attention(q, k, v, causal=False, scale=None):
    """Single-device (or XLA-sharded) softmax attention; fp32 accumulate on
    the MXU via preferred_element_type."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    mask = None
    if causal:
        sq, sk = q.shape[-2], k.shape[-2]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
    o, m, l = _block_attn(q, k, v, scale, mask)
    return (o / l.astype(o.dtype)).astype(q.dtype)


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None):
    """Ring attention over `axis_name`: call INSIDE shard_map.

    q/k/v are the local sequence shards [B, H, S_loc, D].  Equivalent math to
    full attention over the gathered sequence, at O(S_loc) memory.
    """
    d = q.shape[-1]
    s_loc = q.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc_shape = q.shape[:-1] + (d,)
    o0 = jnp.zeros(acc_shape, jnp.float32)
    m0 = jnp.full(q.shape[:-1] + (1,), _NEG, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1] + (1,), jnp.float32)
    if hasattr(lax, "pcast"):
        # jax>=0.8 varying-manual-axes typing: the accumulators start
        # replicated but turn axis-varying inside the ring loop
        vma = tuple(getattr(jax.typeof(q), "vma", ()) or ()) or (axis_name,)
        vma = tuple(set(vma) | {axis_name})
        o0, m0, l0 = (lax.pcast(t, vma, to="varying")
                      for t in (o0, m0, l0))

    def body(step, carry):
        o, m, l, kb, vb = carry
        src = (my - step) % n
        mask = None
        if causal:
            q_pos = my * s_loc + jnp.arange(s_loc)[:, None]
            k_pos = src * s_loc + jnp.arange(s_loc)[None, :]
            mask = k_pos <= q_pos
        ob, mb, lb = _block_attn(q, kb, vb, scale, mask)
        m_new = jnp.maximum(m, mb)
        corr = jnp.exp(m - m_new)
        corr_b = jnp.exp(mb - m_new)
        o = o * corr + ob.astype(jnp.float32) * corr_b
        l = l * corr + lb * corr_b
        # rotate kv one hop around the ring (ICI neighbor exchange)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return o, m_new, l, kb, vb

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_self_attention_sharded(mesh, q, k, v, causal=False,
                                batch_axis="dp", head_axis="tp",
                                seq_axis="sp"):
    """shard_map-wrapped ring attention over a full [B, H, S, D] array whose
    axes are sharded (batch->'dp', heads->'tp', seq->'sp') on `mesh`."""
    spec = P(batch_axis, head_axis, seq_axis, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    return shmap(fn, mesh, (spec, spec, spec), spec)(q, k, v)
