"""Mixture-of-Experts with expert parallelism over a mesh axis.

No reference counterpart (SURVEY.md §2.3 lists expert parallelism as absent
from the reference); built TPU-first: experts are sharded over an 'ep' mesh
axis and tokens travel to their expert's device through ONE pair of
``lax.all_to_all`` collectives (dispatch + return), the canonical
Switch/GShard layout where the routing tensors stay static-shaped — capacity
slots instead of dynamic gathers — so XLA can compile one fixed program.

Routing is top-k softmax gating with per-expert capacity; overflowing tokens
are dropped (their combine weight is zero), matching Switch Transformer
semantics.  Everything is differentiable: the all_to_all transposes are the
reverse all_to_alls, and the load-balancing auxiliary loss is returned for
the caller to add to the objective.

Layout contract (inside shard_map over `axis_name`):
  x        — [T_loc, d] this device's tokens (batch/'dp'-sharded)
  gate_w   — [d, E] replicated router weights (E = global expert count)
  w1/b1/w2/b2 — THIS device's expert shard: [E_loc, ...], E = E_loc * n_ep
  returns  — ([T_loc, d] combined outputs, scalar aux loss)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .pipeline import shmap

__all__ = ["moe_ffn", "moe_ffn_sharded", "top_k_routing"]


def top_k_routing(logits, k, capacity):
    """Static-shape top-k routing.

    logits [T, E] -> dispatch [T, E, C] one-hot slot assignment,
    combine [T, E, C] gating weights, aux (load-balance loss).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # claimed slots per expert accumulate across the k passes so the 2nd
    # choice never collides with slots taken by 1st choices
    base = jnp.zeros((e,), jnp.int32)
    masked = probs
    for _ in range(k):
        choice = jnp.argmax(masked, axis=-1)                  # [T]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # [T, E]
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot      # slot within
        pos = pos + base[None, :] * onehot                     # expert
        keep = (pos < capacity) * onehot                       # fits?
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=jnp.float32) * keep[..., None]
        gate = jnp.sum(probs * onehot, axis=-1, keepdims=True)  # [T, 1]
        dispatch = dispatch + slot
        combine = combine + slot * gate[..., None]
        base = base + jnp.sum(keep, axis=0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)  # next pass picks a new expert

    # Switch-style load balancing: E * sum_e fraction_tokens_e * mean_prob_e
    frac = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, axis=-1), e, dtype=jnp.float32),
        axis=0)
    aux = e * jnp.sum(frac * jnp.mean(probs, axis=0))
    return dispatch, combine, aux


def moe_ffn(gate_w, w1, b1, w2, b2, x, axis_name="ep", k=2,
            capacity_factor=2.0, activation=jax.nn.gelu):
    """Expert-parallel MoE feed-forward.  Call INSIDE shard_map.

    x [T, d]; gate_w [d, E] (replicated); w1 [E_loc, d, h], b1 [E_loc, h],
    w2 [E_loc, h, d], b2 [E_loc, d].  Returns (y [T, d], aux loss).
    """
    n_ep = lax.psum(1, axis_name)
    e_loc = w1.shape[0]
    e = e_loc * n_ep
    t, d = x.shape
    capacity = max(1, int(capacity_factor * k * t / e))

    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        gate_w.astype(jnp.float32))
    dispatch, combine, aux = top_k_routing(logits, k, capacity)

    # dispatch into per-expert capacity buffers: [E, C, d]
    buf = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # ship every expert's buffer to the device that owns it: the global
    # expert axis becomes (n_ep groups of E_loc); after all_to_all this
    # device holds ITS E_loc experts' slots from every peer
    buf = buf.reshape(n_ep, e_loc, capacity, d)
    buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                   # [n_ep, E_loc, C, d]
    buf = buf.transpose(1, 0, 2, 3).reshape(e_loc, n_ep * capacity, d)

    h = activation(jnp.einsum("ecd,edh->ech", buf, w1.astype(jnp.float32))
                   + b1[:, None, :].astype(jnp.float32))
    y = jnp.einsum("ech,ehd->ecd", h, w2.astype(jnp.float32)) \
        + b2[:, None, :].astype(jnp.float32)

    # return trip: inverse reshuffle + all_to_all back to the token owners
    y = y.reshape(e_loc, n_ep, capacity, d).transpose(1, 0, 2, 3)
    y = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                       tiled=False)
    y = y.reshape(e, capacity, d)
    out = jnp.einsum("tec,ecd->td", combine, y)
    return out.astype(x.dtype), aux


def moe_ffn_sharded(mesh, gate_w, w1, b1, w2, b2, x, axis_name="ep",
                    batch_axis="dp", k=2, capacity_factor=2.0,
                    activation=jax.nn.gelu):
    """shard_map wrapper.  Tokens are sharded over BOTH the data and expert
    axes (the GShard layout: every device routes a distinct token shard, so
    the all_to_alls move distinct data); expert weights [E, ...] shard on
    `axis_name`; gate_w is replicated.  The aux loss is the mesh-wide mean.
    """
    def fn(gw, a1, c1, a2, c2, xs):
        y, aux = moe_ffn(gw, a1, c1, a2, c2, xs, axis_name=axis_name, k=k,
                         capacity_factor=capacity_factor,
                         activation=activation)
        return y, lax.pmean(aux, mesh.axis_names)

    espec = P(axis_name)
    tok = P((batch_axis, axis_name))
    shmapped = shmap(fn, mesh, (P(), espec, espec, espec, espec, tok),
                     (tok, P()))
    return shmapped(gate_w, w1, b1, w2, b2, x)
