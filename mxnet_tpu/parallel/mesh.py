"""Device-mesh construction and sharding-axis conventions.

Replaces the reference's device-topology machinery — KVStore comm topologies
solved from the PCIe/NVLink link matrix (src/kvstore/gpu_topology.h,
src/kvstore/comm_tree.h:50) and manual ``group2ctx`` placement
(src/executor/graph_executor.cc:997).  On TPU the topology is a named
``jax.sharding.Mesh`` and placement is a PartitionSpec; XLA lowers every
cross-device exchange to ICI/DCN collectives.

Axis conventions (used across mxnet_tpu.parallel and mxnet_tpu.models):
  'dp'  data parallel          (batch dimension)
  'fsdp' fully-sharded DP      (parameters sharded over the dp workers)
  'tp'  tensor parallel        (attention heads / hidden features)
  'sp'  sequence/context par.  (ring attention over sequence blocks)
  'pp'  pipeline parallel      (layer stages)
  'ep'  expert parallel        (MoE experts)
  'dcn' data-center network    (cross-slice/host hop — the slow axis;
                               gradient sync over it may be 2-bit
                               compressed, see parallel/compression.py)
"""
from __future__ import annotations

import math

import numpy as _np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["AXES", "make_mesh", "data_parallel_mesh", "sharding",
           "shard_batch", "replicated", "local_mesh_devices",
           "PartitionSpec", "Mesh", "NamedSharding"]

AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep", "dcn")


def local_mesh_devices(n=None):
    devs = jax.devices()
    if n is not None:
        if len(devs) < n:
            raise ValueError(
                "Requested %d devices but only %d available" % (n, len(devs)))
        devs = devs[:n]
    return devs


def make_mesh(axes=None, devices=None):
    """Create a Mesh from an {axis_name: size} dict.

    Sizes may use -1 for one axis to absorb the remaining devices, mirroring
    how the reference auto-solves its reduction topology from whatever links
    exist (gpu_topology.h) — here the "solver" is trivial because the TPU
    torus is homogeneous and XLA handles the physical routing.
    """
    if axes is None:
        axes = {"dp": -1}
    devices = list(devices) if devices is not None else jax.devices()
    names = list(axes.keys())
    sizes = [int(axes[n]) for n in names]
    n_dev = len(devices)
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if n_dev % known:
            raise ValueError("Cannot infer -1 axis: %d devices, known=%d"
                             % (n_dev, known))
        sizes[sizes.index(-1)] = n_dev // known
    if math.prod(sizes) != n_dev:
        raise ValueError("Mesh %s does not cover %d devices"
                         % (dict(zip(names, sizes)), n_dev))
    arr = _np.asarray(devices, dtype=object).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(devices=None):
    return make_mesh({"dp": -1}, devices)


def sharding(mesh, *spec):
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_batch(mesh, batch, axis="dp"):
    """Place an array (or pytree) with dim-0 sharded over `axis` —
    the DataParallelExecutorGroup slice-over-contexts analog
    (python/mxnet/module/executor_group.py:144), done by sharding instead
    of slicing."""
    sh = sharding(mesh, axis)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sh), batch)


def replicated(mesh, tree):
    sh = sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)
