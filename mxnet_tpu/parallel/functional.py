"""Functionalize a Gluon Block into a pure (params, apply) pair.

Reference analog: CachedOp extracts a static NNVM graph from a HybridBlock
(src/imperative/cached_op.cc; python/mxnet/gluon/block.py:969 _build_cache)
so the executor can schedule it without Python.  On TPU the equivalent is a
*pure function* over a parameter pytree: XLA compiles it once, and every
sharding/parallelism decision (pjit/shard_map) composes with it.

``functionalize(block)`` returns the trainable/aux split plus an ``apply``
suitable for jax.grad / jax.jit / pjit: auxiliary-state mutations (BatchNorm
running stats — grad_req='null' parameters written during forward) are
captured during tracing and returned explicitly, keeping ``apply`` pure.
"""
from __future__ import annotations

from collections import OrderedDict

from ..ndarray.ndarray import _wrap
from .. import autograd
from .. import _tape  # noqa: F401  (kept: recording must be off inside apply)
from .. import random as _random

__all__ = ["functionalize", "BlockFunction"]


class BlockFunction:
    """Pure-function view of a Block.

    Attributes:
      params        OrderedDict name -> Parameter (all of them)
      trainable     list of names with grad_req != 'null'
      aux           list of names with grad_req == 'null' (running stats)
    ``apply(param_map, inputs, key, training)`` takes/returns raw jax arrays:
      -> (outputs_tuple, new_aux_map)
    """

    def __init__(self, block):
        self.block = block
        self.params = OrderedDict(
            (name, p) for name, p in block.collect_params().items())
        self.trainable = [n for n, p in self.params.items()
                          if p.grad_req != "null"]
        self.aux = [n for n, p in self.params.items() if p.grad_req == "null"]

    def init_values(self):
        """Current parameter values as {name: jax.Array}."""
        return {n: p.data()._data for n, p in self.params.items()}

    def apply(self, param_map, inputs, key=None, training=True):
        from ..gluon import block as block_mod
        block = self.block
        params = self.params
        if key is None:
            key = _random.new_eager_seed_key()
        originals = {}
        wrappers = {}
        for n, p in params.items():
            originals[n] = p._data
            w = _wrap(param_map[n])
            wrappers[n] = w
            p._data = w
        prev_guard = block_mod._TRACE_GUARD.active
        block_mod._TRACE_GUARD.active = True
        try:
            with autograd._RecordingStateScope(False, training):
                with _random.trace_key_scope(key):
                    out = block._eager_forward(
                        *[_wrap(v) for v in inputs])
        finally:
            block_mod._TRACE_GUARD.active = prev_guard
            for n, p in params.items():
                p._data = originals[n]
        multi = isinstance(out, (tuple, list))
        out_vals = tuple(o._data for o in out) if multi else (out._data,)
        new_aux = {}
        for n in self.aux:
            w = wrappers[n]
            if w._data is not param_map[n]:
                new_aux[n] = w._data
        return out_vals, new_aux

    def write_back(self, param_map):
        """Write jax values back into the live Parameters (post-training)."""
        for n, p in self.params.items():
            if n in param_map:
                with autograd.pause():
                    p.data()._data = param_map[n]


def functionalize(block):
    return BlockFunction(block)
