"""mx.parallel.embedding — mesh-sharded embedding tables with deduplicated
row-sparse lookup/update (docs/PERF_NOTES.md "Sharded embeddings").

The recommendation-scale workload (DLRM-style: tables of 10^5..10^9 rows,
each batch touching a few thousand of them) needs three things the dense
data-parallel step cannot give:

  1. **No full-table replication.**  The table is sharded on the VOCAB axis
     over one mesh axis (``NamedSharding(mesh, P(axis))``); every lookup and
     every optimizer update runs under ``shard_map`` so each shard answers
     only the ids it owns and the per-id results meet on ICI via ``psum``
     (owner contributes the row, everyone else contributes zeros).  A dense
     image of the table never exists on any one device.

  2. **Per-batch id deduplication with STATIC shapes.**  Real id batches are
     heavily repeated (Zipf traffic) and ragged.  ``jnp.unique`` with a
     static ``size=`` + sentinel ``fill_value`` keeps the compiled shapes
     identical across batches — one gather per unique id, results scattered
     back through the inverse map, and ``fused_compiles`` stays flat.

  3. **O(rows-touched) updates.**  The update reuses ``Optimizer.step_rows``
     (the lazy row_sparse path of optimizer.py) per shard: only the touched
     rows of the table AND its optimizer state are read/written, inside the
     same donated program as the dense step.

Padding contract: index batches padded by ``io.DevicePrefetcher`` carry a
SENTINEL id (any id >= num_rows; the prefetcher's ``pad_sentinel``).  The
lookup returns zero rows for sentinel ids and the update drops them — on
the owning-shard test ``sentinel - shard_base`` falls outside every shard's
``[0, rows_per_shard)`` range, so the scatter's out-of-bounds-drop semantics
mask them with no extra branch.

Routing: ``SPMDTrainer`` detects trainable 2-D ``grad_stype='row_sparse'``
parameters (what ``gluon.nn.Embedding(sparse_grad=True)`` declares) and,
when ``embedding.sharded`` is on, routes their op calls through
``SparseLookupContext`` below: the table enters the loss as a
NON-differentiated argument, the gathered unique rows get a zero "delta"
leaf added, and the delta's gradient IS the deduplicated row gradient —
``jax.grad`` never materializes a dense table cotangent.
"""
from __future__ import annotations

import math as _math
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ShardedEmbedding", "dedup_ids", "lookup_unique", "update_unique",
           "unique_capacity", "sparse_embedding_params",
           "SparseLookupContext"]


def unique_capacity(n_ids):
    """Static unique-id capacity for a batch of ``n_ids`` indices.

    Default (``embedding.unique_size`` = 0) is ``n_ids`` — always safe,
    since a batch cannot contain more distinct ids than elements.  A
    positive knob value caps the capacity (smaller compiled buffers when
    the per-batch unique count is known to be bounded); ids beyond the cap
    would be silently dropped, so the knob is a user contract.
    """
    from .. import config as _cfg
    cap = int(_cfg.get("embedding.unique_size") or 0)
    n = int(n_ids)
    return n if cap <= 0 else min(cap, n)


def dedup_ids(ids, size, sentinel):
    """Deduplicate a batch of ids with STATIC output shapes.

    Returns ``(uniq, inv)``: ``uniq`` is ``[size]`` int32, sorted ascending,
    padded with ``sentinel`` (which sorts last when ``sentinel >= num_rows``);
    ``inv`` maps every flattened input position to its row in ``uniq``.
    Compiled shapes depend only on ``ids.size`` and ``size`` — ragged batches
    that pad to the same bucket reuse the same program.
    """
    flat = jnp.ravel(jnp.asarray(ids)).astype(jnp.int32)
    uniq, inv = jnp.unique(flat, return_inverse=True, size=int(size),
                           fill_value=jnp.int32(sentinel))
    return uniq, jnp.ravel(inv)


def lookup_unique(table, uniq, mesh=None, axis=None):
    """Gather ``table[uniq]`` — sharded when ``mesh``/``axis`` are given.

    Sharded: each shard answers only the ids it owns (local gather on its
    ``[rows_per_shard, dim]`` slice) and contributes zeros elsewhere; one
    ``psum`` over ``axis`` combines the answers on ICI.  Ids outside the
    table (the pad sentinel) come back as zero rows on every path.
    """
    num_rows = int(table.shape[0])
    if mesh is None or axis is None:
        safe = jnp.minimum(uniq, num_rows - 1)
        vals = jnp.take(table, safe, axis=0)
        return jnp.where((uniq < num_rows)[:, None], vals,
                         jnp.zeros((), table.dtype))
    rows_per = num_rows // int(mesh.shape[axis])

    def _shard(tbl, u):
        base = jax.lax.axis_index(axis) * rows_per
        local = u - base
        owned = (local >= 0) & (local < rows_per)
        vals = jnp.take(tbl, jnp.where(owned, local, 0), axis=0)
        vals = jnp.where(owned[:, None], vals, jnp.zeros((), tbl.dtype))
        return jax.lax.psum(vals, axis)

    return shard_map(_shard, mesh=mesh, in_specs=(P(axis, None), P()),
                     out_specs=P())(table, uniq)


def update_unique(optimizer, table, state, uniq, grad_rows, lr, wd, t,
                  mesh=None, axis=None):
    """Row-sparse optimizer update on deduplicated ids.

    Reuses ``optimizer.step_rows`` — only the rows named in ``uniq`` (and
    the same rows of every optimizer-state leaf) are read and written.
    Sentinel/out-of-table ids map to an out-of-range row index, which the
    ``.at[rows]`` scatters inside ``step_rows`` DROP (jax's default
    out-of-bounds scatter mode), so padded ids never touch the table.

    Sharded (``mesh``+``axis``): runs per shard under ``shard_map`` with the
    shard's local row offsets; non-owned ids fall out of the local range and
    are dropped the same way.  Returns ``(new_table, new_state)``.
    """
    num_rows = int(table.shape[0])
    if mesh is None or axis is None:
        rows = jnp.where(uniq < num_rows, uniq, num_rows)  # OOB -> dropped
        return optimizer.step_rows(table, rows, grad_rows, state, lr, wd, t)
    rows_per = num_rows // int(mesh.shape[axis])

    def _local_rows(u):
        base = jax.lax.axis_index(axis) * rows_per
        local = u - base
        owned = (local >= 0) & (local < rows_per)
        return jnp.where(owned, local, rows_per)  # OOB -> dropped

    if state is None:
        def _shard(tbl, u, g, lr_, wd_, t_):
            new_w, _ = optimizer.step_rows(tbl, _local_rows(u), g, None,
                                           lr_, wd_, t_)
            return new_w
        new_table = shard_map(
            _shard, mesh=mesh,
            in_specs=(P(axis, None), P(), P(), P(), P(), P()),
            out_specs=P(axis, None))(table, uniq, grad_rows, lr, wd, t)
        return new_table, None

    state_spec = jax.tree_util.tree_map(lambda _: P(axis, None), state)

    def _shard(tbl, st, u, g, lr_, wd_, t_):
        return optimizer.step_rows(tbl, _local_rows(u), g, st, lr_, wd_, t_)

    return shard_map(
        _shard, mesh=mesh,
        in_specs=(P(axis, None), state_spec, P(), P(), P(), P(), P()),
        out_specs=(P(axis, None), state_spec))(
            table, state, uniq, grad_rows, lr, wd, t)


def sparse_embedding_params(fn, mesh, axis):
    """Map trainable sparse-grad embedding params to their routing metadata.

    Selects 2-D trainable parameters declared ``grad_stype='row_sparse'``
    (``gluon.nn.Embedding(sparse_grad=True)``).  Each entry carries the
    table's row count, embedding dim and the mesh axis to shard the vocab
    over — ``None`` (replicated table, still deduplicated + row-sparse
    updates) when the axis has one device or the rows don't divide it.
    Empty when the ``embedding.sharded`` knob is off.
    """
    from .. import config as _cfg
    if not _cfg.get("embedding.sharded"):
        return OrderedDict()
    out = OrderedDict()
    axis_size = int(mesh.shape[axis]) if axis in mesh.axis_names else 1
    for n in fn.trainable:
        p = fn.params[n]
        if getattr(p, "_grad_stype", "default") != "row_sparse":
            continue
        shape = getattr(p, "shape", None)
        if not shape or len(shape) != 2 or not shape[0] or not shape[1]:
            continue  # deferred or non-2-D params stay on the dense path
        rows, dim = int(shape[0]), int(shape[1])
        shard_axis = axis if (axis_size > 1 and rows % axis_size == 0) \
            else None
        out[n] = {"rows": rows, "dim": dim, "axis": shard_axis}
    return out


class SparseLookupContext:
    """Routes ``Embedding(sparse_grad=True)`` op calls inside ONE fused-step
    trace through the sharded deduplicated lookup.

    The trainer passes each table into the loss as a NON-differentiated
    argument plus a zero ``delta`` leaf of shape ``[capacity, dim]``; the
    context adds the delta to the gathered unique rows, so the delta's
    gradient is exactly the deduplicated per-row gradient (summed over
    duplicates through the inverse-map scatter) and no dense table
    cotangent is ever built.  Op calls are matched to tables by weight
    shape; each table supports one lookup per forward (its single delta
    leaf carries the row gradient).
    """

    def __init__(self, mesh, meta, deltas):
        self._mesh = mesh
        self._meta = meta        # name -> {'rows', 'dim', 'axis'}
        self._deltas = deltas    # name -> [capacity, dim] zero grad leaves
        self._by_shape = {(m["rows"], m["dim"]): n for n, m in meta.items()}
        self.records = {}        # name -> uniq ids seen this forward

    def lookup(self, data, weight):
        """Sharded deduplicated gather, or None for unrouted weights."""
        shape = tuple(int(s) for s in weight.shape)
        name = self._by_shape.get(shape)
        if name is None:
            return None
        if name in self.records:
            raise NotImplementedError(
                "sparse-grad embedding %r is looked up more than once per "
                "forward (or shares its %r shape with another sparse "
                "table); the sharded row-sparse path supports one lookup "
                "per table — set config embedding.sharded=False for this "
                "model" % (name, shape))
        meta = self._meta[name]
        sentinel = meta["rows"]
        ids = jnp.asarray(data)
        uniq, inv = dedup_ids(ids, self._deltas[name].shape[0], sentinel)
        rows = lookup_unique(jax.lax.stop_gradient(weight), uniq,
                             self._mesh if meta["axis"] else None,
                             meta["axis"])
        rows = rows + self._deltas[name].astype(rows.dtype)
        from .. import numerics as _numerics
        # fused-step trace opens a numerics collector when instrumented;
        # the touched unique rows are the interesting tensor (the dense
        # take() output just repeats them)
        rows = _numerics.tap("embedding.%s.rows" % name, rows)
        self.records[name] = uniq
        return jnp.take(rows, inv, axis=0).reshape(
            tuple(ids.shape) + (shape[1],))


class ShardedEmbedding:
    """A mesh-sharded embedding table with deduplicated lookups and lazy
    row-sparse updates — the standalone counterpart of the fused-step
    routing (same ``dedup_ids``/``lookup_unique``/``update_unique``
    primitives; SPMDTrainer wires those into its donated program directly).

    Programs are cached per ids-shape, so ragged batches padded to a common
    bucket reuse one compile (``embedding.lookup_compiles`` counts cache
    misses).  Every call feeds the ``embedding.*`` telemetry:
    ``unique_ratio`` gauge, ``gathered_rows``/``rows_touched`` counters and
    the ``lookup_ms`` timer (this eager API intentionally blocks on the
    device so the timer measures real work).
    """

    def __init__(self, num_rows, dim, mesh=None, axis=None,
                 dtype=jnp.float32, optimizer=None, init_scale=0.01,
                 seed=0):
        from .mesh import data_parallel_mesh
        from .trainer import _state_to_jax
        from .. import optimizer as opt_mod
        from ..ndarray.ndarray import _wrap
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        if axis is None:
            axis = next((a for a in self.mesh.axis_names
                         if int(self.mesh.shape[a]) > 1
                         and self.num_rows % int(self.mesh.shape[a]) == 0),
                        None)
        elif self.num_rows % int(self.mesh.shape[axis]) != 0:
            raise ValueError(
                "num_rows=%d does not divide mesh axis %r (size %d)"
                % (self.num_rows, axis, int(self.mesh.shape[axis])))
        self.axis = axis
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer)
        self.optimizer = optimizer if optimizer is not None \
            else opt_mod.create("sgd")
        if not getattr(self.optimizer, "lazy_update", False) \
                or not hasattr(self.optimizer, "step_rows"):
            raise ValueError(
                "ShardedEmbedding needs an optimizer with a lazy "
                "step_rows path (sgd, adam); got %r"
                % type(self.optimizer).__name__)
        key = jax.random.PRNGKey(seed)
        table = (jax.random.normal(key, (self.num_rows, self.dim),
                                   jnp.float32) * init_scale).astype(dtype)
        sh = NamedSharding(self.mesh, P(axis) if axis else P())
        self.table = jax.device_put(table, sh)
        st = _state_to_jax(self.optimizer.create_state(0, _wrap(self.table)))
        self.state = None if st is None else jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), st)
        self._t = 0
        self._progs = {}  # (kind, ids_shape, config-epoch) -> program

    # ------------------------------------------------------------ programs
    def _prog(self, kind, ids_shape, instrument=False):
        from .. import config as _config
        from .. import numerics as _numerics
        # the programs bake in config-derived constants (unique_capacity
        # reads embedding.unique_size), so the config epoch is part of
        # the key and superseded entries are evicted — the same
        # invalidation contract as symbol.py's key_sig.  The numerics
        # token is its own element: both variants coexist and toggling
        # capture never evicts (the knob is epoch-neutral).
        epoch = _config.epoch()
        key = (kind, ids_shape, _numerics.capture_token(instrument), epoch)
        prog = self._progs.get(key)
        if prog is not None:
            return prog
        self._progs = {k: v for k, v in self._progs.items()
                       if k[-1] == epoch}
        from .. import telemetry as _telemetry
        _telemetry.counter("embedding.lookup_compiles").inc()
        cap = unique_capacity(max(_math.prod(ids_shape), 1))
        mesh = self.mesh if self.axis else None
        sentinel = self.num_rows
        opt = self.optimizer

        if kind == "lookup":
            def run(table, ids):
                uniq, inv = dedup_ids(ids, cap, sentinel)
                rows = lookup_unique(table, uniq, mesh, self.axis)
                out = jnp.take(rows, inv, axis=0).reshape(
                    tuple(ids.shape) + (self.dim,))
                if instrument:
                    from .. import numerics as _num
                    return (out, jnp.sum(uniq < sentinel),
                            {"embedding.rows": _num.summarize(rows)})
                return out, jnp.sum(uniq < sentinel)
            prog = jax.jit(run)
        else:
            def run(table, state, ids, grad, lr, wd, t):
                uniq, inv = dedup_ids(ids, cap, sentinel)
                gsum = jnp.zeros((cap, self.dim), grad.dtype).at[inv].add(
                    grad.reshape(-1, self.dim))
                return update_unique(opt, table, state, uniq,
                                     gsum.astype(table.dtype), lr, wd, t,
                                     mesh, self.axis)
            prog = jax.jit(run, donate_argnums=(0, 1))
        from .. import perf as _perf
        # no source: embedding programs run inside the caller's step scope
        # (or eagerly) — cost registers, step MFU attribution stays with
        # the owning trainer's fused program
        prog = _perf.wrap(prog, "embedding",
                          "%s/%s%s" % (kind, ids_shape,
                                       "/numerics" if instrument else ""))
        self._progs[key] = prog
        return prog

    # -------------------------------------------------------------- public
    def lookup(self, ids):
        """Gather rows for an integer id batch: ``[*ids.shape, dim]``.

        Ids >= num_rows (the pad sentinel) return zero rows.
        """
        import time as _time
        from .. import telemetry as _telemetry
        from .. import tracing as _tracing
        from .. import numerics as _numerics
        ids = jnp.asarray(ids)
        cap_stats = _numerics.should_capture("embedding")
        with _tracing.span("embedding.lookup", cat="embedding"):
            t0 = _time.perf_counter()
            res = self._prog("lookup", tuple(ids.shape),
                             instrument=cap_stats)(self.table, ids)
            out, n_unique = res[0], res[1]
            out.block_until_ready()
            _telemetry.timer("embedding.lookup_ms").observe(
                (_time.perf_counter() - t0) * 1000.0)
        if cap_stats:
            _numerics.publish("embedding", self._t, res[2])
        n = max(int(ids.size), 1)
        _telemetry.counter("embedding.gathered_rows").inc(
            unique_capacity(n))
        _telemetry.gauge("embedding.unique_ratio").set(
            float(int(n_unique)) / n)
        return out

    def update(self, ids, grad, lr, wd=0.0):
        """Apply one lazy row-sparse optimizer step.

        ``grad`` holds one cotangent row per id (``[*ids.shape, dim]``);
        duplicate ids are summed before the update, sentinel ids are
        dropped, and only touched rows of the table + optimizer state are
        rewritten (the table/state buffers are donated).
        """
        from .. import telemetry as _telemetry
        from .. import tracing as _tracing
        ids = jnp.asarray(ids)
        grad = jnp.asarray(grad)
        self._t += 1
        with _tracing.span("embedding.update", cat="embedding"):
            self.table, self.state = self._prog("update", tuple(ids.shape))(
                self.table, self.state, ids, grad,
                jnp.asarray(lr, jnp.float32), jnp.asarray(wd, jnp.float32),
                jnp.asarray(self._t, jnp.int32))
        _telemetry.counter("embedding.rows_touched").inc(
            unique_capacity(max(int(ids.size), 1)))
        return self.table
