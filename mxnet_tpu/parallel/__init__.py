"""mxnet_tpu.parallel — SPMD scaling layer (mesh, collectives, ring
attention, fused train step).

This package is the TPU-native replacement for the reference's entire
communication stack (SURVEY.md §5.8): KVStore local/device comm
(src/kvstore/comm.h), NCCL backend (src/kvstore/kvstore_nccl.h), and the
ps-lite parameter server (src/kvstore/kvstore_dist.h) all collapse into XLA
collectives over a named Mesh; ``jax.distributed.initialize`` replaces the
ps-lite scheduler rendezvous.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .mesh import (AXES, make_mesh, data_parallel_mesh, sharding,
                   shard_batch, replicated, Mesh, NamedSharding,
                   PartitionSpec)
from .ring_attention import ring_attention, attention, \
    ring_self_attention_sharded
from .functional import functionalize, BlockFunction
from .trainer import SPMDTrainer, build_train_step
from .pipeline import (pipeline_apply, pipeline_sharded, microbatch,
                       unmicrobatch)
from .moe import moe_ffn, moe_ffn_sharded, top_k_routing
from .embedding import (ShardedEmbedding, dedup_ids, lookup_unique,
                        update_unique)

__all__ = ["AXES", "make_mesh", "data_parallel_mesh", "sharding",
           "shard_batch", "replicated", "Mesh", "NamedSharding",
           "PartitionSpec", "ring_attention", "attention",
           "ring_self_attention_sharded", "functionalize", "BlockFunction",
           "SPMDTrainer", "build_train_step", "host_allreduce",
           "initialize", "ensure_initialized", "barrier",
           "pipeline_apply", "pipeline_sharded", "microbatch",
           "unmicrobatch", "moe_ffn", "moe_ffn_sharded", "top_k_routing",
           "ShardedEmbedding", "dedup_ids", "lookup_unique",
           "update_unique"]


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Multi-host rendezvous — the ps-lite scheduler analog
    (DMLC_PS_ROOT_URI env rendezvous, src/kvstore/kvstore_dist.h:44-50).

    Argument resolution order, mirroring how the reference's roles come from
    the dmlc tracker env (DMLC_PS_ROOT_URI / DMLC_NUM_WORKER / DMLC_ROLE,
    tools/launch.py): explicit args > ``MXTPU_COORDINATOR`` /
    ``MXTPU_NUM_PROCESSES`` / ``MXTPU_PROCESS_ID`` env (set by our
    tools/launch.py) > jax cluster auto-detection (SLURM/GKE/etc.).
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get("MXTPU_COORDINATOR")
    if num_processes is None and "MXTPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["MXTPU_NUM_PROCESSES"])
    if process_id is None and "MXTPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["MXTPU_PROCESS_ID"])
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def ensure_initialized():
    """Idempotent rendezvous: initialize jax.distributed iff launcher env is
    present and it has not been initialized yet.  Lets ``mx.kv.create
    ('dist_sync')`` alone bootstrap a worker, the way creating a dist kvstore
    connects to the parameter server in the reference
    (src/kvstore/kvstore_dist.h:44-50)."""
    from jax._src import distributed as _dist
    if getattr(_dist.global_state, "client", None) is not None:
        return
    if ("MXTPU_COORDINATOR" in os.environ
            or "JAX_COORDINATOR_ADDRESS" in os.environ):
        initialize()


def host_allreduce(val):
    """Sum a host-local array across all processes (DCN allreduce) — the
    dist_sync server-merge analog (src/kvstore/kvstore_dist_server.h:349)."""
    if jax.process_count() == 1:
        return val
    from jax.experimental import multihost_utils
    from .. import tracing as _tracing
    with _tracing.span("allreduce", cat="collective"):
        gathered = multihost_utils.process_allgather(jnp.asarray(val))
        return jnp.sum(gathered, axis=0)


def barrier(name="kvstore"):
    """Global barrier (reference: KVStore::Barrier,
    include/mxnet/kvstore.h:300)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    from .. import tracing as _tracing
    with _tracing.span("barrier", cat="collective", name_arg=name):
        multihost_utils.sync_global_devices(name)
