"""mxnet_tpu.parallel — SPMD scaling layer (mesh, collectives, ring
attention, fused train step).

This package is the TPU-native replacement for the reference's entire
communication stack (SURVEY.md §5.8): KVStore local/device comm
(src/kvstore/comm.h), NCCL backend (src/kvstore/kvstore_nccl.h), and the
ps-lite parameter server (src/kvstore/kvstore_dist.h) all collapse into XLA
collectives over a named Mesh; ``jax.distributed.initialize`` replaces the
ps-lite scheduler rendezvous.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .mesh import (AXES, make_mesh, data_parallel_mesh, sharding,
                   shard_batch, replicated, Mesh, NamedSharding,
                   PartitionSpec)
from .ring_attention import ring_attention, attention, \
    ring_self_attention_sharded
from .functional import functionalize, BlockFunction
from .trainer import SPMDTrainer, build_train_step
from .pipeline import (pipeline_apply, pipeline_sharded, microbatch,
                       unmicrobatch)
from .moe import moe_ffn, moe_ffn_sharded, top_k_routing
from .embedding import (ShardedEmbedding, dedup_ids, lookup_unique,
                        update_unique)

__all__ = ["AXES", "make_mesh", "data_parallel_mesh", "sharding",
           "shard_batch", "replicated", "Mesh", "NamedSharding",
           "PartitionSpec", "ring_attention", "attention",
           "ring_self_attention_sharded", "functionalize", "BlockFunction",
           "SPMDTrainer", "build_train_step", "host_allreduce",
           "host_allgather", "initialize", "ensure_initialized", "barrier",
           "pipeline_apply", "pipeline_sharded", "microbatch",
           "unmicrobatch", "moe_ffn", "moe_ffn_sharded", "top_k_routing",
           "ShardedEmbedding", "dedup_ids", "lookup_unique",
           "update_unique"]


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Multi-host rendezvous — the ps-lite scheduler analog
    (DMLC_PS_ROOT_URI env rendezvous, src/kvstore/kvstore_dist.h:44-50).

    Argument resolution order, mirroring how the reference's roles come from
    the dmlc tracker env (DMLC_PS_ROOT_URI / DMLC_NUM_WORKER / DMLC_ROLE,
    tools/launch.py): explicit args > ``MXTPU_COORDINATOR`` /
    ``MXTPU_NUM_PROCESSES`` / ``MXTPU_PROCESS_ID`` env (set by our
    tools/launch.py) > jax cluster auto-detection (SLURM/GKE/etc.).
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get("MXTPU_COORDINATOR")
    if num_processes is None and "MXTPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["MXTPU_NUM_PROCESSES"])
    if process_id is None and "MXTPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["MXTPU_PROCESS_ID"])
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def ensure_initialized():
    """Idempotent rendezvous: initialize jax.distributed iff launcher env is
    present and it has not been initialized yet.  Lets ``mx.kv.create
    ('dist_sync')`` alone bootstrap a worker, the way creating a dist kvstore
    connects to the parameter server in the reference
    (src/kvstore/kvstore_dist.h:44-50)."""
    from jax._src import distributed as _dist
    if getattr(_dist.global_state, "client", None) is not None:
        return
    if ("MXTPU_COORDINATOR" in os.environ
            or "JAX_COORDINATOR_ADDRESS" in os.environ):
        initialize()


# ---- coordination-service transport -----------------------------------
# XLA cross-process collectives need a real interconnect backend; the CPU
# backend has none ("Multiprocess computations aren't implemented"), so on
# CPU the host collectives ride the jax.distributed coordination service
# instead — the same gRPC KV store that did the rendezvous.  Slower, but
# value-exact and deterministic (rows are summed in rank order), which is
# what the dist tests and the elastic chaos harness need.

_COORD_TIMEOUT_MS = 120_000
_COORD_SEQ = {"allreduce": 0, "barrier": 0}  # advances in SPMD order


def _coord_client():
    from jax._src import distributed as _dist
    return getattr(_dist.global_state, "client", None)


def _use_coord_transport():
    return jax.default_backend() == "cpu" and _coord_client() is not None


def _kv_allgather(arr):
    """Allgather host rows through the coordination-service KV store.

    Every collective is one sequence number; all ranks execute collectives
    in the same program order, so the counter agrees without negotiation.
    A rank that reached seq N has read every row of seq N-1, so each rank
    deletes its own seq N-2 key on entry — the store holds O(world) live
    keys, not O(steps)."""
    import numpy as np
    client = _coord_client()
    rank, world = jax.process_index(), jax.process_count()
    _COORD_SEQ["allreduce"] += 1
    seq = _COORD_SEQ["allreduce"]
    if seq > 2:
        try:
            client.key_value_delete("mxtpu/ar/%d/%d" % (seq - 2, rank))
        except Exception:  # already gone / server restarted — harmless
            pass
    client.key_value_set_bytes("mxtpu/ar/%d/%d" % (seq, rank),
                               arr.tobytes())
    rows = []
    for peer in range(world):
        buf = client.blocking_key_value_get_bytes(
            "mxtpu/ar/%d/%d" % (seq, peer), _COORD_TIMEOUT_MS)
        rows.append(np.frombuffer(buf, dtype=arr.dtype).reshape(arr.shape))
    return np.stack(rows)


def host_allgather(val):
    """Stack a host-local array across all processes (world, *shape) — the
    DCN gather primitive under host_allreduce and the kvstore's 2-bit
    compressed wire."""
    import numpy as np
    if jax.process_count() == 1:
        return jnp.asarray(val)[None]
    from .. import tracing as _tracing
    with _tracing.span("allgather", cat="collective"):
        if _use_coord_transport():
            # NB: no ascontiguousarray — it promotes 0-d scalars to 1-d
            # and would change the gathered shape; tobytes() copies
            # non-contiguous inputs itself
            return jnp.asarray(_kv_allgather(np.asarray(val)))
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(jnp.asarray(val))


def host_allreduce(val):
    """Sum a host-local array across all processes (DCN allreduce) — the
    dist_sync server-merge analog (src/kvstore/kvstore_dist_server.h:349)."""
    if jax.process_count() == 1:
        return val
    from .. import tracing as _tracing
    with _tracing.span("allreduce", cat="collective"):
        return jnp.sum(host_allgather(val), axis=0)


def barrier(name="kvstore"):
    """Global barrier (reference: KVStore::Barrier,
    include/mxnet/kvstore.h:300)."""
    if jax.process_count() == 1:
        return
    from .. import tracing as _tracing
    with _tracing.span("barrier", cat="collective", name_arg=name):
        if _use_coord_transport():
            _COORD_SEQ["barrier"] += 1
            _coord_client().wait_at_barrier(
                "mxtpu/bar/%d/%s" % (_COORD_SEQ["barrier"], name),
                _COORD_TIMEOUT_MS)
            return
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
