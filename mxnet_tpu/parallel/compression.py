"""2-bit gradient compression with error-feedback residual.

Reference: src/kvstore/gradient_compression.cc:60 SetTwoBitCompression —
each gradient element is quantized to {-threshold, 0, +threshold} (2 bits),
the quantization error accumulates into a per-key residual added back next
step, and the wire carries 16 elements per 32-bit word.

TPU-native: the codes pack 4 elements per uint8 with jnp bit ops, so a DCN
(host-network) push moves 1/16 of the f32 bytes; ICI allreduce stays
uncompressed (compiler-scheduled psum at full bandwidth is faster than any
recompression, which is why the kvstore facade documents compression as a
DCN-path feature).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["two_bit_compress", "two_bit_decompress", "pack_2bit",
           "unpack_2bit"]


def two_bit_compress(grad, residual, threshold):
    """(grad, residual) -> (codes int8 in {-1, 0, +1}, new_residual).

    codes * threshold is the decompressed gradient; the difference feeds
    back into the residual (error feedback keeps the update unbiased over
    time, reference gradient_compression-inl.h quantize_2bit kernel).
    """
    g = jnp.asarray(grad) + jnp.asarray(residual)
    codes = jnp.where(g >= threshold, 1,
                      jnp.where(g <= -threshold, -1, 0)).astype(jnp.int8)
    new_residual = g - codes.astype(g.dtype) * threshold
    return codes, new_residual


def two_bit_decompress(codes, threshold, dtype=jnp.float32):
    return codes.astype(dtype) * threshold


def pack_2bit(codes):
    """int8 {-1,0,1} [N] -> uint8 [ceil(N/4)] wire format (4 elems/byte)."""
    flat = codes.ravel()
    n = flat.shape[0]
    padded = jnp.zeros(((n + 3) // 4) * 4, jnp.uint8)
    # map {-1,0,1} -> {2,0,1} (2 bits each)
    u = jnp.where(flat < 0, 2, flat).astype(jnp.uint8)
    padded = padded.at[:n].set(u)
    q = padded.reshape(-1, 4)
    return (q[:, 0] | (q[:, 1] << 2) | (q[:, 2] << 4) |
            (q[:, 3] << 6)).astype(jnp.uint8)


def unpack_2bit(packed, n):
    """uint8 wire bytes -> int8 codes [n]."""
    p = jnp.asarray(packed, jnp.uint8)
    parts = jnp.stack([p & 3, (p >> 2) & 3, (p >> 4) & 3, (p >> 6) & 3],
                      axis=1).reshape(-1)[:n]
    return jnp.where(parts == 2, -1, parts).astype(jnp.int8)
