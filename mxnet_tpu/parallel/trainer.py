"""SPMD training step — the TPU-native replacement for the reference's
Module.fit hot loop + KVStore gradient sync.

Reference path (SURVEY.md §3.3-3.4): per batch, DataParallelExecutorGroup
slices data over contexts (python/mxnet/module/executor_group.py:144), the
GraphExecutor pushes bulked engine ops (src/executor/graph_executor.cc:1384),
then KVStore reduces gradients across devices (src/kvstore/comm.h:451) and an
Updater applies the optimizer.  Four subsystems, all asynchrony hand-managed.

Here the ENTIRE iteration — forward, backward, gradient allreduce, optimizer
update — is ONE jitted function over a named mesh.  Batch dims are sharded on
'dp', parameters replicated (or sharded for tensor-parallel models), and XLA
inserts the psum/all-gather collectives and overlaps them with compute; the
engine/kvstore/bulking machinery has no residual role on the hot path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .functional import functionalize
from .mesh import data_parallel_mesh

__all__ = ["SPMDTrainer", "build_train_step"]


def _opt_hyper_arrays(optimizer, num_params, cache=None, indices=None):
    """Evaluate per-parameter lr/wd EAGERLY for the current num_update.

    These are fed into the jitted step as traced arguments so an
    ``lr_scheduler`` (reference: python/mxnet/lr_scheduler.py) keeps working —
    evaluating them at trace time would constant-fold the schedule into the
    compiled program and silently freeze it at the first step's value.

    ``cache`` (a 1-slot dict) skips the two host->device uploads when the
    schedule produced the same values as last step — on a tunneled device
    every upload is a round trip, and constant-lr training would otherwise
    pay two per step for identical bytes.

    ``indices`` overrides the parameter indices the per-param multipliers
    are looked up under (Module's fused step trains a subset of
    ``_param_names``, whose updater indices are not contiguous).
    """
    idxs = tuple(indices) if indices is not None \
        else tuple(range(num_params))
    lr_host = tuple(optimizer._get_lr(i) for i in idxs)
    wd_host = tuple(optimizer._get_wd(i) for i in idxs)
    if cache is not None and cache.get("host") == (lr_host, wd_host):
        return cache["dev"]
    from .. import profiler as _profiler
    _profiler.counter_increment("host_syncs", 2)  # lr + wd uploads
    dev = (jnp.asarray(lr_host, jnp.float32),
           jnp.asarray(wd_host, jnp.float32))
    if cache is not None:
        cache["host"] = (lr_host, wd_host)
        cache["dev"] = dev
    return dev


def _conv_weight_names(block):
    """Names of 2-D convolution weight parameters in a Block tree — the
    exact set the HWIO weight layout applies to."""
    from ..gluon import nn as _gnn
    names, seen = set(), set()

    def walk(b):
        if id(b) in seen:
            return
        seen.add(id(b))
        if isinstance(b, _gnn.Conv2D):
            names.add(b.weight.name)
        for c in getattr(b, "_children", {}).values():
            walk(c)

    walk(block)
    return names


class SPMDTrainer:
    """Fused-step trainer for a Gluon block on a device mesh.

    Usage::

        trainer = SPMDTrainer(net, loss_fn, 'sgd',
                              {'learning_rate': 0.1, 'momentum': 0.9},
                              mesh=mesh)
        for data, label in loader:
            loss = trainer.step(data, label)
        trainer.sync()           # write weights back into the Block

    loss_fn(pred, label) must return a per-example or scalar loss NDArray-free
    (it is called on raw jax arrays via the functionalized block — gluon.loss
    objects work because they are HybridBlocks; plain callables on jnp arrays
    work too).
    """

    def __init__(self, block, loss_fn, optimizer, optimizer_params=None,
                 mesh=None, batch_axis="dp", param_specs=None,
                 donate=True, dtype=None):
        from .. import optimizer as opt_mod
        self.fn = functionalize(block)
        self.block = block
        self.loss_fn = loss_fn
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self.optimizer = optimizer
        # Mixed-precision compute policy (reference analog: mx.amp bf16 —
        # python/mxnet/contrib/amp/).  dtype='bfloat16' keeps f32 MASTER
        # weights and optimizer state, but runs forward+backward in bf16 so
        # matmuls/convs hit the MXU at its native rate.  The cast is part of
        # the jitted step, so grads flow through it back to f32 masters
        # (the standard multi-precision recipe; no loss scaling needed —
        # bf16 shares f32's exponent range).
        self.compute_dtype = (jnp.bfloat16 if str(dtype) in
                              ("bfloat16", "bf16") else None) \
            if dtype is not None else None
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.batch_axis = batch_axis if batch_axis in self.mesh.axis_names \
            else self.mesh.axis_names[0]
        self._param_specs = param_specs or {}

        self.params = None
        self.opt_state = None
        self._step_num = 0
        self._jitted = {}   # masked(bool) -> jitted program (one guard mode)
        self._donate = donate
        # resilience (docs/RESILIENCE.md): optional CheckpointManager for
        # periodic save / preemption save / auto-resume, plus the nanguard
        # bad-step streak carried as a device scalar so the fused step
        # never syncs the host on finite steps
        self._ckpt_manager = None
        self._guard_mode = ""
        self._nan_streak = None
        # channels-last weights end-to-end (conv.weights_layout=HWIO,
        # docs/PERF_NOTES.md): conv weights + grads + optimizer state live
        # HWIO inside the trainer; boundaries (sync, single-file
        # checkpoints) convert to/from the reference OIHW layout
        from .. import config as _cfg
        self._hwio = _cfg.get("conv.weights_layout") == "HWIO"
        self._hwio_names = _conv_weight_names(block) if self._hwio else set()
        # sparse-grad embedding tables (gluon.nn.Embedding(sparse_grad=True))
        # route through the mesh-sharded deduplicated row-sparse path
        # (parallel/embedding.py) when embedding.sharded is on: the table is
        # sharded on the vocab axis, lookups dedup ids per batch, and the
        # update touches only the gathered rows via Optimizer.step_rows —
        # all inside the same donated program as the dense step
        from . import embedding as _pemb
        self._sparse_embed = _pemb.sparse_embedding_params(
            self.fn, self.mesh, self.batch_axis)
        # compressed DCN sync (kvstore.grad_compress=2bit): per-param
        # error-feedback residuals, sharded P('dcn') and donated through
        # the step like optimizer state; None until the first compressed
        # step materializes them (or a checkpoint restores them)
        self._dcn_residuals = None

    # ------------------------------------------------- compressed DCN sync
    def _dcn_compress_active(self, pad=0):
        """True when this trainer's fused step should quantize the DCN
        gradient hop: the 2-bit knob is on AND the mesh declares a 'dcn'
        axis.  Pad-masked steps run uncompressed (the tail mask reduces
        over the global batch; under shard_map it would be shard-local),
        as do sparse-embedding models (row-sparse updates never cross
        DCN whole)."""
        from .. import config as _cfg
        if _cfg.get("kvstore.grad_compress") != "2bit":
            return False
        if "dcn" not in self.mesh.axis_names:
            return False
        if pad:
            return False
        if any(n in self._sparse_embed for n in self.fn.trainable):
            return False
        return True

    def _dcn_check(self):
        """Refuse configurations where the compressed path would silently
        compute the wrong thing instead of a smaller wire."""
        extra = [a for a in self.mesh.axis_names
                 if a not in ("dcn", self.batch_axis)]
        if extra:
            raise NotImplementedError(
                "kvstore.grad_compress=2bit supports data-parallel meshes "
                "('dcn' + the batch axis); this mesh also has axes %s"
                % (extra,))
        bad = [n for n in list(self.fn.trainable) + list(self.fn.aux)
               if len(self._spec_for(n)) > 0]
        if bad:
            raise NotImplementedError(
                "compressed DCN sync needs replicated parameters (each "
                "gradient is quantized whole); sharded specs on %s"
                % bad[:4])

    def _materialize(self, data):
        """Snapshot the Block's parameters into device-placed jax arrays.

        Deferred-shape parameters (Gluon semantics: shape inference happens on
        the first forward, python/mxnet/gluon/block.py:979-1036) are resolved
        by one eager forward on the first batch.  Values are COPIED: the
        jitted step donates its inputs, and donating buffers still referenced
        by the live Parameters would delete them under the Block.
        """
        from ..gluon.parameter import DeferredInitializationError
        from ..ndarray.ndarray import _wrap
        try:
            vals = self.fn.init_values()
        except DeferredInitializationError:
            self.block(_wrap(jnp.asarray(data)))
            self.fn = functionalize(self.block)
            vals = self.fn.init_values()
            from . import embedding as _pemb
            self._sparse_embed = _pemb.sparse_embedding_params(
                self.fn, self.mesh, self.batch_axis)
        if self._hwio:
            # the HWIO flag flips the interpretation of EVERY traced conv
            # weight, but only nn.Conv2D weights were converted: a custom
            # block with its own 4-D conv weight would silently compute
            # wrong math (square kernel, C_in == C_out) — refuse loudly
            unknown = [n for n in self.fn.trainable
                       if getattr(vals.get(n), "ndim", 0) == 4
                       and n not in self._hwio_names]
            if unknown:
                raise NotImplementedError(
                    "conv.weights_layout=HWIO supports models whose conv "
                    "weights belong to gluon nn.Conv2D blocks; found 4-D "
                    "trainable params it cannot classify: %s — use the "
                    "default 'ref' layout for this model" % unknown)
        self.params = {n: jnp.array(v) for n, v in vals.items()}
        self.params = self._layout_internal(self.params)
        self.opt_state = {}
        for i, name in enumerate(self.fn.trainable):
            st = self.optimizer.create_state(i, _wrap(self.params[name]))
            self.opt_state[name] = _state_to_jax(st)
        self._place()

    # -------------------------------------------------------- weight layout
    def _layout_internal(self, params):
        """OIHW -> HWIO for the conv weights this trainer owns (no-op when
        the knob is off or a name is not a 4-D conv weight)."""
        if not self._hwio_names:
            return params
        out = dict(params)
        for n in self._hwio_names:
            if n in out and getattr(out[n], "ndim", 0) == 4:
                out[n] = jnp.transpose(out[n], (2, 3, 1, 0))
        return out

    def _layout_ref(self, params):
        """HWIO -> OIHW (the reference/checkpoint layout) at boundaries."""
        if not self._hwio_names:
            return params
        out = dict(params)
        for n in self._hwio_names:
            if n in out and getattr(out[n], "ndim", 0) == 4:
                out[n] = jnp.transpose(out[n], (3, 2, 0, 1))
        return out

    def _layout_state(self, state, to_internal):
        """Apply the weight-layout transpose to optimizer-state leaves
        (momentum etc. shard and transpose with their weights)."""
        if not self._hwio_names:
            return state
        perm = (2, 3, 1, 0) if to_internal else (3, 2, 0, 1)
        out = dict(state)
        for n in self._hwio_names:
            if n in out and out[n] is not None:
                out[n] = jax.tree_util.tree_map(
                    lambda x: jnp.transpose(x, perm)
                    if getattr(x, "ndim", 0) == 4 else x, out[n])
        return out

    # ------------------------------------------------------------ placement
    def _spec_for(self, name):
        se = self._sparse_embed.get(name)
        if se is not None and se["axis"] is not None \
                and name not in self._param_specs:
            # embedding table sharded on the VOCAB axis: each device holds
            # rows [k*rows_per_shard, (k+1)*rows_per_shard) and its slice
            # of the optimizer state — no replica of the full table exists
            return P(se["axis"])
        spec = self._param_specs.get(name, P())  # default: replicated
        if name in self._hwio_names and len(spec) > 0:
            # user specs are written against the OIHW axis order; permute
            # them with the weight so the same logical axis stays sharded
            axes = tuple(spec) + (None,) * (4 - len(spec))
            spec = P(*(axes[i] for i in (2, 3, 1, 0)))
        return spec

    def _place(self):
        mesh = self.mesh
        for n in list(self.params.keys()):
            sh = NamedSharding(mesh, self._spec_for(n))
            self.params[n] = jax.device_put(self.params[n], sh)
            if n in self.opt_state and self.opt_state[n] is not None:
                self.opt_state[n] = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, sh), self.opt_state[n])

    @property
    def batch_sharding(self):
        """The ``NamedSharding`` the fused step expects batches under (rows
        split along the batch axis).  Available BEFORE the first compile —
        hand it (or ``lambda: trainer.batch_sharding``) to
        ``io.DevicePrefetcher`` so batches arrive pre-placed and ``step``
        never issues a synchronous ``device_put``."""
        sh = getattr(self, "_batch_sharding", None)
        if sh is None:
            if "dcn" in self.mesh.axis_names and self.batch_axis != "dcn":
                # the global batch also splits over the slow axis: each
                # dcn slice computes grads for its own rows and the dcn
                # hop (full psum, or 2-bit codes under grad_compress)
                # merges them — without this, every slice would redo the
                # whole batch
                spec = P((self.batch_axis, "dcn"))
            else:
                spec = P(self.batch_axis)
            sh = NamedSharding(self.mesh, spec)
            self._batch_sharding = sh
        return sh

    # ------------------------------------------------------------ step build
    def _build(self, pad=0, instrument=False):
        sparse_meta = {n: m for n, m in self._sparse_embed.items()
                       if n in self.fn.trainable}
        if sparse_meta:
            return self._build_sparse(pad, sparse_meta, instrument)
        from .. import numerics as _numerics
        masked = pad > 0
        fn = self.fn
        loss_fn = self.loss_fn
        optimizer = self.optimizer
        trainable = fn.trainable
        mesh = self.mesh
        batch_sh = self.batch_sharding
        param_sh = {n: NamedSharding(mesh, self._spec_for(n))
                    for n in fn.params}

        cdt = self.compute_dtype
        hwio = bool(self._hwio_names)

        def loss_of(train_params, aux_params, data, label, key):
            from ..ops import nn as _nn_ops
            param_map = dict(aux_params)  # aux (BN stats) stay f32
            if cdt is not None:
                param_map.update(
                    {n: v.astype(cdt) if v.dtype == jnp.float32 else v
                     for n, v in train_params.items()})
                if data.dtype == jnp.float32:  # int inputs (token ids) keep
                    data = data.astype(cdt)    # their dtype
            else:
                param_map.update(train_params)
            prev = _nn_ops.set_hwio_weights(hwio)
            try:
                if instrument:
                    # numerics variant: model-level tap sites (the scan-
                    # carried transformer/BERT layer stats among them)
                    # fill the collector at trace time and ride out
                    # through the loss aux
                    with _numerics.collect() as sink:
                        (out,), new_aux = fn.apply(param_map, (data,), key,
                                                   training=True)
                    fstats = dict(sink)
                else:
                    (out,), new_aux = fn.apply(param_map, (data,), key,
                                               training=True)
            finally:
                _nn_ops.set_hwio_weights(prev)
            if cdt is not None:
                out = out.astype(jnp.float32)
            if masked:
                loss = _as_masked_scalar_loss(loss_fn, out, label, pad)
            else:
                loss = _as_scalar_loss(loss_fn, out, label)
            if instrument:
                _numerics.record(fstats, "out", out)
                _numerics.record(fstats, "loss", loss)
                return loss, (new_aux, out, fstats)
            return loss, (new_aux, out)

        guard = self._guard_mode
        from .. import kernels as _kernels
        fused_opt = _kernels.fused_step_enabled(optimizer)
        if fused_opt:
            _kernels.note_fused_step()

        # compressed DCN gradient sync (docs/RESILIENCE.md "Multi-host
        # elasticity"): grads crossing the 'dcn' mesh axis ride as packed
        # 2-bit codes with per-param error-feedback residuals carried as
        # donated step state; ICI axes keep the full-precision psum.  The
        # numerics-instrumented variant always runs uncompressed so
        # forensics sees the raw math.
        compress = (not instrument) and self._dcn_compress_active(pad)
        grad_fn = None
        if compress:
            self._dcn_check()
            import math as _math
            from .. import config as _cfg2
            from .pipeline import shmap
            from . import compression as _comp
            thr = float(_cfg2.get("kvstore.grad_compression_threshold"))
            n_dcn = int(mesh.shape["dcn"])
            n_shards = int(_math.prod(mesh.devices.shape))
            ici_axes = tuple(a for a in mesh.axis_names if a != "dcn")
            all_axes = tuple(mesh.axis_names)

            def sync_grads(train_params, aux_params, residuals, data,
                           label, key):
                # per-shard: grads of the LOCAL rows' mean loss
                (loss, aux), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_params, aux_params, data,
                                           label, key)
                new_aux = aux[0]
                out_g, new_res = {}, {}
                for n in trainable:
                    g = grads[n]
                    if ici_axes:
                        # ICI stays full precision: compiler-scheduled
                        # psum at torus bandwidth beats recompression
                        g = jax.lax.psum(g, ici_axes)
                    # this dcn slice's share of the GLOBAL mean gradient
                    # (the dcn-psum of v is the uncompressed global grad)
                    v = g / n_shards
                    codes, r = _comp.two_bit_compress(v, residuals[n][0],
                                                      thr)
                    packed = _comp.pack_2bit(codes)
                    # the DCN hop moves 4 codes/byte — 1/16 of the f32
                    # bytes; each shard unpacks the peers' rows and sums
                    rows = jax.lax.all_gather(packed, "dcn")
                    tot = jnp.zeros((int(v.size),), jnp.int32)
                    for w in range(n_dcn):
                        tot = tot + _comp.unpack_2bit(rows[w], int(v.size))
                    out_g[n] = (tot.astype(v.dtype) * thr).reshape(v.shape)
                    new_res[n] = r[None]
                loss = jax.lax.pmean(loss, all_axes)
                new_aux = jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, all_axes)
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)
                    else a, new_aux)
                return loss, new_aux, out_g, new_res

            bspec = batch_sh.spec
            grad_fn = shmap(
                sync_grads, mesh,
                in_specs=(P(), P(), P("dcn"), bspec, bspec, P()),
                out_specs=(P(), P(), P(), P("dcn")))

        def _step_body(train_params, aux_params, opt_state, residuals,
                       data, label, key, t, lrs, wds, lr_scale, streak):
            if compress:
                loss, new_aux, grads, new_res = grad_fn(
                    train_params, aux_params, residuals, data, label, key)
                stats = None
            else:
                (loss, aux), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_params, aux_params, data,
                                           label, key)
                if instrument:
                    new_aux, _, stats = aux
                else:
                    (new_aux, _), stats = aux, None
                new_res = None
            new_params = {}
            new_state = {}
            from .. import random as _random
            # Stochastic optimizers (SGLD noise) must draw from the step's
            # traced key, not bake a trace-time constant into the compiled
            # program — keep a trace key scope open for the update loop.
            with _random.trace_key_scope(jax.random.fold_in(key, 1)):
                for i, n in enumerate(trainable):
                    g = _preprocess(optimizer, grads[n])
                    if stats is not None:
                        _numerics.record(stats, "grad." + n, g)
                    if fused_opt and \
                            train_params[n].dtype == jnp.float32:
                        # fused Pallas epilogue: update + cast in one
                        # kernel (bitwise-equal to the step/astype pair)
                        w, _m, s = optimizer.step_fused(
                            train_params[n], g, opt_state[n],
                            lrs[i] * lr_scale, wds[i], t,
                            out_dtype=train_params[n].dtype)
                        new_params[n] = w
                        new_state[n] = s
                        continue
                    w, s = optimizer.step(train_params[n], g,
                                          opt_state[n], lrs[i] * lr_scale,
                                          wds[i], t)
                    new_params[n] = w.astype(train_params[n].dtype)
                    new_state[n] = s
            if stats is not None:
                # pre-guard candidate updates — on a bad step they SHOW
                # the non-finite values forensics is after
                for n in trainable:
                    _numerics.record(stats, "update." + n, new_params[n])
            aux_out = dict(aux_params)
            aux_out.update(new_aux)
            if not guard:
                outs = (new_params, aux_out, new_state) \
                    + ((new_res,) if compress else ()) + (loss,)
                if stats is not None:
                    outs += (stats,)
                return outs
            # nanguard (docs/RESILIENCE.md): all on-device — a bad step
            # keeps the pre-step params/state/aux (the update is computed
            # then deselected; XLA still fuses it into one program) and the
            # host hears about it only through the cond-gated callback, so
            # finite steps pay zero host sync
            from .. import resilience as _resilience
            finite = _resilience.all_finite(loss, grads)
            new_streak = _resilience.guarded_streak(finite, streak, "spmd")
            new_params = _resilience.select_tree(finite, new_params,
                                                 train_params)
            new_state = _resilience.select_tree(finite, new_state, opt_state)
            aux_out = _resilience.select_tree(finite, aux_out, aux_params)
            if compress:
                # a rolled-back step must also roll back its quantization
                # error, or the next step double-counts the bad residual
                new_res = _resilience.select_tree(finite, new_res, residuals)
            outs = (new_params, aux_out, new_state) \
                + ((new_res,) if compress else ()) + (loss, new_streak)
            if stats is not None:
                outs += (stats,)
            return outs

        if compress:
            def step(train_params, aux_params, opt_state, residuals, data,
                     label, key, t, lrs, wds, lr_scale, streak=None):
                return _step_body(train_params, aux_params, opt_state,
                                  residuals, data, label, key, t, lrs, wds,
                                  lr_scale, streak)
            donate = (0, 2, 3) if self._donate else ()
        else:
            def step(train_params, aux_params, opt_state, data, label, key,
                     t, lrs, wds, lr_scale, streak=None):
                return _step_body(train_params, aux_params, opt_state, None,
                                  data, label, key, t, lrs, wds, lr_scale,
                                  streak)
            donate = (0, 2) if self._donate else ()

        # Sharding is carried by the arguments themselves (params were
        # device_put with their NamedShardings in _place(); the batch is
        # sharded in step()): XLA propagates and inserts the gradient
        # allreduce — the entire KVStore push/pull of the reference
        # (src/kvstore/comm.h:451) becomes one compiler-scheduled psum.
        self._batch_sharding = batch_sh
        del param_sh
        return jax.jit(step, donate_argnums=donate)

    def _build_sparse(self, pad, sparse_meta, instrument=False):
        """Fused step for models with sparse-grad embedding tables.

        Same program shape as `_build` (one donated jit: forward, backward,
        update, optional nanguard fold) with the row-sparse embedding path
        spliced in (parallel/embedding.py):

        - tables enter the loss as NON-differentiated arguments; a zero
          ``delta`` leaf of shape ``[capacity, dim]`` is added to the
          gathered unique rows, so ``jax.grad`` w.r.t. the deltas yields the
          DEDUPLICATED per-row gradients and never a dense table cotangent;
        - the op-level routing context performs the ``jnp.unique(size=)``
          dedup + shard_map gather (ids recorded through the loss aux);
        - the update applies ``Optimizer.step_rows`` per shard, touching
          only the gathered rows of the table and its optimizer state.

        Capacity is ``data.size`` (a batch cannot reference more distinct
        ids than it has elements; ``embedding.unique_size`` caps it), so
        compiled shapes — and ``fused_compiles`` — stay flat across ragged
        index batches padded to a common bucket.
        """
        from .. import numerics as _numerics
        masked = pad > 0
        fn = self.fn
        loss_fn = self.loss_fn
        optimizer = self.optimizer
        trainable = fn.trainable
        mesh = self.mesh
        batch_sh = self.batch_sharding
        cdt = self.compute_dtype
        hwio = bool(self._hwio_names)
        from . import embedding as _pemb
        sparse_names = [n for n in trainable if n in sparse_meta]
        if not getattr(optimizer, "lazy_update", False) \
                or not hasattr(optimizer, "step_rows"):
            raise ValueError(
                "sparse-grad embedding params %s need an optimizer with a "
                "lazy step_rows path (sgd, adam); %r has none — set config "
                "embedding.sharded=False to train them densely"
                % (sparse_names, type(optimizer).__name__))

        def loss_of(train_params, emb_deltas, aux_params, emb_tables, data,
                    label, key):
            from ..ops import nn as _nn_ops
            from ..ops import tensor as _tensor_ops
            param_map = dict(aux_params)  # aux (BN stats) stay f32
            if cdt is not None:
                param_map.update(
                    {n: v.astype(cdt) if v.dtype == jnp.float32 else v
                     for n, v in train_params.items()})
                param_map.update(
                    {n: v.astype(cdt) if v.dtype == jnp.float32 else v
                     for n, v in emb_tables.items()})
                if data.dtype == jnp.float32:  # int inputs (token ids) keep
                    data = data.astype(cdt)    # their dtype
            else:
                param_map.update(train_params)
                param_map.update(emb_tables)
            ctx = _pemb.SparseLookupContext(mesh, sparse_meta, emb_deltas)
            prev = _nn_ops.set_hwio_weights(hwio)
            prev_ctx = _tensor_ops.set_embed_context(ctx)
            try:
                if instrument:
                    # the touched-rows tap in SparseLookupContext.lookup
                    # fires inside this collector too
                    with _numerics.collect() as sink:
                        (out,), new_aux = fn.apply(param_map, (data,), key,
                                                   training=True)
                    fstats = dict(sink)
                else:
                    (out,), new_aux = fn.apply(param_map, (data,), key,
                                               training=True)
            finally:
                _tensor_ops.set_embed_context(prev_ctx)
                _nn_ops.set_hwio_weights(prev)
            if cdt is not None:
                out = out.astype(jnp.float32)
            if masked:
                loss = _as_masked_scalar_loss(loss_fn, out, label, pad)
            else:
                loss = _as_scalar_loss(loss_fn, out, label)
            if instrument:
                _numerics.record(fstats, "out", out)
                _numerics.record(fstats, "loss", loss)
                return loss, (new_aux, out, ctx.records, fstats)
            return loss, (new_aux, out, ctx.records)

        guard = self._guard_mode
        from .. import kernels as _kernels
        fused_opt = _kernels.fused_step_enabled(optimizer)
        if fused_opt:
            _kernels.note_fused_step()

        def step(train_params, aux_params, opt_state, emb_tables, data,
                 label, key, t, lrs, wds, lr_scale, streak=None):
            cap = _pemb.unique_capacity(int(data.size))
            ddt = cdt if cdt is not None else None
            deltas = {
                n: jnp.zeros((cap, sparse_meta[n]["dim"]),
                             ddt or emb_tables[n].dtype)
                for n in sparse_names}
            (loss, aux), (grads, dgrads) = jax.value_and_grad(
                loss_of, argnums=(0, 1), has_aux=True)(
                    train_params, deltas, aux_params, emb_tables, data,
                    label, key)
            if instrument:
                new_aux, _, recs, stats = aux
            else:
                (new_aux, _, recs), stats = aux, None
            new_params = {}
            new_state = {}
            from .. import random as _random
            with _random.trace_key_scope(jax.random.fold_in(key, 1)):
                for i, n in enumerate(trainable):
                    if n in sparse_meta:
                        uniq = recs.get(n)
                        if uniq is None:
                            # table never looked up this forward: no rows
                            # to touch (the lazy-update contract)
                            new_params[n] = emb_tables[n]
                            new_state[n] = opt_state[n]
                            continue
                        gv = _preprocess(
                            optimizer,
                            dgrads[n].astype(emb_tables[n].dtype))
                        if stats is not None:
                            _numerics.record(stats, "grad." + n, gv)
                        w, s = _pemb.update_unique(
                            optimizer, emb_tables[n], opt_state[n], uniq,
                            gv, lrs[i] * lr_scale, wds[i], t,
                            mesh if sparse_meta[n]["axis"] else None,
                            sparse_meta[n]["axis"])
                        new_params[n] = w.astype(emb_tables[n].dtype)
                        new_state[n] = s
                        continue
                    g = _preprocess(optimizer, grads[n])
                    if stats is not None:
                        _numerics.record(stats, "grad." + n, g)
                    if fused_opt and \
                            train_params[n].dtype == jnp.float32:
                        w, _m, s = optimizer.step_fused(
                            train_params[n], g, opt_state[n],
                            lrs[i] * lr_scale, wds[i], t,
                            out_dtype=train_params[n].dtype)
                        new_params[n] = w
                        new_state[n] = s
                        continue
                    w, s = optimizer.step(train_params[n], g,
                                          opt_state[n], lrs[i] * lr_scale,
                                          wds[i], t)
                    new_params[n] = w.astype(train_params[n].dtype)
                    new_state[n] = s
            if stats is not None:
                for n in trainable:
                    _numerics.record(stats, "update." + n, new_params[n])
            aux_out = dict(aux_params)
            aux_out.update(new_aux)
            if not guard:
                if stats is not None:
                    return new_params, aux_out, new_state, loss, stats
                return new_params, aux_out, new_state, loss
            from .. import resilience as _resilience
            finite = _resilience.all_finite(loss, grads, dgrads)
            new_streak = _resilience.guarded_streak(finite, streak, "spmd")
            old_params = dict(train_params)
            old_params.update(emb_tables)
            new_params = _resilience.select_tree(finite, new_params,
                                                 old_params)
            new_state = _resilience.select_tree(finite, new_state, opt_state)
            aux_out = _resilience.select_tree(finite, aux_out, aux_params)
            if stats is not None:
                return (new_params, aux_out, new_state, loss, new_streak,
                        stats)
            return new_params, aux_out, new_state, loss, new_streak

        self._batch_sharding = batch_sh
        donate = (0, 2, 3) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    def _program(self, pad, instrument=False):
        """Fetch-or-build the fused step program for ``(pad, variant)``.
        The program cache is keyed by pad count — the pad-masked loss
        uses a STATIC slice so its reduction is structurally identical
        to the unpadded program's (bitwise-equal losses) — each distinct
        tail size costs one compile, bounded by the bucket policy.  The
        numerics-instrumented variant is a separate entry: both coexist,
        so cadenced capture never evicts the plain program."""
        from .. import numerics as _numerics
        from .. import tracing as _tracing
        ntok = _numerics.capture_token(instrument)
        jitted = self._jitted.get((pad, ntok))
        if jitted is not None:
            return jitted
        from .. import perf as _perf
        # kernels=on earns its own program key; the OFF key is
        # unchanged from earlier rounds so perf artifacts stay
        # comparable across releases.  A program built after an
        # autotune winner landed gets its own key too, so the tuned
        # and untuned registrations coexist in perf exports.
        pkey = "pad=%d/guard=%s" % (pad, self._guard_mode)
        if self._kernel_mode:
            pkey += "/kernels=on"
        if getattr(self, "_autotune_gen", 0):
            pkey += "/at%d" % self._autotune_gen
        if instrument:
            pkey += "/numerics"
        elif self._dcn_compress_active(pad):
            pkey += "/dcn2bit"
        with _tracing.span("spmd.compile", cat="spmd"):
            jitted = self._jitted[(pad, ntok)] = _perf.wrap(
                self._build(pad, instrument=instrument), "spmd", pkey,
                source="spmd")
        from .. import profiler as _profiler
        _profiler.counter_increment("fused_compiles")
        return jitted

    # ------------------------------------------------------------ public
    def step(self, data, label, lr_scale=1.0, pad=0):
        """Run one fused train step; returns the (device-resident) loss.

        ``pad`` is the number of trailing fill rows in the batch
        (``DataBatch.pad`` from bucketed padding, docs/PERF_NOTES.md): when
        non-zero the step runs a pad-MASKED program whose loss/gradients
        average over the first ``rows - pad`` samples only, so wrap-padded
        rows contribute exactly nothing.  Requires ``loss_fn`` to return
        per-sample (batch-unreduced) losses.

        Feeds the ``spmd.step`` telemetry timer every call; with the JSONL
        step log enabled each step also emits one record carrying the
        collective mesh shape, compile/host-sync deltas, and throughput
        (docs/OBSERVABILITY.md).  Wall time is host-side dispatch time —
        async device work overlaps the next step by design."""
        from ..ndarray.ndarray import NDArray
        from .. import resilience as _resilience
        from .. import telemetry as _telemetry
        from .. import tracing as _tracing
        if isinstance(data, NDArray):
            data = data._data
        if isinstance(label, NDArray):
            label = label._data
        pad = int(pad or 0)
        # nanguard escalation check: a dict lookup per step; raises
        # NonFiniteStepError (after flight-recorder dump + checkpoint)
        # once the device reported K consecutive bad steps
        _resilience.maybe_abort_nonfinite("spmd",
                                          save_fn=self._preempt_save)
        if _resilience.faults_active("nan") and _resilience.should_inject(
                "nan", step=self._step_num + 1):
            data = _resilience.poison_batch(data)
        with _telemetry.step_scope(
                "spmd", samples=int(data.shape[0]) - pad if
                getattr(data, "ndim", 0) else None,
                shape=tuple(getattr(data, "shape", ())) or None,
                mesh={n: int(s) for n, s in zip(self.mesh.axis_names,
                                                self.mesh.devices.shape)},
                default_path="fused"), \
                _tracing.span("spmd.step", cat="spmd"):
            loss = self._step_impl(data, label, lr_scale, pad)
        if self._ckpt_manager is not None:
            self._ckpt_manager.maybe_save(self._step_num,
                                          self.save_checkpoint)
        from .. import elastic as _elastic
        if _elastic.active():
            # multi-host lockstep: a SIGTERM on ANY rank (or an injected
            # peer_preempt) makes EVERY rank adopt the request at this
            # same step boundary, so the coordinated checkpoint below
            # snapshots one consistent world
            _elastic.maybe_cluster_preempt(self._step_num)
        if _resilience.preempt_requested():
            # the in-flight step is done (save gathers to host, which
            # syncs); checkpoint, flush sinks, exit 0
            _resilience.exit_on_preempt(save_fn=self._preempt_save)
        return loss

    def _step_impl(self, data, label, lr_scale, pad=0):
        from .. import io as _io
        from .. import resilience as _resilience
        from .. import tracing as _tracing
        if self.params is None:
            self._materialize(data)
        guard = _resilience.nanguard_mode()
        from .. import config as _config
        from .. import kernels as _kernels
        kmode = _kernels.enabled()
        # the traced step bodies bake in config-derived constants beyond
        # the guard/kernels knobs (the sparse path sizes its dedup
        # buffers from embedding.unique_size), so any config mutation —
        # tracked by the epoch counter — invalidates the program cache;
        # likewise a fresh mx.perf.autotune winner (generation counter)
        # must retrace so the tuned pick bakes in
        from .. import autotune as _autotune
        epoch = _config.epoch()
        agen = _autotune.generation()
        if self._jitted and (guard != self._guard_mode or
                             kmode != getattr(self, "_kernel_mode", kmode)
                             or epoch != getattr(self, "_config_epoch",
                                                 epoch)
                             or agen != getattr(self, "_autotune_gen",
                                                agen)):
            self._jitted.clear()  # knob flip: rebuild with/without the guard
        self._guard_mode = guard
        self._kernel_mode = kmode
        self._config_epoch = epoch
        self._autotune_gen = agen
        # numerics cadence (mx.numerics): on a capture step the program
        # cache serves the instrumented VARIANT — its own (pad, token)
        # entry, so off-cadence steps replay the plain program unchanged
        # and a capture-knob toggle never clears this cache (the knob is
        # epoch-neutral in config.py)
        from .. import numerics as _numerics
        cap = _numerics.should_capture("spmd")
        compressed = (not cap) and self._dcn_compress_active(pad)
        if self._dcn_residuals is not None \
                and not self._dcn_compress_active(0):
            # knob turned off: stale error feedback must not leak into a
            # later re-enable (mirrors set_gradient_compression's reset)
            self._dcn_residuals = None
        jitted = self._program(pad, instrument=cap)
        # the batch shard_put is the host->mesh boundary; the gradient
        # allreduce itself is a compiler-scheduled psum INSIDE the jitted
        # step (visible on the device plane of a merged trace, not here).
        # ensure_staged feeds host numpy STRAIGHT to the sharded device_put
        # (no intermediate default-device commit) and is a NO-OP for batches
        # a DevicePrefetcher already placed — steady-state steps then do
        # zero synchronous H2D here (io.h2d_sync.spmd stays flat).
        with _tracing.span("spmd.shard_batch", cat="spmd"):
            data = _io.ensure_staged(data, self._batch_sharding,
                                     source="spmd")
            label = _io.ensure_staged(label, self._batch_sharding,
                                      source="spmd")
        self._step_num += 1
        self.optimizer.num_update = self._step_num
        if not hasattr(self, "_hyper_cache"):
            self._hyper_cache = {}
        lrs, wds = _opt_hyper_arrays(self.optimizer, len(self.fn.trainable),
                                     self._hyper_cache)
        from .. import random as _random
        key = _random.new_eager_seed_key()
        sparse = {n for n in self._sparse_embed if n in self.fn.trainable}
        train = {n: self.params[n] for n in self.fn.trainable
                 if n not in sparse}
        tables = {n: self.params[n] for n in sparse}
        aux = {n: self.params[n] for n in self.fn.aux}
        scales = self._hyper_cache.setdefault("scales", {})
        # cache only plain-number scales (arrays are unhashable and a
        # dynamic loss-scale would grow the cache unboundedly)
        cacheable = isinstance(lr_scale, (int, float))
        sarr = scales.get(lr_scale) if cacheable else None
        if sarr is None:
            sarr = jnp.asarray(lr_scale, jnp.float32)
            if cacheable and len(scales) < 16:
                scales[lr_scale] = sarr
        t_arr = jnp.asarray(self._step_num, jnp.int32)
        if compressed and self._dcn_residuals is None:
            n_dcn = int(self.mesh.shape["dcn"])
            rsh = NamedSharding(self.mesh, P("dcn"))
            self._dcn_residuals = {
                n: jax.device_put(
                    jnp.zeros((n_dcn,) + tuple(train[n].shape),
                              train[n].dtype if jnp.issubdtype(
                                  train[n].dtype, jnp.inexact)
                              else jnp.float32), rsh)
                for n in train}
        args = (train, aux, self.opt_state) + \
            ((self._dcn_residuals,) if compressed else ()) + \
            ((tables,) if sparse else ()) + (data, label, key, t_arr, lrs,
                                             wds, sarr)
        stats = None
        if self._guard_mode:
            if self._nan_streak is None:
                self._nan_streak = jnp.zeros((), jnp.int32)
            res = jitted(*args, self._nan_streak)
            if cap:
                (new_train, new_aux, self.opt_state, loss,
                 self._nan_streak, stats) = res
            elif compressed:
                (new_train, new_aux, self.opt_state, self._dcn_residuals,
                 loss, self._nan_streak) = res
            else:
                new_train, new_aux, self.opt_state, loss, \
                    self._nan_streak = res
            # no-sync host inspection of completed steps' streaks
            _resilience.watch_streak("spmd", self._nan_streak)

            def _replay(data=data, label=label, key=key, t_arr=t_arr,
                        lrs=lrs, wds=wds, sarr=sarr, pad=pad):
                # nanguard forensics (mx.numerics): re-run THIS batch
                # once through the instrumented variant.  Params and opt
                # state are read live (last-good after select_tree) and
                # COPIED because the replay donates them like any step;
                # the abort path still checkpoints the originals after.
                import jax as _jax
                fi = self._program(pad, instrument=True)
                spn = {n for n in self._sparse_embed
                       if n in self.fn.trainable}
                train = _jax.tree_util.tree_map(
                    jnp.array,
                    {n: self.params[n] for n in self.fn.trainable
                     if n not in spn})
                tables = _jax.tree_util.tree_map(
                    jnp.array, {n: self.params[n] for n in spn})
                aux = {n: self.params[n] for n in self.fn.aux}
                ost = _jax.tree_util.tree_map(jnp.array, self.opt_state)
                a = (train, aux, ost) + ((tables,) if spn else ()) + \
                    (data, label, key, t_arr, lrs, wds, sarr)
                return fi(*a, jnp.zeros((), jnp.int32))[-1]

            _numerics.hold_replay("spmd", _replay)
        else:
            res = jitted(*args)
            if cap:
                new_train, new_aux, self.opt_state, loss, stats = res
            elif compressed:
                (new_train, new_aux, self.opt_state, self._dcn_residuals,
                 loss) = res
            else:
                new_train, new_aux, self.opt_state, loss = res
        if compressed:
            # static wire accounting (no device sync): each step's DCN hop
            # carries the packed codes — 4 per byte vs 4 bytes per f32
            wire = getattr(self, "_dcn_wire", None)
            if wire is None:
                packed = sum((int(v.size) + 3) // 4
                             for v in new_train.values())
                raw_b = sum(int(v.size) * 4 for v in new_train.values())
                wire = self._dcn_wire = (packed, raw_b)
            from .. import telemetry as _telemetry
            _telemetry.counter("kvstore.compressed_bytes").inc(wire[0])
            _telemetry.counter("kvstore.compressed_raw_bytes").inc(wire[1])
            comp = _telemetry.counter("kvstore.compressed_bytes").value
            raw = _telemetry.counter("kvstore.compressed_raw_bytes").value
            if comp:
                _telemetry.gauge("kvstore.compression_ratio").set(
                    raw / comp)
        if stats is not None:
            # device stats enter the pending queue; drained by the
            # is-ready poll later — zero host sync on this thread
            _numerics.publish("spmd", self._step_num, stats)
        from .. import profiler as _profiler
        _profiler.counter_increment("fused_steps")
        if sparse:
            # static per-step accounting (no device sync): each routed table
            # gathers/touches at most `capacity` unique rows this step; the
            # data-dependent unique_ratio gauge is fed by the eager
            # ShardedEmbedding API and the bench/check tools
            from . import embedding as _pemb
            from .. import telemetry as _telemetry
            cap = _pemb.unique_capacity(int(data.size)) * len(tables)
            _telemetry.counter("embedding.gathered_rows").inc(cap)
            _telemetry.counter("embedding.rows_touched").inc(cap)
        self.params = {}
        self.params.update(new_train)
        self.params.update(new_aux)
        return loss

    def sync(self):
        """Write device params back into the Block's Parameters (always in
        the reference OIHW layout, whatever the internal layout is)."""
        self.fn.write_back(self._layout_ref(self.params))

    # ---------------------------------------------------------- checkpoint
    def attach_checkpoint_manager(self, manager, auto_resume=True):
        """Wire a ``resilience.CheckpointManager`` into the step loop:
        ``maybe_save`` fires on its every-N cadence after each step, a
        preemption signal checkpoints through it before exiting, and the
        nanguard abort path writes a last-good checkpoint.  With
        ``auto_resume`` (default) the newest GOOD checkpoint is restored
        immediately — a corrupt/truncated newest file is skipped for the
        last good one.  Returns the resumed step, or None on cold start.

        In a multi-process world a plain CheckpointManager is upgraded to
        the coordinated protocol (``elastic.CoordinatedCheckpointManager``:
        rank 0 writes + world-stamped manifest + all-ranks barrier) — an
        uncoordinated save from N ranks into one directory would race."""
        if jax.process_count() > 1:
            from .. import elastic as _elastic
            manager = _elastic.coordinate(manager, mesh=self.mesh)
        self._ckpt_manager = manager
        if auto_resume:
            return manager.restore(self.load_checkpoint)
        return None

    def _preempt_save(self):
        """Best-effort checkpoint for preemption/nanguard-abort exits."""
        if self._ckpt_manager is not None and self.params is not None:
            self._ckpt_manager.save(self._step_num, self.save_checkpoint)

    def _ckpt_meta(self):
        """Shared guard + metadata for both checkpoint formats."""
        from .. import random as _random
        if self.params is None:
            raise ValueError("nothing to checkpoint: trainer has no "
                             "materialized params (run a step first)")
        return self._step_num, _random._global_key()

    def save_checkpoint_sharded(self, path):
        """Sharded checkpoint via orbax: every host writes ONLY its own
        shards (no gather), the layout that scales to multi-host models too
        big to fit one host's RAM — the TPU-native answer to the
        reference's single-file NDArray serializer (SURVEY §5.4;
        src/ndarray/ndarray.cc Save).  `save_checkpoint` remains the
        single-host portable-file path."""
        import os
        import orbax.checkpoint as ocp
        step_num, rng_key = self._ckpt_meta()
        tree = {
            "params": dict(self.params),
            "opt_state": self.opt_state,
            "meta": {"step_num": jnp.asarray(step_num, jnp.int32),
                     "rng_key": rng_key},
        }
        path = os.path.abspath(path)
        if os.path.isdir(path) and os.listdir(path) and not os.path.exists(
                os.path.join(path, "_CHECKPOINT_METADATA")):
            # force=True rmtree's the target; only a PRIOR CHECKPOINT may
            # be overwritten — never an unrelated user directory
            raise ValueError(
                "%s exists and is not an orbax checkpoint; refusing to "
                "delete it" % path)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, tree, force=True)
        ckptr.wait_until_finished()

    def load_checkpoint_sharded(self, path):
        """Restore an orbax checkpoint directly into this trainer's
        shardings: the restore target carries NamedShardings built from
        the checkpoint metadata + this trainer's param specs, so each host
        reads ONLY the shards it owns (a target-less restore would
        materialize every array in full on every process)."""
        import os
        import orbax.checkpoint as ocp
        from .. import random as _random

        path = os.path.abspath(path)
        from .. import resilience as _resilience
        if not os.path.isdir(path) or not os.path.exists(
                os.path.join(path, "_CHECKPOINT_METADATA")):
            raise _resilience.CheckpointCorruptError(
                "%s is not an orbax checkpoint (missing "
                "_CHECKPOINT_METADATA — interrupted save or wrong path)"
                % path)
        ckptr = ocp.StandardCheckpointer()
        try:
            md = ckptr.metadata(path)
            if hasattr(md, "item_metadata"):
                # newer orbax wraps the tree in a StepMetadata-style object;
                # 0.7.x StandardCheckpointer returns the tree dict directly
                md = md.item_metadata.tree
        except Exception as exc:  # noqa: BLE001 — orbax raises many types
            raise _resilience.CheckpointCorruptError(
                "orbax metadata for %s is unreadable (%s: %s)"
                % (path, type(exc).__name__, exc)) from exc
        if not isinstance(md, dict) or not {
                "params", "opt_state", "meta"} <= set(md):
            raise _resilience.CheckpointCorruptError(
                "orbax checkpoint %s carries no usable tree metadata "
                "(got %s)" % (path, type(md).__name__))
        mesh = self.mesh

        def abstract(meta, spec):
            return jax.ShapeDtypeStruct(
                tuple(meta.shape), meta.dtype,
                sharding=NamedSharding(mesh, spec))

        target = {
            "params": {n: abstract(m, self._spec_for(n))
                       for n, m in md["params"].items()},
            "opt_state": {
                n: jax.tree_util.tree_map(
                    lambda m, s=self._spec_for(n): abstract(m, s), sub)
                for n, sub in md["opt_state"].items()},
            "meta": jax.tree_util.tree_map(lambda m: abstract(m, P()),
                                           md["meta"]),
        }
        restored = ckptr.restore(path, target)
        self._step_num = int(restored["meta"]["step_num"])
        self.optimizer.num_update = self._step_num
        self.params = dict(restored["params"])
        # orbax may hand tuples back as lists; the jitted step was traced
        # with tuple-typed optimizer states, so normalize the structure
        self.opt_state = {n: _state_to_jax(v)
                          for n, v in restored["opt_state"].items()}
        _random._STATE.key = jnp.asarray(restored["meta"]["rng_key"])

    def save_checkpoint(self, path):
        """Save params + optimizer state + step count to ``path``.

        The SPMD analog of Module checkpointing (reference:
        python/mxnet/model.py:394-442 save_checkpoint) plus Trainer optimizer
        state (python/mxnet/gluon/trainer.py:436 save_states) in ONE file:
        there is no symbol/params split because the program is the jitted
        step, and optimizer state lives beside the weights it shards with.
        Arrays are gathered to host; `load_checkpoint` re-places them with
        the trainer's own shardings, so the mesh shape may differ between
        save and restore (e.g. checkpoint on 8 chips, resume on 16).
        """
        import numpy as np
        import pickle
        from .. import resilience as _resilience
        step_num, rng_key = self._ckpt_meta()
        # single-file checkpoints always carry the reference OIHW layout so
        # they stay interchangeable across conv.weights_layout settings
        ref_params = self._layout_ref(self.params)
        ref_state = self._layout_state(self.opt_state, to_internal=False)
        host = {
            "schema": _resilience.CKPT_SCHEMA,
            "format": "mxnet_tpu-spmd-ckpt",
            "step_num": step_num,
            "params": {n: _to_host(v) for n, v in ref_params.items()},
            "opt_state": jax.tree_util.tree_map(_to_host, ref_state),
            # The eager PRNG stream position: models that draw per step
            # (dropout, SGLD) must resume on the same key sequence for the
            # bitwise-continue guarantee to hold.
            "rng_key": np.asarray(rng_key),
        }
        if self._dcn_residuals is not None:
            # compressed-DCN error feedback rides along so a resumed run
            # continues the quantized trajectory bitwise
            host["dcn_residuals"] = {n: _to_host(v) for n, v in
                                     self._dcn_residuals.items()}
        # atomic publish: a crash mid-write leaves the previous checkpoint
        # under `path`, never a truncated pickle (docs/RESILIENCE.md)
        with _resilience.atomic_write(path, "wb") as f:
            pickle.dump(host, f)

    def load_checkpoint(self, path):
        """Restore a `save_checkpoint` file; training continues bitwise
        where it left off (same data ⇒ same loss curve).

        Truncated/unpicklable files and newer-schema checkpoints raise
        ``resilience.CheckpointCorruptError`` up front — never a deep
        ``EOFError``/``KeyError`` from half-restored state — so
        ``CheckpointManager.restore`` can fall back to the previous one."""
        import pickle
        from .. import random as _random
        from .. import resilience as _resilience
        try:
            with open(path, "rb") as f:
                host = pickle.load(f)
        except (OSError, EOFError, pickle.UnpicklingError, AttributeError,
                ImportError, IndexError, ValueError) as exc:
            raise _resilience.CheckpointCorruptError(
                "checkpoint %s is unreadable (%s: %s)"
                % (path, type(exc).__name__, exc)) from exc
        if not isinstance(host, dict) or not (
                {"step_num", "params", "opt_state"} <= set(host)):
            raise _resilience.CheckpointCorruptError(
                "checkpoint %s is not an SPMDTrainer checkpoint (missing "
                "step_num/params/opt_state)" % path)
        if int(host.get("schema", 1)) > _resilience.CKPT_SCHEMA:
            raise _resilience.CheckpointCorruptError(
                "checkpoint %s was written by a newer schema (%s > %s); "
                "upgrade this framework to load it"
                % (path, host.get("schema"), _resilience.CKPT_SCHEMA))
        self._step_num = host["step_num"]
        self.optimizer.num_update = self._step_num
        self.params = self._layout_internal(
            {n: jnp.asarray(v) for n, v in host["params"].items()})
        self.opt_state = self._layout_state(host["opt_state"],
                                            to_internal=True)
        self._place()
        if "rng_key" in host:
            _random._STATE.key = jnp.asarray(host["rng_key"])
        self._nan_streak = None  # restored params are finite by definition
        self._dcn_residuals = None
        dres = host.get("dcn_residuals")
        if dres and "dcn" in self.mesh.axis_names:
            n_dcn = int(self.mesh.shape["dcn"])
            if all(v.shape[0] == n_dcn for v in dres.values()):
                rsh = NamedSharding(self.mesh, P("dcn"))
                self._dcn_residuals = {
                    n: jax.device_put(jnp.asarray(v), rsh)
                    for n, v in dres.items()}
            # a re-formed world with a different dcn extent restarts the
            # error feedback from zero (first compressed step re-inits)
        return self._step_num


def _to_host(x):
    """Gather a (possibly multi-host-sharded) array to a host numpy array."""
    import numpy as np
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils
        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)


def _state_to_jax(st):
    from ..ndarray.ndarray import NDArray
    if st is None:
        return None
    if isinstance(st, NDArray):
        return st._data
    if isinstance(st, (tuple, list)):
        return tuple(_state_to_jax(s) for s in st)
    return st


def _preprocess(optimizer, grad):
    g = grad * optimizer.rescale_grad
    if optimizer.clip_gradient is not None:
        g = jnp.clip(g, -optimizer.clip_gradient, optimizer.clip_gradient)
    return g


def _raw_loss(loss_fn, out, label):
    from ..ndarray.ndarray import NDArray, _wrap
    try:
        loss = loss_fn(_wrap(out), _wrap(label))
        loss = loss._data if isinstance(loss, NDArray) else loss
    except (TypeError, AttributeError):
        loss = loss_fn(out, label)
        loss = loss._data if isinstance(loss, NDArray) else loss
    return loss.astype(jnp.float32)


def _as_scalar_loss(loss_fn, out, label):
    return jnp.mean(_raw_loss(loss_fn, out, label))


def _as_masked_scalar_loss(loss_fn, out, label, pad):
    """Mean loss over all but the last ``pad`` rows: trailing fill rows
    (bucketed padding, ``DataBatch.pad``) contribute nothing to loss OR
    gradients.  ``pad`` is STATIC — the slice makes the reduction
    structurally identical to the unpadded program's ``jnp.mean``, so the
    masked loss matches the unpadded loss bitwise (a traced mask would
    reduce over the padded length and drift in the last ulp)."""
    loss = _raw_loss(loss_fn, out, label)
    if loss.ndim == 0:
        raise ValueError(
            "pad-masked step needs per-sample losses: loss_fn reduced over "
            "the batch already — return unreduced losses or drop pad=")
    valid = int(loss.shape[0]) - int(pad)
    if valid <= 0:
        raise ValueError("pad=%d leaves no valid rows in a %d-row batch"
                         % (pad, int(loss.shape[0])))
    return jnp.mean(loss[:valid])


def build_train_step(block, loss_fn, optimizer, optimizer_params=None,
                     mesh=None, **kw):
    """Convenience: construct an SPMDTrainer and return (trainer, step_fn)."""
    tr = SPMDTrainer(block, loss_fn, optimizer, optimizer_params, mesh=mesh,
                     **kw)
    return tr, tr.step
