"""``mx.obs`` — the live operational plane over the telemetry registry.

Reference: src/profiler/profiler.h aggregate_stats gave the reference
framework an always-on aggregate view, but it died inside the process —
``telemetry.snapshot()`` is only reachable from Python, and a serving
request leaves no record an operator could grep.  This module is the
fleet-facing analog (the vLLM / TF-Serving production pattern): a
scrapeable exporter plus request-level structured logs plus SLO math.

Four pieces, each off by default and independently togglable:

  * EXPORTER (``obs.listen`` / ``MXNET_TPU_OBS_LISTEN=host:port``) — a
    stdlib ``http.server`` daemon thread serving

      - ``/metrics``: the whole telemetry registry in Prometheus text
        exposition format (timers as summaries whose quantiles come from
        the rotating 60s window, so scraped latency is LIVE latency), plus
        SLO burn-rate gauges when ``obs.slo`` is armed;
      - ``/healthz``: per-model breaker state, batcher/engine thread
        liveness, KV-pool saturation and last-step age, aggregated from
        health sources the serving layer registers — HTTP 503 when any
        source reports unhealthy;
      - ``/varz``: every config knob with its effective value and
        ``config.source()`` provenance (override/env/default).

  * ACCESS LOG (``obs.access_log`` / ``MXNET_TPU_OBS_ACCESS_LOG=
    jsonl:<path>``) — exactly one JSONL record per serving/generation
    request, outcome ok|shed|deadline|breaker|error, request_id = the
    ``tracing.span`` trace_id so a slow request's log line joins against
    the Chrome trace (schema below, validated by
    ``validate_access_record``).

  * SLO TRACKER (``obs.slo`` / ``MXNET_TPU_OBS_SLO``) — declared
    objectives (availability percent, windowed-p99 latency bound) with
    multi-window burn rates (5m/1h fast, 30m/6h slow — the SRE-workbook
    pairing) computed from the serving counters; surfaced on ``/metrics``,
    ``slo_status()``, and tools/telemetry_report.py.

  * the windowed ``p50_1m``/``p99_1m`` quantiles themselves live in
    ``telemetry.Timer`` — the only cost this plane adds while both knobs
    are off (one timestamp compare per observation; bench.py
    ``obs_overhead`` proves the ≤2% bound with everything ON).

Access-record schema::

    {"event": "access", "ts": <unix s>, "request_id": <trace_id|null>,
     "model": <str>, "outcome": "ok|shed|deadline|breaker|error",
     "queue_ms": <float|null>, "dispatch_ms": <float|null>,
     "ttft_ms": <float|null>, "tokens": <int|null>, "bytes": <int|null>,
     "error": "<ExcType: message>" (only on outcome=error)}

Stdlib-only on purpose — importable (and scrapeable) with no jax on the
path, so an operator can point the exporter at a dead-looking process.
"""
from __future__ import annotations

import json
import re
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import config as _config
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = ["configure_listen", "configure_access_log", "configure_slo",
           "exporter_address", "render_prometheus", "healthz", "varz",
           "register_health_source", "unregister_health_source",
           "access_log_enabled", "access_log_path", "log_access",
           "flush_access_log", "validate_access_record", "OUTCOMES",
           "SLOTracker", "slo_tracker", "slo_status",
           "SLO_TOTAL_COUNTER", "SLO_ERROR_COUNTERS"]

#: the access-record outcome vocabulary (one terminal outcome per request)
OUTCOMES = ("ok", "shed", "deadline", "breaker", "error")


# ---------------------------------------------------- prometheus rendering
_PROM_PREFIX = "mxnet_tpu_"
_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")

#: registry families whose trailing dotted segment is a per-model series
#: (serving emits both the base counter and a ``<base>.<model>`` twin):
#: rendered as ONE family with a {model="..."} label so the exposition
#: never carries duplicate-family spellings of the same metric
_LABELED_FAMILIES = ("serving.shed_requests", "serving.deadline_exceeded",
                     "serving.breaker_open", "serving.breaker_state")

#: families whose trailing TWO dotted segments are ``<model>.<site>``
#: (mx.numerics' quantization-drift gauges); site names carry no dots,
#: so the split is on the LAST dot
_LABELED_FAMILIES_2 = ("quant.drift_ratio",)


def _prom_name(name):
    return _PROM_PREFIX + _PROM_BAD_CHARS.sub("_", name)


def _prom_labels(labels):
    if not labels:
        return ""
    parts = []
    for key in sorted(labels):
        val = str(labels[key])
        val = val.replace("\\", "\\\\").replace('"', '\\"')
        val = val.replace("\n", "\\n")
        parts.append('%s="%s"' % (key, val))
    return "{%s}" % ",".join(parts)


def _prom_value(value):
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    if value != value:
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    if value == int(value) and abs(value) < 2 ** 53:
        return str(int(value))
    return repr(value)


def _split_family(name):
    for base in _LABELED_FAMILIES_2:
        if name.startswith(base + ".") and len(name) > len(base) + 1:
            model, _, site = name[len(base) + 1:].rpartition(".")
            if model and site:
                return base, {"model": model, "site": site}
    for base in _LABELED_FAMILIES:
        if name.startswith(base + ".") and len(name) > len(base) + 1:
            return base, {"model": name[len(base) + 1:]}
    return name, None


def render_prometheus(snap=None):
    """Render a telemetry snapshot (default: a fresh one) as Prometheus
    text exposition format: counters/gauges one family each (per-model
    twins folded into a labeled family), timers as summaries whose
    quantile samples come from the two-epoch window (live latency) with
    the lifetime reservoir as fallback before the first windowed sample,
    plus the SLO burn-rate gauges when ``obs.slo`` is armed."""
    if snap is None:
        snap = _telemetry.snapshot()
    # family -> {"type": ..., "samples": [(suffix, labels, value)]};
    # keyed on the SANITIZED name so two registry spellings that collide
    # after sanitization merge into one family instead of duplicating it
    families = {}
    order = []

    def add(name, typ, value, labels=None, suffix=""):
        fam = _prom_name(name)
        entry = families.get(fam)
        if entry is None:
            entry = families[fam] = {"type": typ, "samples": []}
            order.append(fam)
        entry["samples"].append((suffix, labels, value))

    for name in sorted(snap.get("counters", ())):
        base, labels = _split_family(name)
        add(base, "counter", snap["counters"][name], labels)
    for name in sorted(snap.get("gauges", ())):
        base, labels = _split_family(name)
        add(base, "gauge", snap["gauges"][name], labels)
    for name in sorted(snap.get("timers", ())):
        st = snap["timers"][name]
        live = st.get("count_1m", 0) > 0
        add(name, "summary", st.get("p50_1m") if live else st.get("p50"),
            {"quantile": "0.5"})
        add(name, "summary", st.get("p99_1m") if live else st.get("p99"),
            {"quantile": "0.99"})
        add(name, "summary", st.get("total", 0.0), None, "_sum")
        add(name, "summary", st.get("count", 0), None, "_count")

    tracker = _slo_tick()
    if tracker is not None:
        status = tracker.status()
        if status.get("error_budget") is not None:
            add("slo.availability_target", "gauge",
                status["availability_target"])
            add("slo.error_budget", "gauge", status["error_budget"])
            add("slo.requests", "gauge", status["requests"])
            add("slo.errors", "gauge", status["errors"])
            for window in sorted(status["burn_rates"]):
                add("slo.burn_rate", "gauge",
                    status["burn_rates"][window], {"window": window})
            for speed, _fast, _slow, _thr in SLOTracker.ALERTS:
                add("slo.burn_alert", "gauge",
                    1 if speed in status["alerts"] else 0,
                    {"speed": speed})
        lat = status.get("latency")
        if lat is not None:
            add("slo.latency_target_ms", "gauge", lat["target_ms"],
                {"timer": lat["timer"]})
            add("slo.latency_p99_1m_ms", "gauge", lat["p99_1m"],
                {"timer": lat["timer"]})
            add("slo.latency_breach", "gauge", 1 if lat["breach"] else 0,
                {"timer": lat["timer"]})

    lines = []
    for fam in order:
        entry = families[fam]
        lines.append("# TYPE %s %s" % (fam, entry["type"]))
        for suffix, labels, value in entry["samples"]:
            val = _prom_value(value)
            if val is None:  # non-numeric gauge: not representable
                continue
            lines.append("%s%s%s %s"
                         % (fam, suffix, _prom_labels(labels), val))
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------ health plane
_HEALTH_LOCK = threading.Lock()
_HEALTH_SOURCES = {}  # guarded-by[writes]: _HEALTH_LOCK — name -> callable


def register_health_source(name, fn):
    """Register a health callable for ``/healthz``.  ``fn()`` returns a
    JSON-serializable dict; a ``"healthy": False`` entry (or a raised
    exception) marks the whole process unhealthy.  ``serving.Server``
    registers one per server around start()/stop()."""
    with _HEALTH_LOCK:
        _HEALTH_SOURCES[name] = fn


def unregister_health_source(name):
    with _HEALTH_LOCK:
        _HEALTH_SOURCES.pop(name, None)


def healthz():
    """Aggregate health: ``(ok, report)``.  The report carries every
    registered source's dict verbatim plus the tracing last-step age; a
    source that raises is itself reported unhealthy rather than taking
    the endpoint down."""
    report = {"healthy": True, "sources": {},
              "last_step_age_s": round(_tracing.last_step_age_s(), 3)}
    with _HEALTH_LOCK:
        items = list(_HEALTH_SOURCES.items())
    for name, fn in items:
        try:
            info = dict(fn() or {})
        except Exception as exc:  # noqa: BLE001 — a dead source IS a finding
            info = {"healthy": False,
                    "error": "%s: %s" % (type(exc).__name__, exc)}
        info.setdefault("healthy", True)
        report["sources"][name] = info
        if not info["healthy"]:
            report["healthy"] = False
    return report["healthy"], report


def varz():
    """Every registered knob: effective value + provenance."""
    out = {}
    for name, knob in sorted(_config.knobs().items()):
        out[name] = {"value": _config.get(name),
                     "source": _config.source(name),
                     "env": knob.env}
    return out


# --------------------------------------------------------------- exporter
_EXPORTER_LOCK = threading.Lock()
_SERVER = None         # guarded-by[writes]: _EXPORTER_LOCK
_SERVER_THREAD = None  # guarded-by[writes]: _EXPORTER_LOCK
_LISTEN_ADDR = None    # guarded-by[writes]: _EXPORTER_LOCK


class _Handler(BaseHTTPRequestHandler):
    server_version = "mx-obs/1"

    def log_message(self, *args):  # stdlib default spams stderr per scrape
        pass

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                _telemetry.counter("obs.scrapes").inc()
                code, ctype = 200, \
                    "text/plain; version=0.0.4; charset=utf-8"
                body = render_prometheus()
            elif path == "/healthz":
                ok, report = healthz()
                code, ctype = (200 if ok else 503), "application/json"
                body = json.dumps(report, default=str) + "\n"
            elif path == "/varz":
                code, ctype = 200, "application/json"
                body = json.dumps(varz(), default=str) + "\n"
            else:
                code, ctype = 404, "text/plain"
                body = "not found: %s\n" % path
        except Exception as exc:  # noqa: BLE001 — scrape must not kill thread
            code, ctype = 500, "text/plain"
            body = "%s: %s\n" % (type(exc).__name__, exc)
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        try:
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-response


def _parse_listen(spec):
    spec = (spec or "").strip()
    if not spec:
        return None
    host, sep, port = spec.rpartition(":")
    if not sep:
        raise ValueError("obs.listen %r is not host:port" % (spec,))
    try:
        port = int(port)
    except ValueError:
        raise ValueError("obs.listen %r has a non-integer port" % (spec,))
    if not 0 <= port <= 65535:
        raise ValueError("obs.listen port %d out of range" % port)
    return (host or "127.0.0.1", port)


def configure_listen(spec):
    """(Re)configure the exporter from an ``obs.listen`` spec: ``host:port``
    starts (or rebinds) the daemon HTTP thread, empty/None stops it.
    Raises ValueError on a malformed spec and OSError when the address
    can't be bound — the knob hook reverts the override on either."""
    global _SERVER, _SERVER_THREAD, _LISTEN_ADDR
    addr = _parse_listen(spec)
    with _EXPORTER_LOCK:
        if addr == _LISTEN_ADDR and (_SERVER is not None) == \
                (addr is not None):
            return
        if _SERVER is not None:
            old = _SERVER
            _SERVER = None
            _SERVER_THREAD = None
            _LISTEN_ADDR = None
            old.shutdown()
            old.server_close()
        if addr is not None:
            srv = ThreadingHTTPServer(addr, _Handler)
            srv.daemon_threads = True
            thread = threading.Thread(target=srv.serve_forever,
                                      kwargs={"poll_interval": 0.1},
                                      name="mx-obs-exporter", daemon=True)
            _SERVER = srv
            _SERVER_THREAD = thread
            _LISTEN_ADDR = addr
            thread.start()


def exporter_address():
    """The exporter's bound ``(host, port)`` (the real port when
    ``obs.listen`` asked for port 0), or None when off."""
    with _EXPORTER_LOCK:
        if _SERVER is None:
            return None
        host, port = _SERVER.server_address[:2]
        return (host, port)


# ------------------------------------------------------------- access log
# The write path is ASYNCHRONOUS: ``log_access`` only builds the record
# dict and appends it to a thread-safe deque (sub-microsecond — this is
# what runs on the batcher/engine dispatch threads), and a daemon writer
# thread drains the queue to disk every _ACCESS_FLUSH_S.  JSON encoding
# and file IO never touch the serving hot path.  The queue is bounded:
# past _ACCESS_QUEUE_MAX pending records new ones are DROPPED and counted
# in ``obs.access_dropped`` (an access log must never become the
# backpressure).  Handles are rebound only under the lock, while
# log_access() reads the sink handle lock-free as the enabled flag (a
# stale read drops at most one record during reconfigure), hence [writes].
_ACCESS_LOCK = threading.Lock()
_ACCESS_SINK = None    # guarded-by[writes]: _ACCESS_LOCK
_ACCESS_PATH = None    # guarded-by[writes]: _ACCESS_LOCK
_ACCESS_THREAD = None  # guarded-by[writes]: _ACCESS_LOCK
_ACCESS_STOP = None    # guarded-by[writes]: _ACCESS_LOCK
_ACCESS_QUEUE = deque()     # thread-safe append/popleft, no lock needed
_ACCESS_QUEUE_MAX = 65536   # pending-record bound before drops start
_ACCESS_FLUSH_S = 0.05      # writer-thread drain cadence


#: printable ASCII minus ``"`` and ``\`` — strings matching this need no
#: JSON escaping, so the writer skips the (slow) json.dumps scan for the
#: identifier-shaped strings every record carries
_JSON_PLAIN = re.compile(r'^[ -!#-\[\]-~]*$')
#: quoted-literal cache for the low-cardinality strings (model names,
#: outcomes) that repeat on every record; bounded so a pathological
#: caller can't grow it without limit
_QUOTED = {}  # guarded-by: _ACCESS_LOCK — only the drain loop touches it


def _json_str(s):  # mxlint: holds(_ACCESS_LOCK)
    """JSON string literal, fast-pathing escape-free ASCII.  The writer
    thread competes for the GIL with the serving hot path, so every
    record serialized here is priced per-microsecond: alphanumeric
    strings (request ids) quote directly, repeated identifiers hit the
    cache, everything else falls back to the full escape scan."""
    if type(s) is not str:
        s = str(s)
    if s.isalnum():
        return '"%s"' % s
    q = _QUOTED.get(s)
    if q is None:
        q = '"%s"' % s if _JSON_PLAIN.match(s) else json.dumps(s)
        if len(_QUOTED) < 1024:
            _QUOTED[s] = q
    return q


def _drain_access_locked():  # mxlint: holds(_ACCESS_LOCK)
    """Serialize and write every queued record to the current sink (drop
    them if the sink is gone).  One flush per batch keeps the on-disk
    tail at most one drain cadence behind the live stream.  Records are
    %-formatted rather than json.dumps'd — ~4x cheaper, and this runs
    concurrently with live dispatch (see _json_str)."""
    sink = _ACCESS_SINK
    if sink is None:
        _ACCESS_QUEUE.clear()
        return
    lines = []
    while True:
        try:
            (ts, model, outcome, request_id, queue_ms, dispatch_ms,
             ttft_ms, tokens, nbytes, error) = _ACCESS_QUEUE.popleft()
        except IndexError:
            break
        line = ('{"event":"access","ts":%.6f,"request_id":%s,'
                '"model":%s,"outcome":%s'
                % (ts,
                   _json_str(request_id) if request_id is not None
                   else "null",
                   _json_str(model), _json_str(outcome)))
        if queue_ms is not None:
            line += ',"queue_ms":%.3f' % float(queue_ms)
        if dispatch_ms is not None:
            line += ',"dispatch_ms":%.3f' % float(dispatch_ms)
        if ttft_ms is not None:
            line += ',"ttft_ms":%.3f' % float(ttft_ms)
        if tokens is not None:
            line += ',"tokens":%d' % tokens
        if nbytes is not None:
            line += ',"bytes":%d' % nbytes
        if error is not None:
            line += ',"error":%s' % _json_str(error)
        lines.append(line)
    if lines:
        sink.write("}\n".join(lines) + "}\n")
        sink.flush()
        _telemetry.counter("obs.access_records").inc(len(lines))


def _access_writer(stop):
    while not stop.wait(_ACCESS_FLUSH_S):
        with _ACCESS_LOCK:
            _drain_access_locked()


def configure_access_log(spec):
    """(Re)configure the per-request JSONL access log from an
    ``obs.access_log`` spec: ``jsonl:<path>`` (bare path accepted), empty
    disables.  Rebinding stops the old writer thread, drains every
    pending record to the OLD sink, then opens the new one."""
    global _ACCESS_SINK, _ACCESS_PATH, _ACCESS_THREAD, _ACCESS_STOP
    spec = (spec or "").strip()
    path = None
    if spec:
        path = spec[len("jsonl:"):] if spec.startswith("jsonl:") else spec
        if not path:
            raise ValueError("obs.access_log %r names no path" % (spec,))
    with _ACCESS_LOCK:
        if path == _ACCESS_PATH and (_ACCESS_SINK is None) == \
                (path is None):
            return
        old_thread, old_stop = _ACCESS_THREAD, _ACCESS_STOP
        _ACCESS_THREAD = _ACCESS_STOP = None
        if old_stop is not None:
            old_stop.set()
    if old_thread is not None:
        old_thread.join(timeout=5.0)
    with _ACCESS_LOCK:
        _drain_access_locked()
        if _ACCESS_SINK is not None:
            try:
                _ACCESS_SINK.close()
            except Exception:  # noqa: BLE001 — best-effort close
                pass
            _ACCESS_SINK = None
        _ACCESS_PATH = path
        if path is not None:
            _ACCESS_SINK = open(path, "a")
            _ACCESS_STOP = threading.Event()
            _ACCESS_THREAD = threading.Thread(
                target=_access_writer, args=(_ACCESS_STOP,),
                name="mx-obs-access", daemon=True)
            _ACCESS_THREAD.start()


def access_log_enabled():
    """Whether the access log is on — serving/generation gate every
    per-record cost (trace-id lookup, record build) on this."""
    return _ACCESS_SINK is not None


def access_log_path():
    return _ACCESS_PATH


def flush_access_log():
    """Synchronously drain the pending queue and fsync the sink — call
    before reading the file (tests, shutdown hooks)."""
    import os as _os
    with _ACCESS_LOCK:
        if _ACCESS_SINK is None:
            return
        _drain_access_locked()
        _ACCESS_SINK.flush()
        try:
            _os.fsync(_ACCESS_SINK.fileno())
        except OSError:  # pragma: no cover — non-fsyncable sink
            pass


def log_access(model, outcome, request_id=None, queue_ms=None,
               dispatch_ms=None, ttft_ms=None, tokens=None,
               bytes=None, error=None,  # noqa: A002 — schema field name
               _now=time.time, _qlen=_ACCESS_QUEUE.__len__,
               _qput=_ACCESS_QUEUE.append):
    """Enqueue one access record (no-op when the log is off).  One call
    per request terminal outcome — the serving/generation layers own the
    exactly-once discipline (a record is emitted where the future is
    resolved, under the same done-check).  Hot-path cost is one
    timestamp, one tuple and one deque append (the trailing underscore
    defaults pre-bind the globals — this runs on the dispatch threads);
    the record build, serialization and IO all happen on the writer
    thread.  _ACCESS_QUEUE is a module-lifetime singleton (configure
    drains it, never rebinds it), so the bound methods stay valid."""
    if _ACCESS_SINK is None:
        return
    if _qlen() >= _ACCESS_QUEUE_MAX:
        _telemetry.counter("obs.access_dropped").inc()
        return
    _qput((_now(), model, outcome, request_id, queue_ms, dispatch_ms,
           ttft_ms, tokens, bytes, error))


_ACCESS_REQUIRED = {"event": str, "ts": (int, float), "model": str,
                    "outcome": str}
_ACCESS_OPTIONAL = {"request_id": str, "queue_ms": (int, float),
                    "dispatch_ms": (int, float), "ttft_ms": (int, float),
                    "tokens": int, "bytes": int, "error": str}


def validate_access_record(rec):
    """Validate one parsed access-log record against the documented
    schema; raises ValueError naming the offending field."""
    if not isinstance(rec, dict):
        raise ValueError("access record must be an object, got %r" % (rec,))
    for key, typ in _ACCESS_REQUIRED.items():
        if key not in rec:
            raise ValueError("access record missing required field %r" % key)
        if not isinstance(rec[key], typ) or isinstance(rec[key], bool):
            raise ValueError("field %r: expected %s, got %r"
                             % (key, typ, rec[key]))
    if rec["event"] != "access":
        raise ValueError("not an access record: event=%r" % (rec["event"],))
    if rec["outcome"] not in OUTCOMES:
        raise ValueError("outcome %r not in %r" % (rec["outcome"], OUTCOMES))
    for key, typ in _ACCESS_OPTIONAL.items():
        if rec.get(key) is not None and (not isinstance(rec[key], typ)
                                         or isinstance(rec[key], bool)):
            raise ValueError("field %r: expected %s or null, got %r"
                             % (key, typ, rec[key]))
    for key in ("queue_ms", "dispatch_ms", "ttft_ms", "tokens", "bytes"):
        if rec.get(key) is not None and rec[key] < 0:
            raise ValueError("field %r: negative %r" % (key, rec[key]))
    return rec


# ------------------------------------------------------------ SLO tracker
#: the availability denominator: every admitted serving/generation request
SLO_TOTAL_COUNTER = "serving.requests"
#: the availability numerator: request-terminal failures.  dispatch_errors
#: is per-BATCH (a lower bound on failed requests); the rest are
#: per-request.  Documented in docs/OBSERVABILITY.md.
SLO_ERROR_COUNTERS = ("serving.shed_requests", "serving.deadline_exceeded",
                      "serving.breaker_rejected", "serving.dispatch_errors")


class SLOTracker:
    """Multi-window multi-burn-rate SLO tracking over a ring of
    ``(ts, total, errors)`` counter samples.

    Burn rate over window W = (error rate across W) / (error budget),
    where budget = 1 - availability_target: burn 1.0 spends the budget
    exactly at the objective period's natural pace, burn 14.4 exhausts a
    30-day budget in ~50 hours.  Alerting uses the SRE-workbook pairing —
    page when BOTH fast windows (5m and 1h) burn > 14.4, ticket when both
    slow windows (30m and 6h) burn > 6 — so a single scrape blip can't
    page and a slow leak can't hide.

    Samples arrive from ``/metrics`` scrapes and ``slo_status()`` calls
    (resolution = scrape cadence); tests drive ``observe`` directly with
    explicit timestamps — the math is deterministic given the stream."""

    BURN_WINDOWS = (("5m", 300.0), ("30m", 1800.0),
                    ("1h", 3600.0), ("6h", 21600.0))
    #: (speed, short window, long window, burn threshold)
    ALERTS = (("fast", "5m", "1h", 14.4), ("slow", "30m", "6h", 6.0))
    MAX_POINTS = 8192  # ring bound: ~22h of 10s scrapes, covers 6h window

    def __init__(self, availability=None, latency_p99_ms=None,
                 latency_timer="serving.request_ms"):
        if availability is not None and not 0.0 < availability < 100.0:
            raise ValueError("availability %r must be in (0, 100) percent"
                             % (availability,))
        if latency_p99_ms is not None and latency_p99_ms <= 0:
            raise ValueError("latency_p99_ms %r must be > 0"
                             % (latency_p99_ms,))
        self.availability = availability
        self.latency_p99_ms = latency_p99_ms
        self.latency_timer = latency_timer
        self._lock = threading.Lock()
        # (monotonic ts, total, errors) samples
        self._points = deque(maxlen=self.MAX_POINTS)  # guarded-by: _lock

    @property
    def error_budget(self):
        if self.availability is None:
            return None
        return 1.0 - self.availability / 100.0

    def observe(self, total, errors, now=None):
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._points and now <= self._points[-1][0]:
                # scrapes race: keep the stream monotonic in time
                now = self._points[-1][0] + 1e-9
            self._points.append((now, int(total), int(errors)))

    def burn_rates(self, now=None):
        """``{window_label: burn_rate}`` — 0.0 for a window with no
        traffic (the no-requests state spends no budget)."""
        budget = self.error_budget
        if budget is None or budget <= 0.0:
            return {}
        with self._lock:
            pts = list(self._points)
        if not pts:
            return {label: 0.0 for label, _ in self.BURN_WINDOWS}
        t_now, total_now, err_now = pts[-1]
        if now is not None:
            t_now = max(t_now, now)
        out = {}
        for label, span in self.BURN_WINDOWS:
            cutoff = t_now - span
            base = pts[0]
            for p in pts:
                # latest sample at or before the window start: a young
                # stream falls back to its oldest sample (partial window)
                if p[0] <= cutoff:
                    base = p
                else:
                    break
            d_total = total_now - base[1]
            d_err = err_now - base[2]
            rate = (float(d_err) / d_total) if d_total > 0 else 0.0
            out[label] = rate / budget
        return out

    def alerts(self, burn=None, now=None):
        if burn is None:
            burn = self.burn_rates(now)
        fired = []
        for speed, short, long_, threshold in self.ALERTS:
            if burn.get(short, 0.0) > threshold \
                    and burn.get(long_, 0.0) > threshold:
                fired.append(speed)
        return fired

    def status(self, now=None):
        burn = self.burn_rates(now)
        with self._lock:
            last = self._points[-1] if self._points else (0.0, 0, 0)
        out = {"availability_target": self.availability,
               "error_budget": self.error_budget,
               "requests": last[1], "errors": last[2],
               "burn_rates": burn, "alerts": self.alerts(burn),
               "latency": None}
        if self.latency_p99_ms is not None:
            st = _telemetry.timer(self.latency_timer).stats()
            out["latency"] = {"timer": self.latency_timer,
                              "target_ms": self.latency_p99_ms,
                              "p99_1m": round(st["p99_1m"], 3),
                              "breach": st["p99_1m"] > self.latency_p99_ms}
        return out


_SLO_LOCK = threading.Lock()
_SLO = None       # guarded-by[writes]: _SLO_LOCK — armed SLOTracker | None
_SLO_SPEC = None  # guarded-by[writes]: _SLO_LOCK


def configure_slo(spec):
    """(Re)arm the SLO tracker from an ``obs.slo`` spec:
    ``availability=99.9,latency_p99_ms=50[,timer=serving.request_ms]``;
    empty disables.  Raises ValueError on unknown keys, unparsable
    numbers, or a spec with no objective at all."""
    global _SLO, _SLO_SPEC
    spec = (spec or "").strip()
    tracker = None
    if spec:
        kv = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError("obs.slo part %r is not key=value" % part)
            key, val = part.split("=", 1)
            kv[key.strip()] = val.strip()
        unknown = set(kv) - {"availability", "latency_p99_ms", "timer"}
        if unknown:
            raise ValueError("obs.slo: unknown objective(s) %s"
                             % ", ".join(sorted(unknown)))
        try:
            availability = (float(kv["availability"])
                            if "availability" in kv else None)
            latency = (float(kv["latency_p99_ms"])
                       if "latency_p99_ms" in kv else None)
        except ValueError:
            raise ValueError("obs.slo %r has a non-numeric objective"
                             % (spec,))
        if availability is None and latency is None:
            raise ValueError("obs.slo %r declares no objective" % (spec,))
        tracker = SLOTracker(
            availability=availability, latency_p99_ms=latency,
            latency_timer=kv.get("timer", "serving.request_ms"))
    with _SLO_LOCK:
        _SLO = tracker
        _SLO_SPEC = spec or None


def slo_tracker():
    return _SLO


def _registry_error_total():
    total = _telemetry.counter(SLO_TOTAL_COUNTER).value
    errors = sum(_telemetry.counter(name).value
                 for name in SLO_ERROR_COUNTERS)
    return total, errors


def _slo_tick(now=None):
    """Feed the armed tracker one sample from the live registry counters;
    returns the tracker (or None when ``obs.slo`` is off)."""
    tracker = _SLO
    if tracker is None:
        return None
    total, errors = _registry_error_total()
    tracker.observe(total, errors, now)
    return tracker


def slo_status():
    """The armed tracker's status dict (objectives, burn rates, fired
    alerts, windowed latency vs target), ticked against the live registry
    — or None when ``obs.slo`` is off."""
    tracker = _slo_tick()
    if tracker is None:
        return None
    return tracker.status()


# honor the MXNET_TPU_OBS_* env vars at import (the knobs' set() hooks
# handle runtime flips) — same contract as telemetry.configure_sink
try:
    configure_listen(_config.get("obs.listen"))
    configure_access_log(_config.get("obs.access_log"))
    configure_slo(_config.get("obs.slo"))
except KeyError:  # pragma: no cover — config stripped of the knobs
    pass
