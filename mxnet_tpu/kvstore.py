"""KVStore — parameter synchronization facade.

Reference: ``include/mxnet/kvstore.h:59-391`` + ``src/kvstore/`` — three
backends behind one API: local/device (intra-process multi-GPU reduce,
comm.h:451), NCCL (kvstore_nccl.h), and ps-lite parameter server
(kvstore_dist.h).  ``KVStore::Create`` parses the type string
(src/kvstore/kvstore.cc:40-72).

TPU-native re-design (SURVEY.md §5.8): the whole comm stack collapses into XLA
collectives.  Within one process all devices live under one jax namespace, so
"reduce across device copies" is a sum over the provided arrays; across hosts
(``dist_*``) gradients are allreduced with ``jax.lax.psum`` over the global
mesh via ``mxnet_tpu.parallel`` (DCN-hierarchical, handled by XLA).  The
push/pull/updater semantics — including update_on_kvstore placement, which
affects numerics — follow kvstore_local.h:69,195-294.
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp

from .ndarray.ndarray import NDArray, _wrap
from . import optimizer as opt
from . import telemetry as _telemetry
from . import resilience as _resilience

__all__ = ["KVStore", "create"]


def _key_str(key):
    return str(key)


def _payload_bytes(value):
    """Wire-size accounting for push/pull telemetry: bytes of every array
    in a possibly-nested value list (per-device copies each count — they
    each cross the reduce boundary in the reference model)."""
    if isinstance(value, (list, tuple)):
        return sum(_payload_bytes(v) for v in value)
    data = getattr(value, "_data", value)
    try:
        return int(data.size) * int(data.dtype.itemsize)
    except Exception:  # noqa: BLE001 — sparse wrappers without one buffer
        try:
            import numpy as _np
            return int(_np.prod(value.shape)) * 4
        except Exception:  # noqa: BLE001
            return 0


class KVStore:
    """A key-value store for parameter synchronization
    (reference: include/mxnet/kvstore.h:59, python/mxnet/kvstore.py:66)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._updater_obj = None
        self._compression_params = None
        self._is_dist = kv_type.startswith("dist")
        if self._is_dist:
            # Creating a dist kvstore IS the worker's rendezvous in the
            # reference (ps::KVWorker construction, kvstore_dist.h:44-50);
            # mirror that: join the jax.distributed cluster if a launcher
            # provided one and we have not joined yet.
            from .parallel import ensure_initialized
            ensure_initialized()

    # --------------------------------------------------------------- meta
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """Worker rank (reference: KVStore::get_rank)."""
        if self._is_dist:
            return jax.process_index()
        return 0

    @property
    def num_workers(self):
        if self._is_dist:
            return jax.process_count()
        return 1

    # --------------------------------------------------------------- CRUD
    def init(self, key, value):
        """Initializes one or more key-value pairs
        (reference: kvstore.py:139)."""
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = _wrap(jnp.asarray(v._data))

    def _merge(self, value):
        """Reduce per-device copies — the CommDevice::Reduce analog
        (src/kvstore/comm.h:451)."""
        if isinstance(value, (list, tuple)):
            merged = value[0]._data
            for v in value[1:]:
                merged = jnp.add(merged, v._data)
            return merged
        return value._data

    def _allreduce_dist(self, val):
        """Cross-process sum over DCN (ps-lite server-merge analog,
        src/kvstore/kvstore_dist_server.h:349)."""
        if self.num_workers == 1:
            return val
        # the simulated DCN failure point: before the hop, so the push()
        # retry wrapper re-runs it without double-applying anything
        _resilience.inject("dcn_push")
        from .parallel import host_allreduce
        return host_allreduce(val)

    def _allreduce_codes(self, codes):
        """Cross-process sum of 2-bit CODES over DCN: the wire carries the
        PACKED form (4 codes/byte — 1/16 of the f32 bytes, reference
        gradient_compression-inl.h quantize_2bit wire layout); each worker
        unpacks the peers' rows and sums locally.  Value contract is
        identical to ``_allreduce_dist`` on the unpacked codes."""
        if self.num_workers == 1:
            return codes
        _resilience.inject("dcn_push")
        from . import tracing as _tracing
        from .parallel import host_allgather
        from .parallel.compression import pack_2bit, unpack_2bit
        shape, n = codes.shape, int(codes.size)
        packed = pack_2bit(codes)
        wire = int(packed.size)
        _telemetry.counter("kvstore.compressed_bytes").inc(wire)
        _telemetry.counter("kvstore.compressed_raw_bytes").inc(n * 4)
        comp = _telemetry.counter("kvstore.compressed_bytes").value
        raw = _telemetry.counter("kvstore.compressed_raw_bytes").value
        if comp:
            _telemetry.gauge("kvstore.compression_ratio").set(raw / comp)
        with _tracing.span("allreduce_2bit", cat="collective"):
            gathered = host_allgather(packed)
        total = jnp.zeros(shape, jnp.int32)
        for w in range(int(gathered.shape[0])):
            total = total + unpack_2bit(gathered[w], n).reshape(shape)
        return total

    def _compression_threshold(self):
        from . import config as _config
        params = self._compression_params or {}
        return float(params.get(
            "threshold", _config.get("kvstore.grad_compression_threshold")))

    def _compress(self, k, merged):
        """2-bit quantization with per-key error feedback (reference
        gradient_compression.cc); enabled by ``set_gradient_compression``
        or the ``kvstore.grad_compress`` knob.  Returns ``(payload,
        compressed_flag, new_residual)`` — the caller commits the
        residual only AFTER the DCN hop succeeds, so a retried
        ``dcn_push`` fault re-runs this bit-identically instead of
        double-counting the quantization error."""
        from . import config as _config
        params = getattr(self, "_compression_params", None)
        ctype = (params or {}).get("type") or \
            _config.get("kvstore.grad_compress")
        if ctype != "2bit" or self.num_workers == 1:
            return merged, False, None
        if self.num_workers > 127:
            # summed int8 codes would overflow the wire dtype
            return merged, False, None
        from .parallel.compression import two_bit_compress
        thr = self._compression_threshold()
        if not hasattr(self, "_residuals"):
            self._residuals = {}
        res = self._residuals.get(k)
        if res is None:
            res = jnp.zeros_like(merged)
        codes, new_res = two_bit_compress(merged, res, thr)
        return codes, True, new_res

    def push(self, key, value, priority=0):
        """Pushes (aggregates) value(s) into the store
        (reference: kvstore.py:178; KVStoreLocal::PushImpl kvstore_local.h:206).
        """
        from . import tracing as _tracing
        keys, values = _normalize_push(key, value)
        _telemetry.counter("kvstore.push_calls").inc()
        _telemetry.counter("kvstore.push_bytes").inc(_payload_bytes(values))
        with _tracing.span("kvstore.push", cat="kvstore", keys=len(keys)):
            # transient transport errors retry with backoff; fault
            # injection ("kvstore" kind) fires at entry, before any key is
            # merged, so a retried injected fault never double-applies an
            # update.  Real mid-body failures on the update_on_kvstore
            # path may re-run the updater for already-pushed keys.
            _resilience.call_with_retry(self._push_impl, keys, values,
                                        kind="kvstore", inject_faults=True)

    def _push_impl(self, keys, values):
        for k, v in zip(keys, values):
            merged = self._merge(v)
            payload, compressed, new_res = self._compress(k, merged)
            if compressed:
                reduced = self._allreduce_codes(payload)
                # commit the error feedback only once the hop succeeded
                self._residuals[k] = new_res
                # sum(codes) * threshold == sum of decompressed gradients
                merged = reduced.astype(merged.dtype) * \
                    self._compression_threshold()
            else:
                merged = self._allreduce_dist(payload)
            if self._updater is not None:
                self._updater(_key_int(k), _wrap(merged), self._store[k])
            else:
                self._store[k]._data = merged

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Pulls value(s) from the store into out
        (reference: kvstore.py:248)."""
        assert out is not None
        from . import tracing as _tracing
        keys, outs = _normalize_push(key, out)
        _telemetry.counter("kvstore.pull_calls").inc()
        _telemetry.counter("kvstore.pull_bytes").inc(_payload_bytes(outs))
        with _tracing.span("kvstore.pull", cat="kvstore", keys=len(keys)):
            # pull is idempotent (pure store → out copy), so retrying a
            # mid-body failure is always safe
            _resilience.call_with_retry(self._pull_impl, keys, outs,
                                        kind="kvstore", inject_faults=True)

    def _pull_impl(self, keys, outs):
        for k, o in zip(keys, outs):
            src = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t._data = jnp.asarray(src._data, t._data.dtype)

    def pushpull(self, key, value, out=None, priority=0):
        """Combined push and pull (reference: kvstore.py:290)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)
        else:
            self.pull(key, value, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull ONLY the requested rows of a stored value (reference:
        kvstore.py:318 / src/kvstore/kvstore_local.h:294 PullRowSparse).

        `out` receives a tensor that is zero everywhere except `row_ids`,
        whose rows hold the store's current values — the dense image of the
        row_sparse result (XLA gather does the row selection)."""
        assert out is not None
        if row_ids is None:
            self.pull(key, out, priority)
            return
        import jax.numpy as jnp
        outs = out if isinstance(out, (list, tuple)) else [out]
        keys = key if isinstance(key, (list, tuple)) else [key] * len(outs)
        ids = row_ids if isinstance(row_ids, (list, tuple)) else \
            [row_ids] * len(outs)
        from .ndarray.sparse import RowSparseNDArray
        from . import telemetry as _telemetry
        for k, o, rid in zip(keys, outs, ids):
            stored = self._store[k]
            src = stored._data if hasattr(stored, "_data") else \
                jnp.asarray(stored)
            rows = jnp.asarray(rid._data if hasattr(rid, "_data")
                               else rid).astype(jnp.int32).ravel()
            # deduplicate repeated row_ids BEFORE the gather (reference:
            # kvstore_local.h:354 Unique on the pull keys): each distinct
            # row crosses the store boundary once; duplicates are restored
            # on output through the inverse map — a cheap [K]-row gather
            uniq, inv = jnp.unique(rows, return_inverse=True)
            dup = int(rows.shape[0]) - int(uniq.shape[0])
            if dup:
                _telemetry.counter("kvstore.rowsparse_dedup_rows").inc(dup)
            gathered = src[uniq]
            if isinstance(o, RowSparseNDArray):
                # sparse out: only the K requested rows are gathered and
                # stored — no dense image is built on either side
                o._set_rows(rows, gathered[jnp.ravel(inv)].astype(o.dtype))
                continue
            dense = jnp.zeros_like(src).at[uniq].set(gathered)
            o._set_data(dense.astype(o._data.dtype)) \
                if hasattr(o, "_set_data") else setattr(o, "_data", dense)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    # ----------------------------------------------------------- optimizer
    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error feedback (reference:
        src/kvstore/gradient_compression.cc:60), applied on the DIST push
        path before the cross-process hop (see _compress).  ICI collectives
        stay uncompressed — compiler-scheduled psum at full ICI bandwidth
        beats recompression; DCN (multi-process host network) is where the
        16x byte reduction pays."""
        self._compression_params = dict(compression_params or {})
        self._residuals = {}

    def set_optimizer(self, optimizer):
        """Registers an optimizer so updates run "on kvstore" — the
        update_on_kvstore path (reference: kvstore.py:399)."""
        self._optimizer = optimizer
        self._updater_obj = opt.get_updater(optimizer)
        self._updater = self._updater_obj

    def set_updater(self, updater):
        """Sets a push updater (reference: kvstore.py:512)."""
        self._updater = updater
        if isinstance(updater, opt.Updater):
            self._updater_obj = updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater_obj is not None, "Cannot save states for distributed training"
        with _resilience.atomic_write(fname, "wb") as fout:
            fout.write(self._updater_obj.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater_obj is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater_obj.set_states(fin.read())

    # ----------------------------------------------------------- dist sync
    def barrier(self):
        """Global barrier across workers (reference: KVStore::Barrier)."""
        if self._is_dist and self.num_workers > 1:
            from .parallel import barrier
            barrier()

    def _send_command_to_servers(self, head, body):
        pass


def _key_int(k):
    try:
        return int(k)
    except ValueError:
        return k


def _normalize(key, value):
    if isinstance(key, (list, tuple)):
        keys = [_key_str(k) for k in key]
        values = list(value)
    else:
        keys = [_key_str(key)]
        values = [value]
    return keys, values


def _normalize_push(key, value):
    if isinstance(key, (list, tuple)):
        keys = [_key_str(k) for k in key]
        values = list(value)
    else:
        keys = [_key_str(key)]
        values = [value]
    return keys, values


def create(name="local"):
    """Creates a KVStore (reference: python/mxnet/kvstore.py:649;
    type parsing src/kvstore/kvstore.cc:40-72)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "device", "nccl", "dist_sync", "dist_device_sync",
             "dist_async", "dist_sync_device", "local_allreduce_cpu",
             "local_allreduce_device")
    if name not in valid:
        raise ValueError("Unknown KVStore type %r" % name)
    if name == "dist_async":
        # Explicit, documented alias (docs/MIGRATION.md "dist_async"):
        # the reference's async mode exists to hide straggler latency
        # behind parameter-server staleness
        # (src/kvstore/kvstore_dist_server.h:349-359, apply-on-push).  On a
        # TPU pod there is no parameter server — updates ride synchronous
        # XLA collectives over ICI, which are faster than a PS round trip —
        # so async's staleness tradeoff buys nothing and training runs
        # SYNCHRONOUSLY.  Convergence therefore matches dist_sync (a
        # strictly stronger contract than async staleness).
        import warnings
        warnings.warn(
            "kvstore 'dist_async' runs with SYNCHRONOUS semantics on this "
            "backend (no parameter server; see docs/MIGRATION.md). "
            "Convergence is dist_sync-equivalent or better.",
            stacklevel=2)
    return KVStore(name)
