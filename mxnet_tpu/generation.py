"""``mx.serving`` generation engine — token-level continuous batching
over a paged device-resident KV cache.

Reference: the C predict API's stateful RNN serving
(include/mxnet/c_predict_api.h MXPredCreatePartialOut + state handles)
kept one sequence's recurrent state device-resident across calls; the
TPU-native analog generalizes that to MANY concurrent sequences sharing
one page pool, scheduled per decode ITERATION (Orca) instead of per
request, with vLLM-style block-paged KV memory so cache capacity is
pooled instead of pre-reserved per slot.

Architecture (one :class:`GenerationEngine` thread per generation model,
run under the same restart supervisor as the one-shot batcher):

  submit ──► admission check ──► FIFO ──► engine loop, per iteration:
             (bounded queue,              1. harvest expired deadlines
              breaker state)              2. admit queue head into a free
                                             decode slot IF the page pool
                                             covers prompt+max_new pages
                                             (head-of-line wait otherwise:
                                             serving.kv_pool_exhausted)
                                          3. PREFILL each new request
                                             (B=1 program at its prompt
                                             bucket) → first token (TTFT)
                                          4. one DECODE step for all
                                             active slots (B=slots
                                             program at the page-table
                                             width bucket) → next tokens
                                          5. finished sequences (EOS /
                                             max_new) resolve futures,
                                             pages recycle immediately

Key properties:

* **Flat compiles** — programs are AOT-compiled at ``start()``: one
  prefill program per prompt bucket and one decode program per
  page-table width, all at fixed batch (1 and ``decode_slots``).  Ragged
  traffic — any prompt-length mix, mid-flight exits, joins — never
  reaches the compiler (``tools/check_generation.py`` proves it).
* **Paged KV memory** — position ``t`` of a sequence lives at slot
  ``t % page_size`` of page ``table[t // page_size]``; pages come from a
  shared free list and return to it the iteration their sequence
  finishes.  The pool dimension is symbolic in the v4 artifact, so
  ``serving.kv_pages`` is a pure runtime choice.
* **Bitwise parity** — the token stream each request receives is bitwise
  equal to the eager greedy oracle
  (``models.TransformerLM.greedy_decode``) regardless of what else is in
  flight: prefill runs the exact ``apply()`` attention math and the
  decode step's masked paged attention contributes exact zeros for
  padding (kernels.paged_attention).
* **Donated pool** — the page pool is donated into every program call
  (it is the only O(pool) buffer); a dispatch failure therefore poisons
  it, so the engine fails every in-flight sequence with the causal
  error, rebuilds the pool zeroed, feeds the model's circuit breaker and
  keeps serving.
* **Shared-prefix pages** (``serving.shared_prefix``) — full prompt-
  prefix pages are content-hashed at admission; concurrent requests with
  a common prefix (the system-prompt case) map to the SAME physical
  pages with refcounted sharing, freed only when the last reader exits.
  Causal attention makes a prefix position's K/V depend only on the
  tokens before it, so the shared bytes are identical no matter which
  sharer wrote them; divergence is page-granular copy-on-write by
  construction — the first token past the shared full pages lands in a
  private page.  ``serving.prefix_hits`` / ``serving.prefix_pages_shared``
  count the wins; ``kv_pages_in_use`` counts every physical page ONCE.
* **Sampling** (v5 artifacts) — per-request temperature / top-k / top-p
  ride the decode program family with a per-request PRNG key folded by
  position, so a fixed seed yields ONE deterministic stream regardless
  of batch composition.  Greedy (temperature 0) stays the default and
  keeps the bitwise oracle contract.
* **PR-7 fault tolerance per slot** — admission sheds past
  ``serving.max_pending`` (ServerOverloadedError), queued requests whose
  deadline lapses complete typed and never prefill
  (DeadlineExceededError), an open breaker fails submits fast
  (CircuitOpenError), and the engine thread restarts under the
  ``mx.resilience`` budget.

Telemetry: ``serving.tokens_generated[.model]`` counters,
``serving.kv_pages_in_use.<model>`` gauge, ``serving.prefill_ms`` /
``serving.decode_step_ms`` / ``serving.ttft_ms`` /
``serving.generate_request_ms`` timers,
``serving.kv_pool_exhausted[.model]`` counters, and one
``serving_generate`` JSONL record per finished request (prompt_len,
new_tokens, ttft_ms, wall_ms — ``tools/telemetry_report.py`` folds these
into per-model TTFT/tokens-per-second columns and the
``kv_pool_exhaustion`` anomaly).

Knobs (config.py): ``serving.kv_page_size`` (baked at export),
``serving.kv_pages``, ``serving.decode_slots``; docs/SERVING.md
"Generation" has the full walkthrough.
"""
from __future__ import annotations

import logging
import math as _math
import threading
import time as _time
from collections import deque
from concurrent.futures import Future

import numpy as _np

import jax

from . import config as _config
from . import io as _io
from . import obs as _obs
from . import telemetry as _telemetry
from .serving import (CircuitOpenError, DeadlineExceededError,
                      ServerOverloadedError, ServingError,
                      _access_outcome)

__all__ = ["GenerationEngine"]

_LOG = logging.getLogger("mxnet_tpu.generation")


def _kernels_enabled():
    from . import kernels as _kernels
    return _kernels.enabled()


class _EngineCrashError(OSError):
    """Internal: wraps an engine-loop crash so
    ``resilience.call_with_retry`` drives the restart backoff."""


class _GenRequest:
    """One generation request: prompt + budget + the future its token
    stream resolves, stamped for TTFT / deadline accounting."""

    __slots__ = ("prompt", "plen", "max_new", "eos_id", "future",
                 "t_submit", "deadline", "need", "stall_counted",
                 "trace_id", "temperature", "top_k", "top_p", "key_words",
                 "prefix_keys")

    def __init__(self, prompt, max_new, eos_id, deadline_ms, need,
                 trace_id=None, temperature=0.0, top_k=0, top_p=1.0,
                 seed=0, prefix_keys=()):
        self.prompt = prompt
        self.plen = int(prompt.shape[0])
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.future = Future()
        self.t_submit = _time.perf_counter()
        self.deadline = (self.t_submit + float(deadline_ms) * 1e-3) \
            if deadline_ms and deadline_ms > 0 else None
        self.need = int(need)          # pages for prompt + max_new
        self.stall_counted = False     # kv_pool_exhausted counted once
        self.trace_id = trace_id       # submit span id for the access log
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        # raw uint32 key words in jax.random.PRNGKey layout — built
        # host-side once so every dispatch sees the same stream identity
        s = int(seed) & 0xFFFFFFFFFFFFFFFF
        self.key_words = (s >> 32, s & 0xFFFFFFFF)
        # content hashes of the FULL prompt-prefix pages, page 0 first:
        # key i covers tokens [0, (i+1)*page_size) — admission maps them
        # to shared physical pages
        self.prefix_keys = tuple(prefix_keys)

    def expired(self, now):
        return self.deadline is not None and now >= self.deadline


class _Slot:
    """One active decode slot: the sequence's pages, cached length and
    generated tokens.  Engine-thread-only state (``prefix_keys`` names
    the leading ``slot.pages`` entries owned by the shared-prefix map —
    released through ``_release_pages_locked``, never freed directly)."""

    __slots__ = ("req", "pages", "pos", "tokens", "ttft_ms",
                 "prefix_keys")

    def __init__(self, req, pages, prefix_keys=()):
        self.req = req
        self.pages = pages
        self.pos = req.plen      # tokens already in the cache
        self.tokens = []
        self.ttft_ms = None
        self.prefix_keys = tuple(prefix_keys)


class GenerationEngine:
    """Per-model continuous-batching generation scheduler (one thread).

    Owned by :class:`mxnet_tpu.serving.Server` (``register(...,
    generate=True)``); drives a :class:`mxnet_tpu.deploy
    .GenerationPredictor`'s prefill/decode program families over a
    shared page pool."""

    def __init__(self, name, predictor, breaker=None, num_pages=None,
                 decode_slots=None, max_pending=None,
                 default_deadline_ms=None):
        self.name = name
        self.predictor = predictor
        self.breaker = breaker
        self.num_pages = int(num_pages if num_pages is not None
                             else _config.get("serving.kv_pages"))
        self.decode_slots = int(decode_slots if decode_slots is not None
                                else _config.get("serving.decode_slots"))
        if predictor.decode_batch is not None:
            # the artifact pinned its decode batch at export (a concrete
            # dim is what lets the Pallas paged kernel bake in) — the
            # AOT program admits exactly that many slots, knob or not
            self.decode_slots = predictor.decode_batch
        self.max_pending = int(max_pending if max_pending is not None
                               else _config.get("serving.max_pending"))
        self.default_deadline_ms = float(
            default_deadline_ms if default_deadline_ms is not None
            else _config.get("serving.default_deadline_ms"))
        psz = predictor.page_size
        # a single request may never need more pages than the pool holds
        self.max_need = min(self.num_pages,
                            _math.ceil(predictor.max_context / psz))
        if self.max_need < 1:
            raise ServingError(
                "model %r: serving.kv_pages=%d cannot hold one page"
                % (name, self.num_pages))
        self._share = bool(_config.get("serving.shared_prefix"))
        # Cross-thread state (submit side vs engine thread) — the same
        # lock-discipline contract tools/mxlint.py checks on the Server.
        self._queue = deque()            # guarded-by: _cond
        self._free = list(range(self.num_pages))  # guarded-by: _cond
        # shared-prefix map: content key -> [page_id, refcount, populated]
        self._prefix = {}                # guarded-by: _cond
        self._cond = threading.Condition()
        self._started = False            # guarded-by: _cond
        self._stopping = False           # guarded-by: _cond
        self._abort = False              # guarded-by: _cond
        self._dead = None                # guarded-by: _cond — crash exc
        # last engine-loop iteration (the watchdog probe's liveness clock)
        self._last_iteration = _time.perf_counter()  # guarded-by: _cond
        self._probe_name = "serving-generate-%x" % id(self)
        # guarded-by[writes]: _cond — stop() joins outside the lock
        self._thread = None
        # Engine-thread-only state: the page pool arrays and decode slots
        # are touched exclusively by the engine loop — no lock.
        self._slots = [None] * self.decode_slots
        self._kv = None       # page-pool pytree (2 arrays, 4 when int8)
        self._prefill = {}    # prompt bucket -> compiled program
        self._decode = {}     # page-table width -> compiled program

    # ----------------------------------------------------------- compile
    def _compile_programs(self):
        """AOT-compile the full program family: one prefill per prompt
        bucket (B=1) and one decode step per page-table width
        (B=decode_slots).  This is the ENTIRE compiled set — ragged
        generation traffic never adds to it (``serving.compiles`` stays
        equal to the family size, the check_generation.py gate)."""
        from . import perf as _perf
        from . import tracing as _tracing
        gp = self.predictor
        params = gp._params
        pspec = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), params)
        kvspec = gp.kv_pool_specs(self.num_pages)
        i32 = _np.int32

        def sample_specs(b):
            # the uniform program wrappers take the sampling operands in
            # every format (v4 ignores them)
            return (jax.ShapeDtypeStruct((b,), _np.float32),
                    jax.ShapeDtypeStruct((b,), i32),
                    jax.ShapeDtypeStruct((b,), _np.float32),
                    jax.ShapeDtypeStruct((b, 2), _np.uint32))

        def compile_one(fn, arg_specs, label):
            t0 = _time.perf_counter()
            with _tracing.span("serving.compile", cat="serving",
                               model=self.name, program=label):
                traced = fn.trace(*arg_specs)
                t1 = _time.perf_counter()
                lowered = traced.lower()
                t2 = _time.perf_counter()
                program = lowered.compile()
                t3 = _time.perf_counter()
            _telemetry.counter("serving.compiles").inc()
            _telemetry.timer("serving.compile_ms").observe(
                (t3 - t0) * 1e3)
            _perf.register_compiled(
                "serving", "%s/%s" % (self.name, label), program,
                phases_ms={"trace_ms": (t1 - t0) * 1e3,
                           "lower_ms": (t2 - t1) * 1e3,
                           "compile_ms": (t3 - t2) * 1e3},
                dtype=str(gp.kv_dtype))
            return program

        for s_bucket in gp.prompt_buckets:
            if s_bucket in self._prefill:
                continue
            w_s = _math.ceil(s_bucket / gp.page_size)
            self._prefill[s_bucket] = compile_one(
                gp.prefill_fn(s_bucket),
                (pspec, kvspec,
                 jax.ShapeDtypeStruct((1, s_bucket), i32),
                 jax.ShapeDtypeStruct((1,), i32),
                 jax.ShapeDtypeStruct((1, w_s), i32))
                + sample_specs(1),
                "prefill-s%d" % s_bucket)
        for width in gp.decode_widths:
            if width in self._decode:
                continue
            self._decode[width] = compile_one(
                gp.decode_fn(width),
                (pspec, kvspec,
                 jax.ShapeDtypeStruct((self.decode_slots,), i32),
                 jax.ShapeDtypeStruct((self.decode_slots,), i32),
                 jax.ShapeDtypeStruct((self.decode_slots, width), i32))
                + sample_specs(self.decode_slots),
                "decode-w%d" % width)

    # --------------------------------------------------------- lifecycle
    def start(self):
        from . import tracing as _tracing
        with self._cond:
            if self._started:
                return self
        self._compile_programs()
        self._kv = self.predictor.make_kv(self.num_pages)
        with self._cond:
            self._stopping = False
            self._abort = False
            self._dead = None
            self._started = True
            self._last_iteration = _time.perf_counter()
            self._thread = threading.Thread(
                target=_tracing.wrap_context(self._supervise), daemon=True,
                name="mx-serving-generate-%s" % self.name)
        self._thread.start()
        # the serving batcher has carried a stall probe since PR-3; the
        # generation engine gets its sibling here — KV-pool occupancy,
        # decode-loop liveness and oldest in-flight request age land in
        # the watchdog hang report
        _tracing.register_stall_probe(self._probe_name, self._stall_probe)
        return self

    def stop(self, drain=True, timeout_s=30.0):
        """Stop the engine.  With ``drain`` (default) queued requests
        prefill and every in-flight sequence runs to completion; with
        ``drain=False`` queued AND active sequences fail promptly."""
        with self._cond:
            if not self._started:
                return
            self._stopping = True
            self._abort = self._abort or not drain
            self._cond.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout_s)
            if thread.is_alive():
                _telemetry.counter("serving.stop_timeout").inc()
                _LOG.warning("serving: generation engine %r did not "
                             "drain within %.1fs", self.name, timeout_s)
        from . import tracing as _tracing
        _tracing.unregister_stall_probe(self._probe_name)
        with self._cond:
            self._started = False
            self._thread = None

    # ------------------------------------------------------------ submit
    def submit(self, prompt, max_new_tokens, eos_id=None,
               deadline_ms=None, temperature=0.0, top_k=0, top_p=1.0,
               seed=None):
        """Enqueue one prompt; returns a Future resolving to the
        generated token ids (np.int32, EOS included when hit).  With
        ``temperature`` 0 (default) that is the bitwise
        ``greedy_decode`` stream; ``temperature`` > 0 samples with
        optional ``top_k`` / ``top_p`` truncation under a per-request
        ``seed`` (fresh entropy when None) — v5 artifacts only."""
        gp = self.predictor
        prompt = _np.asarray(prompt, _np.int32).reshape(-1)
        plen = int(prompt.shape[0])
        max_new = int(max_new_tokens)
        if plen < 1 or max_new < 1:
            raise ValueError(
                "model %r: need a non-empty prompt and max_new_tokens "
                ">= 1" % (self.name,))
        temperature = float(temperature)
        if temperature > 0.0 and not gp.sampling:
            raise ValueError(
                "model %r: temperature=%g needs a sampling-enabled "
                "artifact (format v5) — re-export with "
                "export_generation(..., sampling=True)"
                % (self.name, temperature))
        if seed is None:
            seed = _time.time_ns() if temperature > 0.0 else 0
        if plen + max_new > gp.max_context:
            raise ValueError(
                "model %r: prompt (%d) + max_new_tokens (%d) exceeds the "
                "artifact's max_context %d"
                % (self.name, plen, max_new, gp.max_context))
        gp.prefill_bucket(plen)   # raises if no bucket fits
        need = _math.ceil((plen + max_new) / gp.page_size)
        if need > self.max_need:
            raise ValueError(
                "model %r: request needs %d KV pages but the pool holds "
                "%d (serving.kv_pages) — shorten the request or grow the "
                "pool" % (self.name, need, self.num_pages))
        _telemetry.counter("serving.requests").inc()
        # the enclosing serving.submit span's trace_id (None when tracing
        # is off) rides the request so its access record joins the trace
        from . import tracing as _tracing
        sp = _tracing.current_span()
        trace_id = sp.trace_id if sp is not None else None
        breaker = self.breaker
        if breaker is not None and breaker.rejects_submit():
            _telemetry.counter("serving.breaker_rejected").inc()
            _obs.log_access(self.name, "breaker", request_id=trace_id)
            raise CircuitOpenError(
                "model %r circuit breaker is OPEN after %d consecutive "
                "dispatch failure(s); failing fast for %.0fms more"
                % (self.name, breaker.failures,
                   breaker.cooldown_remaining_ms()))
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        prefix_keys = ()
        if self._share:
            # content keys for the FULL prompt-prefix pages: key i covers
            # tokens [0, (i+1)*page_size) — causal attention makes the
            # page's K/V a pure function of those tokens, so equal keys
            # mean byte-equal pages
            psz = gp.page_size
            prefix_keys = tuple(
                (i, prompt[:(i + 1) * psz].tobytes())
                for i in range(plen // psz))
        req = _GenRequest(prompt, max_new, eos_id,
                          float(deadline_ms or 0.0), need,
                          trace_id=trace_id, temperature=temperature,
                          top_k=top_k, top_p=top_p, seed=seed,
                          prefix_keys=prefix_keys)
        with self._cond:
            if self._dead is not None:
                exc = self._dead
                raise ServingError(
                    "generation engine for model %r crashed (%s: %s) and "
                    "exhausted its restart budget; submit rejected"
                    % (self.name, type(exc).__name__, exc))
            if self._stopping or not self._started:
                raise ServingError(
                    "generation engine for model %r is %s; submit "
                    "rejected" % (self.name, "stopping" if self._stopping
                                  else "not started"))
            if self.max_pending > 0 \
                    and len(self._queue) >= self.max_pending:
                shed = True
            else:
                shed = False
                self._queue.append(req)
                self._cond.notify_all()
        if shed:
            _telemetry.counter("serving.shed_requests").inc()
            _telemetry.counter(
                "serving.shed_requests.%s" % self.name).inc()
            _obs.log_access(self.name, "shed", request_id=trace_id)
            raise ServerOverloadedError(
                "generation queue for model %r is at serving.max_pending"
                "=%d; request shed — back off and retry"
                % (self.name, self.max_pending))
        return req.future

    # ----------------------------------------------------------- the loop
    def _supervise(self):
        from . import resilience as _resilience
        try:
            _resilience.call_with_retry(self._run_engine,
                                        kind="serving_batcher")
        except BaseException as exc:  # noqa: BLE001 — budget exhausted
            cause = exc.__cause__ if exc.__cause__ is not None else exc
            with self._cond:
                self._dead = cause
                queued = list(self._queue)
                self._queue.clear()
                self._cond.notify_all()
            self._fail_all(queued, cause)
            _LOG.error(
                "serving: generation engine %r crashed and exhausted its "
                "restart budget (%s: %s); submits now fail fast",
                self.name, type(cause).__name__, cause)

    def _run_engine(self):
        try:
            self._loop()
        except BaseException as exc:  # noqa: BLE001 — supervised crash
            _telemetry.counter("serving.batcher_crashes").inc()
            with self._cond:
                queued = list(self._queue)
                self._queue.clear()
            self._fail_all(queued, exc)
            self._fail_active(exc)
            _LOG.warning(
                "serving: generation engine %r crashed (%s: %s); "
                "restarting under the resilience retry budget",
                self.name, type(exc).__name__, exc)
            raise _EngineCrashError(
                "generation engine crashed: %s: %s"
                % (type(exc).__name__, exc)) from exc

    def _active(self):
        return [s for s in self._slots if s is not None]

    def _fail_all(self, reqs, exc):
        outcome = _access_outcome(exc)
        err = ("%s: %s" % (type(exc).__name__, exc)
               if outcome == "error" else None)
        for req in reqs:
            if not req.future.done():
                req.future.set_exception(exc)
                if _obs.access_log_enabled():
                    _obs.log_access(
                        self.name, outcome, request_id=req.trace_id,
                        queue_ms=(_time.perf_counter() - req.t_submit)
                        * 1e3, error=err)

    def _fail_active(self, exc):
        """Fail every in-flight sequence and recycle its pages (the pool
        arrays were donated into the failed dispatch, so their state is
        gone — rebuild zeroed)."""
        released = []
        outcome = _access_outcome(exc)
        err = ("%s: %s" % (type(exc).__name__, exc)
               if outcome == "error" else None)
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            self._slots[i] = None
            released.append(slot)
            if not slot.req.future.done():
                slot.req.future.set_exception(exc)
                if _obs.access_log_enabled():
                    _obs.log_access(
                        self.name, outcome,
                        request_id=slot.req.trace_id,
                        ttft_ms=slot.ttft_ms,
                        tokens=len(slot.tokens), error=err)
        with self._cond:
            for slot in released:
                self._release_pages_locked(slot)
            # the rebuilt pool is zeroed, so any surviving shared-prefix
            # entries (refs held only by already-failed slots) are stale
            # — drop them and recycle their pages
            for entry in self._prefix.values():
                self._free.append(entry[0])
            self._prefix.clear()
            self._cond.notify_all()
        self._gauge_pages()
        self._kv = self.predictor.make_kv(self.num_pages)

    def _release_pages_locked(self, slot):  # mxlint: holds(_cond)
        """Return a slot's pages to the free list — shared-prefix pages
        decref through the map and only hit the free list when the LAST
        reader exits; the trailing private pages free unconditionally.
        ``kv_pages_in_use`` therefore counts every physical page once."""
        for key in slot.prefix_keys:
            entry = self._prefix.get(key)
            if entry is None:      # pool rebuild cleared the map already
                continue
            entry[1] -= 1
            if entry[1] <= 0:
                del self._prefix[key]
                self._free.append(entry[0])
        self._free.extend(slot.pages[len(slot.prefix_keys):])
        self._cond.notify_all()

    def _gauge_pages(self):
        with self._cond:
            in_use = self.num_pages - len(self._free)
        _telemetry.gauge(
            "serving.kv_pages_in_use.%s" % self.name).set(in_use)

    def _harvest_expired_locked(self, now):  # mxlint: holds(_cond)
        dead = [r for r in self._queue if r.expired(now)]
        for req in dead:
            self._queue.remove(req)
        return dead

    def _admit_locked(self, now):  # mxlint: holds(_cond)
        """Pop queue-head requests into free slots while the page pool
        covers them.  FIFO: a head request the pool cannot cover BLOCKS
        later ones (no starvation of long requests) and counts one
        ``serving.kv_pool_exhausted`` per stall episode."""
        admitted = []
        free_slots = [i for i, s in enumerate(self._slots) if s is None]
        while self._queue and free_slots:
            req = self._queue[0]
            # walk the request's full-prefix pages front-to-back: each
            # key already in the map is a shared page this request can
            # reuse instead of drawing from the free list.  The walk is
            # contiguous — a sharer holding key i also holds 0..i-1, so
            # refcounts are monotone non-increasing along the prefix.
            shared = []
            for key in req.prefix_keys:
                entry = self._prefix.get(key)
                if entry is None:
                    break
                shared.append((key, entry))
            if req.need - len(shared) > len(self._free):
                if not req.stall_counted:
                    req.stall_counted = True
                    _telemetry.counter("serving.kv_pool_exhausted").inc()
                    _telemetry.counter(
                        "serving.kv_pool_exhausted.%s" % self.name).inc()
                break
            self._queue.popleft()
            pages = []
            for key, entry in shared:
                entry[1] += 1
                pages.append(entry[0])
            # the remaining FULL-prefix pages are fresh: register them so
            # later requests with the same prompt prefix share them
            for key in req.prefix_keys[len(shared):]:
                page = self._free.pop()
                self._prefix[key] = [page, 1, False]
                pages.append(page)
            while len(pages) < req.need:
                pages.append(self._free.pop())
            if shared:
                _telemetry.counter("serving.prefix_hits").inc()
                _telemetry.counter(
                    "serving.prefix_hits.%s" % self.name).inc()
                _telemetry.counter(
                    "serving.prefix_pages_shared").inc(len(shared))
            self._slots[free_slots.pop(0)] = _Slot(
                req, pages, prefix_keys=req.prefix_keys)
            admitted.append(req)
        return admitted

    def _loop(self):
        while True:
            now = _time.perf_counter()
            with self._cond:
                self._last_iteration = now
                expired = self._harvest_expired_locked(now)
                admitted = self._admit_locked(now)
                active = self._active()
                if not admitted and not active:
                    if self._stopping and (self._abort
                                           or not self._queue):
                        queued = list(self._queue)
                        self._queue.clear()
                        abort = self._abort
                    else:
                        self._cond.wait(timeout=0.05)
                        queued = None
                        abort = False
                else:
                    queued = None
                    abort = False
            self._expire(expired)
            if queued is not None:
                if abort:
                    self._fail_all(queued, ServingError(
                        "generation engine stopped without drain"))
                return
            if not admitted and not active:
                continue
            with self._cond:
                abort = self._abort
            if abort:
                with self._cond:
                    queued = list(self._queue)
                    self._queue.clear()
                exc = ServingError(
                    "generation engine stopped without drain")
                self._fail_all(queued, exc)
                self._fail_active(exc)
                return
            self._gauge_pages()
            ok = True
            for req in admitted:
                if not self._dispatch_prefill(req):
                    ok = False
                    break
            if ok and self._active():
                self._dispatch_decode()

    def _expire(self, reqs):
        for req in reqs:
            _telemetry.counter("serving.deadline_exceeded").inc()
            _telemetry.counter(
                "serving.deadline_exceeded.%s" % self.name).inc()
            if not req.future.done():
                queued_ms = (_time.perf_counter() - req.t_submit) * 1e3
                req.future.set_exception(DeadlineExceededError(
                    "generation request for model %r expired in queue "
                    "before prefill (queued %.1fms, deadline passed)"
                    % (self.name, queued_ms)))
                _obs.log_access(self.name, "deadline",
                                request_id=req.trace_id,
                                queue_ms=queued_ms)

    def _dispatch_failed(self, exc):
        """Shared failure path: the donated pool is poisoned, so every
        in-flight sequence fails with the causal error and the breaker
        records the failure.  Returns False for the caller to bail."""
        _telemetry.counter("serving.dispatch_errors").inc()
        if self.breaker is not None:
            self.breaker.record_failure()
        self._fail_active(exc)
        return False

    def _dispatch_prefill(self, req):
        """Run one admitted request's prompt through its bucket's prefill
        program: seeds the shared pool (scatter touches only this
        request's pages, so in-flight sequences are untouched — the
        mid-flight JOIN) and produces the first token (TTFT)."""
        gp = self.predictor
        slot_idx = next(i for i, s in enumerate(self._slots)
                        if s is not None and s.req is req)
        slot = self._slots[slot_idx]
        breaker = self.breaker
        if breaker is not None and not breaker.allow_dispatch():
            self._slots[slot_idx] = None
            with self._cond:
                self._release_pages_locked(slot)
            if not req.future.done():
                req.future.set_exception(CircuitOpenError(
                    "model %r circuit breaker is OPEN; prefill failed "
                    "fast, retry after the cooldown" % (self.name,)))
                _obs.log_access(
                    self.name, "breaker", request_id=req.trace_id,
                    queue_ms=(_time.perf_counter() - req.t_submit) * 1e3)
            return True   # engine itself is fine
        s_bucket = gp.prefill_bucket(req.plen)
        w_s = _math.ceil(s_bucket / gp.page_size)
        sentinel = self.num_pages
        tokens = _np.zeros((1, s_bucket), _np.int32)
        tokens[0, :req.plen] = req.prompt
        table = _np.full((1, w_s), sentinel, _np.int32)
        k = min(w_s, len(slot.pages))
        table[0, :k] = slot.pages[:k]
        # shared-prefix pages another request already POPULATED must not
        # be rewritten mid-decode — sentinel them so this prefill's
        # scatter drops those rows (the bytes are already there; the
        # attention gather still reads them through slot.pages).
        # Populated-ness is decided here at dispatch time, not admission:
        # if the registering request died before its prefill ran, the
        # next sharer writes the pages itself.
        write_table = table
        if slot.prefix_keys:
            with self._cond:
                populated = [bool(self._prefix[key][2])
                             for key in slot.prefix_keys
                             if key in self._prefix]
            if any(populated):
                write_table = table.copy()
                for i, done in enumerate(populated):
                    if done and i < w_s:
                        write_table[0, i] = sentinel
        temp, tk, tp, keys = self._sample_arrays([(0, slot)], 1)
        t0 = _time.perf_counter()
        try:
            self._kv, nxt = self._prefill[s_bucket](
                gp._params, self._kv, tokens,
                _np.asarray([req.plen], _np.int32), write_table,
                temp, tk, tp, keys)
            first = int(nxt[0])
        except BaseException as exc:  # noqa: BLE001 — pool donated away
            return self._dispatch_failed(exc)
        if slot.prefix_keys:
            with self._cond:
                for key in slot.prefix_keys:
                    entry = self._prefix.get(key)
                    if entry is not None:
                        entry[2] = True
        t1 = _time.perf_counter()
        if breaker is not None:
            breaker.record_success()
        slot.tokens.append(first)
        slot.ttft_ms = (t1 - req.t_submit) * 1e3
        _telemetry.timer("serving.prefill_ms").observe((t1 - t0) * 1e3)
        _telemetry.timer("serving.ttft_ms").observe(slot.ttft_ms)
        self._count_tokens(1)
        self._maybe_finish(slot_idx)
        return True

    def _dispatch_decode(self):
        """One decode iteration for every active slot.  The page-table
        width buckets to the widest need among active sequences; inactive
        slots ride along on the all-sentinel row (writes drop, output
        ignored) — that is what keeps the compiled set flat while
        sequences EXIT and JOIN mid-flight."""
        gp = self.predictor
        B = self.decode_slots
        active = [(i, s) for i, s in enumerate(self._slots)
                  if s is not None]
        breaker = self.breaker
        if breaker is not None and not breaker.allow_dispatch():
            exc = CircuitOpenError(
                "model %r circuit breaker is OPEN; in-flight decode "
                "failed fast, retry after the cooldown" % (self.name,))
            for i, _ in active:
                self._slots[i] = None
            for _, s in active:
                if not s.req.future.done():
                    s.req.future.set_exception(exc)
                    _obs.log_access(
                        self.name, "breaker", request_id=s.req.trace_id,
                        ttft_ms=s.ttft_ms, tokens=len(s.tokens))
            with self._cond:
                for _, s in active:
                    self._release_pages_locked(s)
            self._gauge_pages()
            return
        width = _io.pick_bucket(
            gp.decode_widths, max(len(s.pages) for _, s in active))
        sentinel = self.num_pages
        token_ids = _np.zeros((B,), _np.int32)
        positions = _np.zeros((B,), _np.int32)
        table = _np.full((B, width), sentinel, _np.int32)
        for i, s in active:
            token_ids[i] = s.tokens[-1]
            positions[i] = s.pos
            k = min(width, len(s.pages))
            table[i, :k] = s.pages[:k]
        temp, tk, tp, keys = self._sample_arrays(active, B)
        t0 = _time.perf_counter()
        try:
            self._kv, nxt = self._decode[width](
                gp._params, self._kv, token_ids, positions, table,
                temp, tk, tp, keys)
            nxt = _np.asarray(nxt)
        except BaseException as exc:  # noqa: BLE001 — pool donated away
            self._dispatch_failed(exc)
            return
        t1 = _time.perf_counter()
        if breaker is not None:
            breaker.record_success()
        _telemetry.timer("serving.decode_step_ms").observe(
            (t1 - t0) * 1e3)
        route = gp.paged_routes.get(str(width))
        if route is not None:
            # serve-side mirror of the export-time routing verdict: every
            # decode iteration that ran through the Pallas paged kernel
            # (or fell back while the kernel tier was on) is counted
            if route.get("impl") == "paged":
                _telemetry.counter("kernels.paged_attention").inc()
            elif _kernels_enabled():
                _telemetry.counter("kernels.paged_fallback").inc()
        self._count_tokens(len(active))
        for i, s in active:
            s.tokens.append(int(nxt[i]))
            s.pos += 1
            self._maybe_finish(i)

    def _sample_arrays(self, active, B):
        """Per-row sampling operands for a dispatch: active rows carry
        their request's temperature / top-k / top-p / PRNG key words;
        padding rows ride greedy with a zero key (their output is
        discarded, but every operand must still be well-formed)."""
        temp = _np.zeros((B,), _np.float32)
        tk = _np.zeros((B,), _np.int32)
        tp = _np.ones((B,), _np.float32)
        keys = _np.zeros((B, 2), _np.uint32)
        for i, s in active:
            req = s.req
            temp[i] = req.temperature
            tk[i] = req.top_k
            tp[i] = req.top_p
            keys[i] = req.key_words
        return temp, tk, tp, keys

    def _count_tokens(self, n):
        _telemetry.counter("serving.tokens_generated").inc(n)
        _telemetry.counter(
            "serving.tokens_generated.%s" % self.name).inc(n)

    def _maybe_finish(self, slot_idx):
        """Mid-flight EXIT: resolve the future and recycle the pages the
        same iteration the sequence hits EOS or its token budget."""
        slot = self._slots[slot_idx]
        req = slot.req
        done = len(slot.tokens) >= req.max_new or (
            req.eos_id is not None
            and slot.tokens[-1] == int(req.eos_id))
        if not done:
            return
        self._slots[slot_idx] = None
        with self._cond:
            self._release_pages_locked(slot)
        self._gauge_pages()
        t1 = _time.perf_counter()
        wall_ms = (t1 - req.t_submit) * 1e3
        _telemetry.timer("serving.generate_request_ms").observe(wall_ms)
        if not req.future.done():
            req.future.set_result(_np.asarray(slot.tokens, _np.int32))
            if _obs.access_log_enabled():
                _obs.log_access(
                    self.name, "ok", request_id=req.trace_id,
                    dispatch_ms=wall_ms, ttft_ms=slot.ttft_ms,
                    tokens=len(slot.tokens),
                    bytes=len(slot.tokens) * 4)
        if _telemetry.enabled():
            _telemetry.log_event(
                "serving_generate", model=self.name,
                prompt_len=req.plen, new_tokens=len(slot.tokens),
                max_new=req.max_new, pages=len(slot.pages),
                ttft_ms=round(slot.ttft_ms, 4)
                if slot.ttft_ms is not None else None,
                wall_ms=round(wall_ms, 4),
                pool_exhausted_wait=req.stall_counted,
                breaker=self.breaker.state
                if self.breaker is not None else "closed")

    def _stall_probe(self, interval_s):
        """mx.tracing stall probe (registered in :meth:`start`): reports
        the engine wedged when work is pending but the decode loop has
        not turned over within the watchdog interval.  Mirrors the
        one-shot ``Server`` probe registered in serving.py."""
        now = _time.perf_counter()
        with self._cond:
            queued = len(self._queue)
            free = len(self._free)
            last_iter = self._last_iteration
            thread = self._thread
            oldest_q = min((r.t_submit for r in self._queue),
                           default=None)
        # advisory cross-thread read of the engine-owned slot table (the
        # same precedent stats() relies on) — staleness is acceptable here
        active = self._active()
        if queued == 0 and not active:
            return None
        if now - last_iter < interval_s:
            return None
        ages = [now - s.req.t_submit for s in active]
        if oldest_q is not None:
            ages.append(now - oldest_q)
        return {
            "model": self.name,
            "queued": queued,
            "active": len(active),
            "kv_pages": self.num_pages,
            "kv_pages_free": free,
            "since_last_iteration_s": round(now - last_iter, 3),
            "engine_alive": bool(thread is not None
                                 and thread.is_alive()),
            "oldest_request_age_s": round(max(ages), 3) if ages else 0.0,
        }

    # ------------------------------------------------------------- stats
    def stats(self):
        with self._cond:
            queued = len(self._queue)
            free = len(self._free)
            thread = self._thread
            prefix_entries = len(self._prefix)
            prefix_shared = sum(
                max(0, e[1] - 1) for e in self._prefix.values())
        _telemetry.gauge(
            "serving.prefix_shared_pages.%s" % self.name).set(
            prefix_entries)
        return {
            "queued": queued,
            "active": len(self._active()),
            "decode_slots": self.decode_slots,
            "shared_prefix": self._share,
            "prefix_entries": prefix_entries,
            "prefix_pages_shared": prefix_shared,
            "kv_pages": self.num_pages,
            "kv_pages_free": free,
            "page_size": self.predictor.page_size,
            "max_context": self.predictor.max_context,
            "prompt_buckets": list(self.predictor.prompt_buckets),
            "decode_widths": list(self.predictor.decode_widths),
            "engine_alive": bool(thread is not None
                                 and thread.is_alive()),
            "breaker": self.breaker.state
            if self.breaker is not None else "closed",
        }
