"""``mx.serving`` generation engine — token-level continuous batching
over a paged device-resident KV cache.

Reference: the C predict API's stateful RNN serving
(include/mxnet/c_predict_api.h MXPredCreatePartialOut + state handles)
kept one sequence's recurrent state device-resident across calls; the
TPU-native analog generalizes that to MANY concurrent sequences sharing
one page pool, scheduled per decode ITERATION (Orca) instead of per
request, with vLLM-style block-paged KV memory so cache capacity is
pooled instead of pre-reserved per slot.

Architecture (one :class:`GenerationEngine` thread per generation model,
run under the same restart supervisor as the one-shot batcher):

  submit ──► admission check ──► FIFO ──► engine loop, per iteration:
             (bounded queue,              1. harvest expired deadlines
              breaker state)              2. admit queue head into a free
                                             decode slot IF the page pool
                                             covers prompt+max_new pages
                                             (head-of-line wait otherwise:
                                             serving.kv_pool_exhausted)
                                          3. PREFILL each new request
                                             (B=1 program at its prompt
                                             bucket) → first token (TTFT)
                                          4. one DECODE step for all
                                             active slots (B=slots
                                             program at the page-table
                                             width bucket) → next tokens
                                          5. finished sequences (EOS /
                                             max_new) resolve futures,
                                             pages recycle immediately

Key properties:

* **Flat compiles** — programs are AOT-compiled at ``start()``: one
  prefill program per prompt bucket and one decode program per
  page-table width, all at fixed batch (1 and ``decode_slots``).  Ragged
  traffic — any prompt-length mix, mid-flight exits, joins — never
  reaches the compiler (``tools/check_generation.py`` proves it).
* **Paged KV memory** — position ``t`` of a sequence lives at slot
  ``t % page_size`` of page ``table[t // page_size]``; pages come from a
  shared free list and return to it the iteration their sequence
  finishes.  The pool dimension is symbolic in the v4 artifact, so
  ``serving.kv_pages`` is a pure runtime choice.
* **Bitwise parity** — the token stream each request receives is bitwise
  equal to the eager greedy oracle
  (``models.TransformerLM.greedy_decode``) regardless of what else is in
  flight: prefill runs the exact ``apply()`` attention math and the
  decode step's masked paged attention contributes exact zeros for
  padding (kernels.paged_attention).
* **Donated pool** — the page pool is donated into every program call
  (it is the only O(pool) buffer); a dispatch failure therefore poisons
  it, so the engine fails every in-flight sequence with the causal
  error, rebuilds the pool zeroed, feeds the model's circuit breaker and
  keeps serving.
* **PR-7 fault tolerance per slot** — admission sheds past
  ``serving.max_pending`` (ServerOverloadedError), queued requests whose
  deadline lapses complete typed and never prefill
  (DeadlineExceededError), an open breaker fails submits fast
  (CircuitOpenError), and the engine thread restarts under the
  ``mx.resilience`` budget.

Telemetry: ``serving.tokens_generated[.model]`` counters,
``serving.kv_pages_in_use.<model>`` gauge, ``serving.prefill_ms`` /
``serving.decode_step_ms`` / ``serving.ttft_ms`` /
``serving.generate_request_ms`` timers,
``serving.kv_pool_exhausted[.model]`` counters, and one
``serving_generate`` JSONL record per finished request (prompt_len,
new_tokens, ttft_ms, wall_ms — ``tools/telemetry_report.py`` folds these
into per-model TTFT/tokens-per-second columns and the
``kv_pool_exhaustion`` anomaly).

Knobs (config.py): ``serving.kv_page_size`` (baked at export),
``serving.kv_pages``, ``serving.decode_slots``; docs/SERVING.md
"Generation" has the full walkthrough.
"""
from __future__ import annotations

import logging
import math as _math
import threading
import time as _time
from collections import deque
from concurrent.futures import Future

import numpy as _np

import jax

from . import config as _config
from . import io as _io
from . import obs as _obs
from . import telemetry as _telemetry
from .serving import (CircuitOpenError, DeadlineExceededError,
                      ServerOverloadedError, ServingError,
                      _access_outcome)

__all__ = ["GenerationEngine"]

_LOG = logging.getLogger("mxnet_tpu.generation")


class _EngineCrashError(OSError):
    """Internal: wraps an engine-loop crash so
    ``resilience.call_with_retry`` drives the restart backoff."""


class _GenRequest:
    """One generation request: prompt + budget + the future its token
    stream resolves, stamped for TTFT / deadline accounting."""

    __slots__ = ("prompt", "plen", "max_new", "eos_id", "future",
                 "t_submit", "deadline", "need", "stall_counted",
                 "trace_id")

    def __init__(self, prompt, max_new, eos_id, deadline_ms, need,
                 trace_id=None):
        self.prompt = prompt
        self.plen = int(prompt.shape[0])
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.future = Future()
        self.t_submit = _time.perf_counter()
        self.deadline = (self.t_submit + float(deadline_ms) * 1e-3) \
            if deadline_ms and deadline_ms > 0 else None
        self.need = int(need)          # pages for prompt + max_new
        self.stall_counted = False     # kv_pool_exhausted counted once
        self.trace_id = trace_id       # submit span id for the access log

    def expired(self, now):
        return self.deadline is not None and now >= self.deadline


class _Slot:
    """One active decode slot: the sequence's pages, cached length and
    generated tokens.  Engine-thread-only state."""

    __slots__ = ("req", "pages", "pos", "tokens", "ttft_ms")

    def __init__(self, req, pages):
        self.req = req
        self.pages = pages
        self.pos = req.plen      # tokens already in the cache
        self.tokens = []
        self.ttft_ms = None


class GenerationEngine:
    """Per-model continuous-batching generation scheduler (one thread).

    Owned by :class:`mxnet_tpu.serving.Server` (``register(...,
    generate=True)``); drives a :class:`mxnet_tpu.deploy
    .GenerationPredictor`'s prefill/decode program families over a
    shared page pool."""

    def __init__(self, name, predictor, breaker=None, num_pages=None,
                 decode_slots=None, max_pending=None,
                 default_deadline_ms=None):
        self.name = name
        self.predictor = predictor
        self.breaker = breaker
        self.num_pages = int(num_pages if num_pages is not None
                             else _config.get("serving.kv_pages"))
        self.decode_slots = int(decode_slots if decode_slots is not None
                                else _config.get("serving.decode_slots"))
        self.max_pending = int(max_pending if max_pending is not None
                               else _config.get("serving.max_pending"))
        self.default_deadline_ms = float(
            default_deadline_ms if default_deadline_ms is not None
            else _config.get("serving.default_deadline_ms"))
        psz = predictor.page_size
        # a single request may never need more pages than the pool holds
        self.max_need = min(self.num_pages,
                            _math.ceil(predictor.max_context / psz))
        if self.max_need < 1:
            raise ServingError(
                "model %r: serving.kv_pages=%d cannot hold one page"
                % (name, self.num_pages))
        # Cross-thread state (submit side vs engine thread) — the same
        # lock-discipline contract tools/mxlint.py checks on the Server.
        self._queue = deque()            # guarded-by: _cond
        self._free = list(range(self.num_pages))  # guarded-by: _cond
        self._cond = threading.Condition()
        self._started = False            # guarded-by: _cond
        self._stopping = False           # guarded-by: _cond
        self._abort = False              # guarded-by: _cond
        self._dead = None                # guarded-by: _cond — crash exc
        # last engine-loop iteration (the watchdog probe's liveness clock)
        self._last_iteration = _time.perf_counter()  # guarded-by: _cond
        self._probe_name = "serving-generate-%x" % id(self)
        # guarded-by[writes]: _cond — stop() joins outside the lock
        self._thread = None
        # Engine-thread-only state: the page pool arrays and decode slots
        # are touched exclusively by the engine loop — no lock.
        self._slots = [None] * self.decode_slots
        self._kk = None
        self._vv = None
        self._prefill = {}    # prompt bucket -> compiled program
        self._decode = {}     # page-table width -> compiled program

    # ----------------------------------------------------------- compile
    def _compile_programs(self):
        """AOT-compile the full program family: one prefill per prompt
        bucket (B=1) and one decode step per page-table width
        (B=decode_slots).  This is the ENTIRE compiled set — ragged
        generation traffic never adds to it (``serving.compiles`` stays
        equal to the family size, the check_generation.py gate)."""
        from . import perf as _perf
        from . import tracing as _tracing
        gp = self.predictor
        params = gp._params
        pspec = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), params)
        kv = gp.meta["kv"]
        pool_shape = (kv["num_layers"], self.num_pages, gp.page_size,
                      kv["num_heads"], kv["head_dim"])
        kspec = jax.ShapeDtypeStruct(pool_shape, gp.kv_dtype)
        i32 = _np.int32

        def compile_one(fn, arg_specs, label):
            t0 = _time.perf_counter()
            with _tracing.span("serving.compile", cat="serving",
                               model=self.name, program=label):
                traced = fn.trace(*arg_specs)
                t1 = _time.perf_counter()
                lowered = traced.lower()
                t2 = _time.perf_counter()
                program = lowered.compile()
                t3 = _time.perf_counter()
            _telemetry.counter("serving.compiles").inc()
            _telemetry.timer("serving.compile_ms").observe(
                (t3 - t0) * 1e3)
            _perf.register_compiled(
                "serving", "%s/%s" % (self.name, label), program,
                phases_ms={"trace_ms": (t1 - t0) * 1e3,
                           "lower_ms": (t2 - t1) * 1e3,
                           "compile_ms": (t3 - t2) * 1e3},
                dtype=str(gp.kv_dtype))
            return program

        for s_bucket in gp.prompt_buckets:
            if s_bucket in self._prefill:
                continue
            w_s = _math.ceil(s_bucket / gp.page_size)
            self._prefill[s_bucket] = compile_one(
                gp.prefill_fn(s_bucket),
                (pspec, kspec, kspec,
                 jax.ShapeDtypeStruct((1, s_bucket), i32),
                 jax.ShapeDtypeStruct((1,), i32),
                 jax.ShapeDtypeStruct((1, w_s), i32)),
                "prefill-s%d" % s_bucket)
        for width in gp.decode_widths:
            if width in self._decode:
                continue
            self._decode[width] = compile_one(
                gp.decode_fn(width),
                (pspec, kspec, kspec,
                 jax.ShapeDtypeStruct((self.decode_slots,), i32),
                 jax.ShapeDtypeStruct((self.decode_slots,), i32),
                 jax.ShapeDtypeStruct((self.decode_slots, width), i32)),
                "decode-w%d" % width)

    # --------------------------------------------------------- lifecycle
    def start(self):
        from . import tracing as _tracing
        with self._cond:
            if self._started:
                return self
        self._compile_programs()
        self._kk, self._vv = self.predictor.make_kv(self.num_pages)
        with self._cond:
            self._stopping = False
            self._abort = False
            self._dead = None
            self._started = True
            self._last_iteration = _time.perf_counter()
            self._thread = threading.Thread(
                target=_tracing.wrap_context(self._supervise), daemon=True,
                name="mx-serving-generate-%s" % self.name)
        self._thread.start()
        # the serving batcher has carried a stall probe since PR-3; the
        # generation engine gets its sibling here — KV-pool occupancy,
        # decode-loop liveness and oldest in-flight request age land in
        # the watchdog hang report
        _tracing.register_stall_probe(self._probe_name, self._stall_probe)
        return self

    def stop(self, drain=True, timeout_s=30.0):
        """Stop the engine.  With ``drain`` (default) queued requests
        prefill and every in-flight sequence runs to completion; with
        ``drain=False`` queued AND active sequences fail promptly."""
        with self._cond:
            if not self._started:
                return
            self._stopping = True
            self._abort = self._abort or not drain
            self._cond.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout_s)
            if thread.is_alive():
                _telemetry.counter("serving.stop_timeout").inc()
                _LOG.warning("serving: generation engine %r did not "
                             "drain within %.1fs", self.name, timeout_s)
        from . import tracing as _tracing
        _tracing.unregister_stall_probe(self._probe_name)
        with self._cond:
            self._started = False
            self._thread = None

    # ------------------------------------------------------------ submit
    def submit(self, prompt, max_new_tokens, eos_id=None,
               deadline_ms=None):
        """Enqueue one prompt; returns a Future resolving to the
        generated token ids (np.int32, EOS included when hit) — the
        bitwise ``greedy_decode`` stream."""
        gp = self.predictor
        prompt = _np.asarray(prompt, _np.int32).reshape(-1)
        plen = int(prompt.shape[0])
        max_new = int(max_new_tokens)
        if plen < 1 or max_new < 1:
            raise ValueError(
                "model %r: need a non-empty prompt and max_new_tokens "
                ">= 1" % (self.name,))
        if plen + max_new > gp.max_context:
            raise ValueError(
                "model %r: prompt (%d) + max_new_tokens (%d) exceeds the "
                "artifact's max_context %d"
                % (self.name, plen, max_new, gp.max_context))
        gp.prefill_bucket(plen)   # raises if no bucket fits
        need = _math.ceil((plen + max_new) / gp.page_size)
        if need > self.max_need:
            raise ValueError(
                "model %r: request needs %d KV pages but the pool holds "
                "%d (serving.kv_pages) — shorten the request or grow the "
                "pool" % (self.name, need, self.num_pages))
        _telemetry.counter("serving.requests").inc()
        # the enclosing serving.submit span's trace_id (None when tracing
        # is off) rides the request so its access record joins the trace
        from . import tracing as _tracing
        sp = _tracing.current_span()
        trace_id = sp.trace_id if sp is not None else None
        breaker = self.breaker
        if breaker is not None and breaker.rejects_submit():
            _telemetry.counter("serving.breaker_rejected").inc()
            _obs.log_access(self.name, "breaker", request_id=trace_id)
            raise CircuitOpenError(
                "model %r circuit breaker is OPEN after %d consecutive "
                "dispatch failure(s); failing fast for %.0fms more"
                % (self.name, breaker.failures,
                   breaker.cooldown_remaining_ms()))
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        req = _GenRequest(prompt, max_new, eos_id,
                          float(deadline_ms or 0.0), need,
                          trace_id=trace_id)
        with self._cond:
            if self._dead is not None:
                exc = self._dead
                raise ServingError(
                    "generation engine for model %r crashed (%s: %s) and "
                    "exhausted its restart budget; submit rejected"
                    % (self.name, type(exc).__name__, exc))
            if self._stopping or not self._started:
                raise ServingError(
                    "generation engine for model %r is %s; submit "
                    "rejected" % (self.name, "stopping" if self._stopping
                                  else "not started"))
            if self.max_pending > 0 \
                    and len(self._queue) >= self.max_pending:
                shed = True
            else:
                shed = False
                self._queue.append(req)
                self._cond.notify_all()
        if shed:
            _telemetry.counter("serving.shed_requests").inc()
            _telemetry.counter(
                "serving.shed_requests.%s" % self.name).inc()
            _obs.log_access(self.name, "shed", request_id=trace_id)
            raise ServerOverloadedError(
                "generation queue for model %r is at serving.max_pending"
                "=%d; request shed — back off and retry"
                % (self.name, self.max_pending))
        return req.future

    # ----------------------------------------------------------- the loop
    def _supervise(self):
        from . import resilience as _resilience
        try:
            _resilience.call_with_retry(self._run_engine,
                                        kind="serving_batcher")
        except BaseException as exc:  # noqa: BLE001 — budget exhausted
            cause = exc.__cause__ if exc.__cause__ is not None else exc
            with self._cond:
                self._dead = cause
                queued = list(self._queue)
                self._queue.clear()
                self._cond.notify_all()
            self._fail_all(queued, cause)
            _LOG.error(
                "serving: generation engine %r crashed and exhausted its "
                "restart budget (%s: %s); submits now fail fast",
                self.name, type(cause).__name__, cause)

    def _run_engine(self):
        try:
            self._loop()
        except BaseException as exc:  # noqa: BLE001 — supervised crash
            _telemetry.counter("serving.batcher_crashes").inc()
            with self._cond:
                queued = list(self._queue)
                self._queue.clear()
            self._fail_all(queued, exc)
            self._fail_active(exc)
            _LOG.warning(
                "serving: generation engine %r crashed (%s: %s); "
                "restarting under the resilience retry budget",
                self.name, type(exc).__name__, exc)
            raise _EngineCrashError(
                "generation engine crashed: %s: %s"
                % (type(exc).__name__, exc)) from exc

    def _active(self):
        return [s for s in self._slots if s is not None]

    def _fail_all(self, reqs, exc):
        outcome = _access_outcome(exc)
        err = ("%s: %s" % (type(exc).__name__, exc)
               if outcome == "error" else None)
        for req in reqs:
            if not req.future.done():
                req.future.set_exception(exc)
                if _obs.access_log_enabled():
                    _obs.log_access(
                        self.name, outcome, request_id=req.trace_id,
                        queue_ms=(_time.perf_counter() - req.t_submit)
                        * 1e3, error=err)

    def _fail_active(self, exc):
        """Fail every in-flight sequence and recycle its pages (the pool
        arrays were donated into the failed dispatch, so their state is
        gone — rebuild zeroed)."""
        freed = []
        outcome = _access_outcome(exc)
        err = ("%s: %s" % (type(exc).__name__, exc)
               if outcome == "error" else None)
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            self._slots[i] = None
            freed.extend(slot.pages)
            if not slot.req.future.done():
                slot.req.future.set_exception(exc)
                if _obs.access_log_enabled():
                    _obs.log_access(
                        self.name, outcome,
                        request_id=slot.req.trace_id,
                        ttft_ms=slot.ttft_ms,
                        tokens=len(slot.tokens), error=err)
        if freed:
            with self._cond:
                self._free.extend(freed)
                self._cond.notify_all()
        self._gauge_pages()
        self._kk, self._vv = self.predictor.make_kv(self.num_pages)

    def _gauge_pages(self):
        with self._cond:
            in_use = self.num_pages - len(self._free)
        _telemetry.gauge(
            "serving.kv_pages_in_use.%s" % self.name).set(in_use)

    def _harvest_expired_locked(self, now):  # mxlint: holds(_cond)
        dead = [r for r in self._queue if r.expired(now)]
        for req in dead:
            self._queue.remove(req)
        return dead

    def _admit_locked(self, now):  # mxlint: holds(_cond)
        """Pop queue-head requests into free slots while the page pool
        covers them.  FIFO: a head request the pool cannot cover BLOCKS
        later ones (no starvation of long requests) and counts one
        ``serving.kv_pool_exhausted`` per stall episode."""
        admitted = []
        free_slots = [i for i, s in enumerate(self._slots) if s is None]
        while self._queue and free_slots:
            req = self._queue[0]
            if req.need > len(self._free):
                if not req.stall_counted:
                    req.stall_counted = True
                    _telemetry.counter("serving.kv_pool_exhausted").inc()
                    _telemetry.counter(
                        "serving.kv_pool_exhausted.%s" % self.name).inc()
                break
            self._queue.popleft()
            pages = [self._free.pop() for _ in range(req.need)]
            self._slots[free_slots.pop(0)] = _Slot(req, pages)
            admitted.append(req)
        return admitted

    def _loop(self):
        while True:
            now = _time.perf_counter()
            with self._cond:
                self._last_iteration = now
                expired = self._harvest_expired_locked(now)
                admitted = self._admit_locked(now)
                active = self._active()
                if not admitted and not active:
                    if self._stopping and (self._abort
                                           or not self._queue):
                        queued = list(self._queue)
                        self._queue.clear()
                        abort = self._abort
                    else:
                        self._cond.wait(timeout=0.05)
                        queued = None
                        abort = False
                else:
                    queued = None
                    abort = False
            self._expire(expired)
            if queued is not None:
                if abort:
                    self._fail_all(queued, ServingError(
                        "generation engine stopped without drain"))
                return
            if not admitted and not active:
                continue
            with self._cond:
                abort = self._abort
            if abort:
                with self._cond:
                    queued = list(self._queue)
                    self._queue.clear()
                exc = ServingError(
                    "generation engine stopped without drain")
                self._fail_all(queued, exc)
                self._fail_active(exc)
                return
            self._gauge_pages()
            ok = True
            for req in admitted:
                if not self._dispatch_prefill(req):
                    ok = False
                    break
            if ok and self._active():
                self._dispatch_decode()

    def _expire(self, reqs):
        for req in reqs:
            _telemetry.counter("serving.deadline_exceeded").inc()
            _telemetry.counter(
                "serving.deadline_exceeded.%s" % self.name).inc()
            if not req.future.done():
                queued_ms = (_time.perf_counter() - req.t_submit) * 1e3
                req.future.set_exception(DeadlineExceededError(
                    "generation request for model %r expired in queue "
                    "before prefill (queued %.1fms, deadline passed)"
                    % (self.name, queued_ms)))
                _obs.log_access(self.name, "deadline",
                                request_id=req.trace_id,
                                queue_ms=queued_ms)

    def _dispatch_failed(self, exc):
        """Shared failure path: the donated pool is poisoned, so every
        in-flight sequence fails with the causal error and the breaker
        records the failure.  Returns False for the caller to bail."""
        _telemetry.counter("serving.dispatch_errors").inc()
        if self.breaker is not None:
            self.breaker.record_failure()
        self._fail_active(exc)
        return False

    def _dispatch_prefill(self, req):
        """Run one admitted request's prompt through its bucket's prefill
        program: seeds the shared pool (scatter touches only this
        request's pages, so in-flight sequences are untouched — the
        mid-flight JOIN) and produces the first token (TTFT)."""
        gp = self.predictor
        slot_idx = next(i for i, s in enumerate(self._slots)
                        if s is not None and s.req is req)
        slot = self._slots[slot_idx]
        breaker = self.breaker
        if breaker is not None and not breaker.allow_dispatch():
            self._slots[slot_idx] = None
            with self._cond:
                self._free.extend(slot.pages)
                self._cond.notify_all()
            if not req.future.done():
                req.future.set_exception(CircuitOpenError(
                    "model %r circuit breaker is OPEN; prefill failed "
                    "fast, retry after the cooldown" % (self.name,)))
                _obs.log_access(
                    self.name, "breaker", request_id=req.trace_id,
                    queue_ms=(_time.perf_counter() - req.t_submit) * 1e3)
            return True   # engine itself is fine
        s_bucket = gp.prefill_bucket(req.plen)
        w_s = _math.ceil(s_bucket / gp.page_size)
        sentinel = self.num_pages
        tokens = _np.zeros((1, s_bucket), _np.int32)
        tokens[0, :req.plen] = req.prompt
        table = _np.full((1, w_s), sentinel, _np.int32)
        k = min(w_s, len(slot.pages))
        table[0, :k] = slot.pages[:k]
        t0 = _time.perf_counter()
        try:
            self._kk, self._vv, nxt = self._prefill[s_bucket](
                gp._params, self._kk, self._vv, tokens,
                _np.asarray([req.plen], _np.int32), table)
            first = int(nxt[0])
        except BaseException as exc:  # noqa: BLE001 — pool donated away
            return self._dispatch_failed(exc)
        t1 = _time.perf_counter()
        if breaker is not None:
            breaker.record_success()
        slot.tokens.append(first)
        slot.ttft_ms = (t1 - req.t_submit) * 1e3
        _telemetry.timer("serving.prefill_ms").observe((t1 - t0) * 1e3)
        _telemetry.timer("serving.ttft_ms").observe(slot.ttft_ms)
        self._count_tokens(1)
        self._maybe_finish(slot_idx)
        return True

    def _dispatch_decode(self):
        """One decode iteration for every active slot.  The page-table
        width buckets to the widest need among active sequences; inactive
        slots ride along on the all-sentinel row (writes drop, output
        ignored) — that is what keeps the compiled set flat while
        sequences EXIT and JOIN mid-flight."""
        gp = self.predictor
        B = self.decode_slots
        active = [(i, s) for i, s in enumerate(self._slots)
                  if s is not None]
        breaker = self.breaker
        if breaker is not None and not breaker.allow_dispatch():
            exc = CircuitOpenError(
                "model %r circuit breaker is OPEN; in-flight decode "
                "failed fast, retry after the cooldown" % (self.name,))
            for i, _ in active:
                self._slots[i] = None
            freed = []
            for _, s in active:
                freed.extend(s.pages)
                if not s.req.future.done():
                    s.req.future.set_exception(exc)
                    _obs.log_access(
                        self.name, "breaker", request_id=s.req.trace_id,
                        ttft_ms=s.ttft_ms, tokens=len(s.tokens))
            with self._cond:
                self._free.extend(freed)
                self._cond.notify_all()
            self._gauge_pages()
            return
        width = _io.pick_bucket(
            gp.decode_widths, max(len(s.pages) for _, s in active))
        sentinel = self.num_pages
        token_ids = _np.zeros((B,), _np.int32)
        positions = _np.zeros((B,), _np.int32)
        table = _np.full((B, width), sentinel, _np.int32)
        for i, s in active:
            token_ids[i] = s.tokens[-1]
            positions[i] = s.pos
            k = min(width, len(s.pages))
            table[i, :k] = s.pages[:k]
        t0 = _time.perf_counter()
        try:
            self._kk, self._vv, nxt = self._decode[width](
                gp._params, self._kk, self._vv, token_ids, positions,
                table)
            nxt = _np.asarray(nxt)
        except BaseException as exc:  # noqa: BLE001 — pool donated away
            self._dispatch_failed(exc)
            return
        t1 = _time.perf_counter()
        if breaker is not None:
            breaker.record_success()
        _telemetry.timer("serving.decode_step_ms").observe(
            (t1 - t0) * 1e3)
        self._count_tokens(len(active))
        for i, s in active:
            s.tokens.append(int(nxt[i]))
            s.pos += 1
            self._maybe_finish(i)

    def _count_tokens(self, n):
        _telemetry.counter("serving.tokens_generated").inc(n)
        _telemetry.counter(
            "serving.tokens_generated.%s" % self.name).inc(n)

    def _maybe_finish(self, slot_idx):
        """Mid-flight EXIT: resolve the future and recycle the pages the
        same iteration the sequence hits EOS or its token budget."""
        slot = self._slots[slot_idx]
        req = slot.req
        done = len(slot.tokens) >= req.max_new or (
            req.eos_id is not None
            and slot.tokens[-1] == int(req.eos_id))
        if not done:
            return
        self._slots[slot_idx] = None
        with self._cond:
            self._free.extend(slot.pages)
            self._cond.notify_all()
        self._gauge_pages()
        t1 = _time.perf_counter()
        wall_ms = (t1 - req.t_submit) * 1e3
        _telemetry.timer("serving.generate_request_ms").observe(wall_ms)
        if not req.future.done():
            req.future.set_result(_np.asarray(slot.tokens, _np.int32))
            if _obs.access_log_enabled():
                _obs.log_access(
                    self.name, "ok", request_id=req.trace_id,
                    dispatch_ms=wall_ms, ttft_ms=slot.ttft_ms,
                    tokens=len(slot.tokens),
                    bytes=len(slot.tokens) * 4)
        if _telemetry.enabled():
            _telemetry.log_event(
                "serving_generate", model=self.name,
                prompt_len=req.plen, new_tokens=len(slot.tokens),
                max_new=req.max_new, pages=len(slot.pages),
                ttft_ms=round(slot.ttft_ms, 4)
                if slot.ttft_ms is not None else None,
                wall_ms=round(wall_ms, 4),
                pool_exhausted_wait=req.stall_counted,
                breaker=self.breaker.state
                if self.breaker is not None else "closed")

    def _stall_probe(self, interval_s):
        """mx.tracing stall probe (registered in :meth:`start`): reports
        the engine wedged when work is pending but the decode loop has
        not turned over within the watchdog interval.  Mirrors the
        one-shot ``Server`` probe registered in serving.py."""
        now = _time.perf_counter()
        with self._cond:
            queued = len(self._queue)
            free = len(self._free)
            last_iter = self._last_iteration
            thread = self._thread
            oldest_q = min((r.t_submit for r in self._queue),
                           default=None)
        # advisory cross-thread read of the engine-owned slot table (the
        # same precedent stats() relies on) — staleness is acceptable here
        active = self._active()
        if queued == 0 and not active:
            return None
        if now - last_iter < interval_s:
            return None
        ages = [now - s.req.t_submit for s in active]
        if oldest_q is not None:
            ages.append(now - oldest_q)
        return {
            "model": self.name,
            "queued": queued,
            "active": len(active),
            "kv_pages": self.num_pages,
            "kv_pages_free": free,
            "since_last_iteration_s": round(now - last_iter, 3),
            "engine_alive": bool(thread is not None
                                 and thread.is_alive()),
            "oldest_request_age_s": round(max(ages), 3) if ages else 0.0,
        }

    # ------------------------------------------------------------- stats
    def stats(self):
        with self._cond:
            queued = len(self._queue)
            free = len(self._free)
            thread = self._thread
        return {
            "queued": queued,
            "active": len(self._active()),
            "decode_slots": self.decode_slots,
            "kv_pages": self.num_pages,
            "kv_pages_free": free,
            "page_size": self.predictor.page_size,
            "max_context": self.predictor.max_context,
            "prompt_buckets": list(self.predictor.prompt_buckets),
            "decode_widths": list(self.predictor.decode_widths),
            "engine_alive": bool(thread is not None
                                 and thread.is_alive()),
            "breaker": self.breaker.state
            if self.breaker is not None else "closed",
        }
