"""``mx.executor`` — facade module (reference: python/mxnet/executor.py).

The Executor class itself lives with the symbol layer (one jit-specialized
program per shape signature, mxnet_tpu/symbol/symbol.py); this module keeps
the reference import path working."""
from .symbol.symbol import Executor  # noqa: F401

__all__ = ["Executor"]
