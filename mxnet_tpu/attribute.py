"""Attribute scoping for symbols (reference: python/mxnet/attribute.py
AttrScope — annotates every symbol created inside the scope, the mechanism
behind ctx_group model-parallel placement and lr_mult/wd_mult hints)."""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]


class AttrScope:
    """Context manager that stamps its attributes onto every Symbol op node
    created within (stored in the node's annotation map, queryable via
    Symbol.attr / attr_dict)."""

    _state = threading.local()

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("attributes must be strings (reference "
                                 "AttrScope contract)")
        self._attr = kwargs

    @classmethod
    def _stack(cls):
        if not hasattr(cls._state, "stack"):
            cls._state.stack = []
        return cls._state.stack

    @classmethod
    def current_attrs(cls):
        merged = {}
        for scope in cls._stack():
            merged.update(scope._attr)
        return merged

    def get(self, attr=None):
        """THIS scope's attrs as defaults; EXPLICIT attrs win (reference
        AttrScope.get: ret = self._attr.copy(); ret.update(attr)).  The
        ambient stack is deliberately not consulted — that merge belongs
        to symbol creation (current_attrs), not to reading one scope."""
        merged = dict(self._attr)
        if attr:
            merged.update(attr)
        return merged

    def __enter__(self):
        self._stack().append(self)
        return self

    def __exit__(self, *exc):
        self._stack().pop()


def current():
    return AttrScope.current_attrs()
