"""``mx.serving`` — continuous-batching inference over the StableHLO
export path.

Reference deployment story: the C predict API served one process-local
model per handle (include/mxnet/c_predict_api.h) and TensorRT subgraph
serving owned the batched GPU path (SURVEY §2, §5).  The TPU-native analog
is a REQUEST QUEUE in front of the ``mx.deploy`` artifact: concurrent
``submit()`` calls coalesce into batches padded up to the shared
``io.pad_buckets`` bucket set, so a SMALL, FIXED family of AOT-compiled
programs (one per ``(model, bucket)``) serves every request size — the
same pad-bucket policy the PR-5 input pipeline uses to keep training
compiles flat now keeps serving compiles flat.

Architecture (one background batcher thread per :class:`Server`, run
under a restart supervisor):

  submit(name, x) ──► admission check ──► per-server FIFO ──► batcher:
                      (bounded queue,                          take first request
                       breaker state)                          reap expired deadlines
                                                               coalesce same-model requests
                                                                 until rows == max_batch or
                                                                 max_queue_delay_ms elapses
                                                               concat + wrap-pad → bucket
                                                               AOT program(params, batch)
                                                               scatter rows → caller futures

Key properties:

  * **Bitwise-stable batching** — each output row of a bucketed dispatch
    equals the row the unbatched ``StableHLOPredictor.predict`` produces
    (row-independent inference math; ``tools/check_serving.py`` proves it
    under concurrent ragged traffic, ``tools/check_serving_chaos.py``
    under injected faults).
  * **Zero steady-state compiles** — every ``(model, bucket)`` program is
    compiled eagerly at :meth:`Server.start`; ragged request sizes never
    reach the compiler.  ``serving.compile_cache_dir`` wires jax's
    persistent compilation cache so a RESTARTED server skips even those
    (near-zero cold start).
  * **Fail-fast under overload** — the pending queue is bounded
    (``serving.max_pending``): a submit past the bound raises a retryable
    :class:`ServerOverloadedError` instead of queuing until memory dies.
  * **Deadlines** — ``submit(name, x, deadline_ms=...)`` (default from
    ``serving.default_deadline_ms``): a request still queued past its
    deadline completes with :class:`DeadlineExceededError` at
    batch-formation time and is NEVER dispatched — no compute is spent on
    answers nobody is waiting for.  ``predict(timeout=...)`` cancels its
    queued request on timeout the same way.
  * **Failure isolation** — a per-model circuit breaker opens after K
    consecutive dispatch failures (``serving.breaker_threshold``),
    fails that model's submits fast with :class:`CircuitOpenError` while
    other models keep serving, then goes half-open after the cooldown and
    probes with a single batch (success closes it, failure re-opens).
  * **Batcher supervision** — an unexpected batcher crash fails every
    pending future with the causal exception, bumps
    ``serving.batcher_crashes``, and restarts the loop under the
    ``mx.resilience`` retry budget/backoff; once the budget is exhausted
    submits fail fast instead of hanging.  The PR-3 watchdog carries a
    serving stall probe (``tracing.register_stall_probe``) that
    flight-records open requests and breaker state whenever the queue is
    non-empty but no dispatch completed within the watchdog interval.
  * **Device-resident params** — uploaded once at ``register()`` (by the
    underlying :class:`~mxnet_tpu.deploy.StableHLOPredictor`), never per
    request.
  * **Multi-model** — a bounded LRU table of registered models; the least
    recently used model (programs + device params) is evicted when
    ``max_models`` is exceeded.
  * **Quantized models** — ``register(name, prefix, quantized=True)``
    serves an int8 deploy-v3 artifact (``mx.quantization``): int8 params
    stage once, the int8 program AOT-compiles per bucket exactly like
    fp32 (compiles stay flat), ``serving.quantized_dispatches`` counts
    its batches and the ``quantized`` flag rides ``stats()`` and every
    per-dispatch JSONL record (docs/QUANTIZATION.md).
  * **Telemetry** — ``serving.requests`` / ``serving.batch_dispatches`` /
    ``serving.compiles`` / ``serving.shed_requests[.model]`` /
    ``serving.deadline_exceeded[.model]`` / ``serving.breaker_open
    [.model]`` / ``serving.batcher_crashes`` counters, a
    ``serving.breaker_state.<model>`` gauge (0 closed / 1 half-open / 2
    open), ``serving.queue_delay_ms`` / ``serving.batch_fill`` /
    ``serving.dispatch_ms`` / ``serving.request_ms`` timer histograms,
    one ``serving`` JSONL record per dispatch on the telemetry sink
    (now carrying shed/deadline/breaker state for
    ``tools/telemetry_report.py``'s overload anomaly), and
    ``serving.submit`` / ``serving.dispatch`` spans with cross-thread
    parentage (the batcher runs under ``tracing.wrap_context``, the
    ``io.prefetch`` pattern).

Deterministic chaos: the ``serving_dispatch`` (fail a dispatch) and
``serving_slow`` (delay a dispatch) fault kinds plug into the shared
``MXNET_TPU_FAULTS`` harness, so every failure path above is scriptable —
``tools/check_serving_chaos.py`` proves shed counts, deadline counts,
breaker transitions and crash-restart bitwise-deterministically in <5s.

Knobs (config.py): ``serving.max_batch`` (MXNET_TPU_SERVING_MAX_BATCH),
``serving.max_queue_delay_ms`` (MXNET_TPU_SERVING_MAX_QUEUE_DELAY_MS),
``serving.compile_cache_dir`` (MXNET_TPU_SERVING_COMPILE_CACHE_DIR),
``serving.max_pending`` (MXNET_TPU_SERVING_MAX_PENDING),
``serving.default_deadline_ms`` (MXNET_TPU_SERVING_DEFAULT_DEADLINE_MS),
``serving.breaker_threshold`` / ``serving.breaker_cooldown_ms``; the
bucket POLICY is the shared ``io.pad_buckets`` knob.  docs/SERVING.md has
the full architecture + fault-tolerance note.
"""
from __future__ import annotations

import logging
import threading
import time as _time
from collections import OrderedDict, deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout

import numpy as _np

import jax

from . import config as _config
from . import io as _io
from . import obs as _obs
from . import telemetry as _telemetry

__all__ = ["Server", "ServingError", "ServerOverloadedError",
           "DeadlineExceededError", "CircuitOpenError", "load_server"]

_LOG = logging.getLogger("mxnet_tpu.serving")

#: sleep injected by the ``serving_slow`` fault kind: long enough to trip a
#: sub-second watchdog interval and make shed/deadline schedules
#: deterministic, short enough that chaos smokes stay under their budget.
_SLOW_DISPATCH_S = 0.25


class ServingError(RuntimeError):
    """Raised for serving lifecycle errors (stopped server, evicted or
    unknown model, oversized request on a fixed-batch artifact, dead
    batcher)."""


class ServerOverloadedError(ServingError, OSError):
    """The pending queue is at ``serving.max_pending``: the request was
    shed instead of queued.  Subclasses OSError so
    ``resilience.call_with_retry`` treats it as retryable — back off and
    resubmit."""


class DeadlineExceededError(ServingError):
    """The request's deadline expired while it was still queued: it was
    completed with this error at batch-formation time and never
    dispatched (or cancelled by ``predict(timeout=...)``)."""


class CircuitOpenError(ServingError, OSError):
    """The model's circuit breaker is open after consecutive dispatch
    failures: failing fast instead of queuing onto a broken model.
    Retryable (OSError subclass) — the breaker goes half-open after its
    cooldown and probes with a single batch."""


class _BatcherCrashError(OSError):
    """Internal: wraps an arbitrary batcher-loop crash so
    ``resilience.call_with_retry`` (which retries OSError) drives the
    restart backoff and bounds the restart budget."""


def _access_outcome(exc):
    """Map a request-terminal exception to its access-log outcome (the
    mx.obs vocabulary: ok|shed|deadline|breaker|error)."""
    if isinstance(exc, CircuitOpenError):
        return "breaker"
    if isinstance(exc, DeadlineExceededError):
        return "deadline"
    if isinstance(exc, ServerOverloadedError):
        return "shed"
    return "error"


class _Request:
    """One caller request: host-side rows plus the future its output rows
    resolve, stamped with the submit time for queue-delay accounting, an
    optional absolute deadline, and the submit span's trace_id so the
    mx.obs access-log record joins against the Chrome trace."""

    __slots__ = ("model", "data", "rows", "future", "t_submit", "deadline",
                 "trace_id")

    def __init__(self, model, data, future, deadline_ms=0.0,
                 trace_id=None):
        self.model = model
        self.data = data
        self.rows = int(data.shape[0])
        self.future = future
        self.t_submit = _time.perf_counter()
        self.deadline = (self.t_submit + float(deadline_ms) * 1e-3) \
            if deadline_ms and deadline_ms > 0 else None
        self.trace_id = trace_id

    def expired(self, now=None):
        if self.deadline is None:
            return False
        return (now if now is not None else _time.perf_counter()) \
            >= self.deadline


_BREAKER_STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


class _Breaker:
    """Per-model circuit breaker: ``closed`` → ``open`` after
    ``threshold`` consecutive dispatch failures → ``half_open`` once the
    cooldown elapses (ONE probe batch goes through) → ``closed`` on probe
    success / back to ``open`` on probe failure.  ``threshold <= 0``
    disables the breaker (every check short-circuits)."""

    __slots__ = ("model", "threshold", "cooldown_s", "state", "failures",
                 "opened_at", "_lock")

    def __init__(self, model, threshold, cooldown_s):
        self.model = model
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        # Reads on the submit fast path are deliberately lock-free (a
        # stale read only delays a fast-fail by one batch), so only the
        # writes are lock-checked.
        self.state = "closed"    # guarded-by[writes]: _lock
        self.failures = 0        # guarded-by[writes]: _lock
        self.opened_at = 0.0     # guarded-by[writes]: _lock
        self._lock = threading.Lock()

    def _set_state(self, state):  # mxlint: holds(_lock)
        self.state = state
        _telemetry.gauge("serving.breaker_state.%s" % self.model).set(
            _BREAKER_STATE_VALUE[state])

    def cooldown_remaining_ms(self):
        return max(0.0, (self.cooldown_s
                         - (_time.perf_counter() - self.opened_at))) * 1e3

    def rejects_submit(self):
        """Fast-fail check on the submit path: only while OPEN and still
        inside the cooldown.  Once the cooldown elapses submits are
        accepted again — they feed the half-open probe."""
        if self.threshold <= 0 or self.state != "open":
            return False
        return _time.perf_counter() - self.opened_at < self.cooldown_s

    def allow_dispatch(self):
        """Dispatch-side gate: closed/half-open batches dispatch; an open
        breaker whose cooldown elapsed transitions to half-open and lets
        this ONE batch through as the probe."""
        if self.threshold <= 0:
            return True
        with self._lock:
            if self.state != "open":
                return True
            if _time.perf_counter() - self.opened_at < self.cooldown_s:
                return False
            self._set_state("half_open")
        _LOG.info("serving: breaker for model %r half-open after %.0fms "
                  "cooldown; probing with one batch",
                  self.model, self.cooldown_s * 1e3)
        return True

    def record_success(self):
        if self.threshold <= 0:
            return
        with self._lock:
            closing = self.state != "closed"
            self.failures = 0
            if closing:
                self._set_state("closed")
        if closing:
            _LOG.info("serving: breaker for model %r closed after a "
                      "successful probe", self.model)

    def record_failure(self):
        if self.threshold <= 0:
            return
        with self._lock:
            if self.state == "half_open":
                # the probe failed: straight back to open, fresh cooldown
                self.failures += 1
                self.opened_at = _time.perf_counter()
                self._set_state("open")
                opened = True
            else:
                self.failures += 1
                opened = self.state == "closed" \
                    and self.failures >= self.threshold
                if opened:
                    self.opened_at = _time.perf_counter()
                    self._set_state("open")
        if opened:
            _telemetry.counter("serving.breaker_open").inc()
            _telemetry.counter("serving.breaker_open.%s" % self.model).inc()
            try:
                from . import tracing as _tracing
                _tracing.record_event(
                    "serving", "breaker_open", model=self.model,
                    failures=self.failures)
            except Exception:  # noqa: BLE001 — telemetry must not break it
                pass
            _LOG.warning(
                "serving: breaker for model %r OPEN after %d consecutive "
                "dispatch failure(s); failing fast for %.0fms",
                self.model, self.failures, self.cooldown_s * 1e3)


class _ModelEntry:
    """A registered model: reloaded artifact, device-resident params, the
    per-bucket AOT program table, plus its breaker and fault-tolerance
    tallies (cumulative shed / deadline-expired requests)."""

    __slots__ = ("name", "prefix", "predictor", "buckets", "programs",
                 "item_shape", "in_dtype", "breaker", "shed",
                 "deadline_exceeded", "quantized", "cost_per_item",
                 "drift_call", "drift_sites", "drift_count", "drift_ewma")

    def __init__(self, name, prefix, predictor, buckets):
        self.name = name
        self.prefix = prefix
        self.predictor = predictor
        self.quantized = bool(getattr(predictor, "quantized", False))
        # quantization drift probe (docs/OBSERVABILITY.md): the stats
        # twin exported next to the int8 program, lazily loaded on the
        # first sampled dispatch; False = tried and absent
        self.drift_call = None
        meta = getattr(predictor, "meta", None) or {}
        self.drift_sites = tuple(meta.get("stats_sites") or ())
        self.drift_count = 0
        self.drift_ewma = {}
        self.buckets = tuple(buckets)
        self.programs = {}
        shape = predictor.meta.get("input_shape") or []
        self.item_shape = tuple(int(s) for s in shape[1:])
        self.in_dtype = _np.dtype(predictor.meta.get("input_dtype",
                                                     "float32"))
        self.breaker = None   # assigned by Server.register
        self.shed = 0
        self.deadline_exceeded = 0
        self.cost_per_item = None  # set by _compile from cost_analysis

    @property
    def capacity(self):
        return self.buckets[-1]


_CACHE_DIR_APPLIED = [None]


def _configure_compile_cache():
    """Wire jax's persistent compilation cache from the
    ``serving.compile_cache_dir`` knob (idempotent).  With the cache dir
    set, a restarted server's eager ``start()`` compiles hit disk instead
    of XLA — the near-zero cold-start contract."""
    cache_dir = (_config.get("serving.compile_cache_dir") or "").strip()
    if not cache_dir:
        return False
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # serving programs are small and fast-compiling on CPU; without these
    # floors the cache would skip exactly the programs we want to persist
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    if _CACHE_DIR_APPLIED[0] != cache_dir:
        # jax initializes its cache object on the FIRST compile of the
        # process; a dir set after that (the common case — params staged
        # and models warmed before start()) is silently ignored until the
        # cache is re-initialized
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — older jax: dir applies lazily
            pass
        _CACHE_DIR_APPLIED[0] = cache_dir
    return True


class Server:
    """Continuous-batching inference server over ``mx.deploy`` artifacts.

    Usage::

        srv = mx.serving.Server(max_batch=32, max_queue_delay_ms=2.0)
        srv.register("resnet", "/models/resnet50")   # params → device
        srv.start()                                  # AOT-compile buckets
        fut = srv.submit("resnet", batch_of_images)  # any request size
        probs = fut.result()                         # host numpy rows
        srv.stop()                                   # graceful drain

    ``submit`` is thread-safe; requests from any number of caller threads
    coalesce into bucketed batches on the single batcher thread.  Requests
    larger than the biggest bucket are transparently split into chunks and
    their outputs re-concatenated.  ``Server`` is also a context manager
    (``with Server() as srv: ...`` starts and drains it).

    Fault tolerance (docs/SERVING.md): submits past ``max_pending`` shed
    with :class:`ServerOverloadedError`; ``submit(deadline_ms=...)``
    requests that expire in queue complete with
    :class:`DeadlineExceededError` and never dispatch; a per-model
    breaker fails a broken model fast (:class:`CircuitOpenError`) while
    other models keep serving; and the batcher thread is supervised —
    a crash fails pending futures with the causal exception and restarts
    the loop under the ``mx.resilience`` retry budget.
    """

    def __init__(self, max_batch=None, max_queue_delay_ms=None,
                 buckets=None, max_models=8, max_pending=None,
                 default_deadline_ms=None, breaker_threshold=None,
                 breaker_cooldown_ms=None):
        if max_batch is None:
            max_batch = _config.get("serving.max_batch")
        if max_queue_delay_ms is None:
            max_queue_delay_ms = _config.get("serving.max_queue_delay_ms")
        if buckets is None:
            buckets = _config.get("io.pad_buckets")
        if max_pending is None:
            max_pending = _config.get("serving.max_pending")
        if default_deadline_ms is None:
            default_deadline_ms = _config.get("serving.default_deadline_ms")
        if breaker_threshold is None:
            breaker_threshold = _config.get("serving.breaker_threshold")
        if breaker_cooldown_ms is None:
            breaker_cooldown_ms = _config.get("serving.breaker_cooldown_ms")
        self.max_batch = int(max_batch)
        self.max_queue_delay_ms = float(max_queue_delay_ms)
        self._bucket_policy = buckets
        self.max_models = int(max_models)
        self.max_pending = int(max_pending)
        self.default_deadline_ms = float(default_deadline_ms)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_ms = float(breaker_cooldown_ms)
        # Cross-thread state below is lock-checked by tools/mxlint.py
        # (docs/ANALYSIS.md): every access must hold _cond unless the
        # annotation says writes-only.
        self._models = OrderedDict()     # guarded-by: _cond — _ModelEntry, LRU order
        self._generation = {}            # guarded-by: _cond — GenerationEngine per model
        self._pending = deque()          # guarded-by: _cond
        self._cond = threading.Condition()
        # guarded-by[writes]: _cond — stop() joins outside the lock
        self._thread = None
        self._leaked_thread = None       # batcher that missed stop()'s join
        self._batcher_dead = None        # guarded-by: _cond — exc once restarts exhaust
        self._started = False            # guarded-by: _cond
        self._stopping = False           # guarded-by: _cond
        self._last_dispatch_done = _time.perf_counter()  # guarded-by: _cond
        self._probe_name = "serving-%x" % id(self)

    # ------------------------------------------------------------ models
    def _policy_buckets(self, cap):
        sizes = _io.bucket_sizes(self._bucket_policy, cap)
        # serving must always have at least one compiled shape; policy
        # 'off' (natural shapes) degenerates to the single full bucket
        return sizes or (cap,)

    def register(self, name, prefix, quantized=False, generate=False):
        """Load the ``mx.deploy`` artifact at ``prefix`` under ``name``:
        params go device-resident now; bucket programs compile now if the
        server is already started (else at :meth:`start`).  Re-registering
        a name replaces the entry (and resets its breaker).  The table is
        LRU-bounded at ``max_models`` — registering past it evicts the
        least recently used model (its programs and device params become
        collectable).

        ``quantized=True`` registers an int8 (deploy format v3) artifact
        written by ``mx.quantization.export_quantized``: its int8 bucket
        programs AOT-compile exactly like fp32 ones (``serving.compiles``
        stays == bucket count under ragged traffic, persistent compile
        cache included) and the model is flagged ``quantized`` in
        :meth:`stats` and every per-dispatch JSONL record.  The flag must
        match the artifact — a v3 artifact without it (or an fp32
        artifact with it) raises, so int8 numerics are always explicit.

        ``generate=True`` registers a GENERATION (deploy format v4)
        artifact written by ``deploy.export_generation``: instead of
        joining the one-shot batcher, the model gets its own
        :class:`~mxnet_tpu.generation.GenerationEngine` — a per-iteration
        continuous-batching scheduler over a paged device-resident KV
        cache (``serving.kv_pages`` x ``serving.kv_page_size`` tokens,
        ``serving.decode_slots`` concurrent sequences).  Drive it with
        :meth:`submit_generate` / :meth:`generate`; plain :meth:`submit`
        refuses it.  Generation models sit outside the one-shot LRU
        table (an engine holds live sequences — evicting it mid-flight
        would kill them) and are removed by :meth:`unregister`."""
        from . import deploy as _deploy
        if generate:
            if quantized:
                raise ServingError(
                    "model %r: generate=True with quantized=True is not "
                    "supported — KV quantization for generation is baked "
                    "at EXPORT time (export_generation(..., "
                    "kv_quantized=True), int8 KV pages), not applied at "
                    "register" % (name,))
            return self._register_generation(name, prefix)
        predictor = _deploy.StableHLOPredictor(prefix, quantized=quantized)
        if predictor._params is None:
            raise ServingError(
                "model %r: artifact %r was exported with "
                "include_params=False; serving needs shipped params"
                % (name, prefix))
        if predictor.dynamic_batch:
            buckets = self._policy_buckets(self.max_batch)
        else:
            # fixed-shape artifact (v1, or a model whose lowering
            # constrains the batch dim): its one exported batch size IS
            # the bucket set
            fixed = int(predictor.meta["input_shape"][0])
            buckets = (fixed,)
        entry = _ModelEntry(name, prefix, predictor, buckets)
        entry.breaker = _Breaker(name, self.breaker_threshold,
                                 self.breaker_cooldown_ms * 1e-3)
        with self._cond:
            self._models.pop(name, None)
            self._models[name] = entry
            evicted = []
            while len(self._models) > self.max_models:
                victim, _ = self._models.popitem(last=False)
                evicted.append(victim)
            started = self._started
        for victim in evicted:
            _telemetry.counter("serving.models_evicted").inc()
            _LOG.info("serving: evicted LRU model %r (max_models=%d)",
                      victim, self.max_models)
        if started:
            self._compile_entry(entry)
        return entry

    def _register_generation(self, name, prefix):
        from . import deploy as _deploy
        from .generation import GenerationEngine
        predictor = _deploy.load_generator(prefix)
        if predictor._params is None:
            raise ServingError(
                "model %r: artifact %r was exported with "
                "include_params=False; serving needs shipped params"
                % (name, prefix))
        engine = GenerationEngine(
            name, predictor,
            breaker=_Breaker(name, self.breaker_threshold,
                             self.breaker_cooldown_ms * 1e-3),
            max_pending=self.max_pending,
            default_deadline_ms=self.default_deadline_ms)
        with self._cond:
            old = self._generation.pop(name, None)
            self._generation[name] = engine
            started = self._started
        if old is not None:
            old.stop(drain=False)
        if started:
            engine.start()
        return engine

    def unregister(self, name):
        with self._cond:
            self._models.pop(name, None)
            engine = self._generation.pop(name, None)
        if engine is not None:
            engine.stop(drain=False)

    def models(self):
        """Registered model names, least recently used first (one-shot
        models; generation models follow)."""
        with self._cond:
            return list(self._models) + list(self._generation)

    def _entry(self, name):
        with self._cond:
            entry = self._models.get(name)
            if entry is not None:
                self._models.move_to_end(name)  # LRU touch
            is_generation = entry is None and name in self._generation
        if is_generation:
            raise ServingError(
                "model %r is a GENERATION model (registered with "
                "generate=True): it serves token streams, not one-shot "
                "predicts — use submit_generate()/generate()" % (name,))
        if entry is None:
            raise ServingError(
                "unknown model %r (registered: %s — evicted models must "
                "be register()ed again)" % (name, self.models()))
        return entry

    def _engine(self, name):
        with self._cond:
            engine = self._generation.get(name)
            is_oneshot = engine is None and name in self._models
        if is_oneshot:
            raise ServingError(
                "model %r is a one-shot predict model: register it with "
                "generate=True (a deploy.export_generation artifact) to "
                "generate — use submit()/predict() for it" % (name,))
        if engine is None:
            raise ServingError(
                "unknown generation model %r (registered: %s)"
                % (name, self.models()))
        return engine

    # ----------------------------------------------------------- compile
    def _compile_entry(self, entry):
        for bucket in entry.buckets:
            if bucket not in entry.programs:
                entry.programs[bucket] = self._compile(entry, bucket)

    def _compile(self, entry, bucket):
        from . import tracing as _tracing
        exported = entry.predictor._exported
        params = entry.predictor._params
        fn = jax.jit(lambda ps, x: exported.call(ps, x))
        pspec = tuple(jax.ShapeDtypeStruct(p.shape, p.dtype)
                      for p in params)
        xspec = jax.ShapeDtypeStruct((bucket,) + entry.item_shape,
                                     entry.in_dtype)
        t0 = _time.perf_counter()
        with _tracing.span("serving.compile", cat="serving",
                           model=entry.name, bucket=bucket):
            traced = fn.trace(pspec, xspec)
            t1 = _time.perf_counter()
            lowered = traced.lower()
            t2 = _time.perf_counter()
            program = lowered.compile()
            t3 = _time.perf_counter()
        _telemetry.counter("serving.compiles").inc()
        _telemetry.timer("serving.compile_ms").observe(
            (_time.perf_counter() - t0) * 1e3)
        from . import perf as _perf
        rec = _perf.register_compiled(
            "serving", "%s/b%d" % (entry.name, bucket), program,
            phases_ms={"trace_ms": (t1 - t0) * 1e3,
                       "lower_ms": (t2 - t1) * 1e3,
                       "compile_ms": (t3 - t2) * 1e3},
            dtype=str(entry.in_dtype))
        if rec is not None and rec["flops"] > 0:
            # per-request cost from the largest bucket compiled so far —
            # its amortization is what a full batch actually achieves
            prev = entry.cost_per_item
            if prev is None or bucket >= prev["bucket"]:
                entry.cost_per_item = {
                    "flops": rec["flops"] / bucket,
                    "bytes": rec["bytes_accessed"] / bucket,
                    "bucket": bucket,
                }
                _telemetry.gauge(
                    "serving.flops_per_request.%s" % entry.name).set(
                    round(entry.cost_per_item["flops"], 1))
                _telemetry.gauge(
                    "serving.bytes_per_request.%s" % entry.name).set(
                    round(entry.cost_per_item["bytes"], 1))
        return program

    # ------------------------------------------------- quantization drift
    def _load_drift_twin(self, entry):
        """Deserialize ``<prefix>-stats.stablehlo`` (the per-site runtime
        amax program exported next to the int8 artifact) into a jitted
        call over the entry's staged params; ``False`` when the artifact
        ships no twin (pre-PR-18 exports, nothing quantized)."""
        import os
        from jax import export as jexport
        path = entry.prefix + "-stats.stablehlo"
        if not os.path.exists(path):
            return False
        with open(path, "rb") as f:
            stats_exp = jexport.deserialize(f.read())
        return jax.jit(lambda ps, x: stats_exp.call(ps, x))

    def _maybe_sample_drift(self, entry, padded):
        """Every ``quant.drift_every``-th quantized dispatch, re-run the
        dispatched batch through the artifact's stats twin and fold the
        per-site runtime activation amax into the drift EWMA
        (``quant.drift_ratio.<model>.<site>`` gauges, ``quant_drift``
        JSONL events past ``quant.drift_threshold``).  The probe is an
        extra device program per sampled dispatch — off (0) by
        default."""
        every = int(_config.get("quant.drift_every") or 0)
        if every <= 0 or not entry.drift_sites:
            return
        entry.drift_count += 1
        if entry.drift_count % every:
            return
        if entry.drift_call is None:
            entry.drift_call = self._load_drift_twin(entry)
        if entry.drift_call is False:
            return
        from . import numerics as _numerics
        amaxes = _np.asarray(
            entry.drift_call(entry.predictor._params, padded))
        cal = (entry.predictor.meta.get("calibration") or {})
        thresholds = cal.get("thresholds") or {}
        _numerics.update_quant_drift(entry.name, entry.drift_sites,
                                     amaxes, thresholds, entry.drift_ewma)

    # --------------------------------------------------------- lifecycle
    def start(self):
        """Compile every registered ``(model, bucket)`` program eagerly
        (restart-warm via the persistent compile cache when
        ``serving.compile_cache_dir`` is set) and start the supervised
        batcher thread.  Idempotent while running; restartable after
        ``stop`` — unless a previous batcher missed its join deadline and
        is STILL running, in which case this raises instead of racing two
        batchers on one queue (the ``PrefetchingIter.reset`` contract)."""
        from . import tracing as _tracing
        with self._cond:
            if self._started:
                return self
        if self._leaked_thread is not None:
            if self._leaked_thread.is_alive():
                raise ServingError(
                    "a previous batcher thread missed its stop() join "
                    "deadline and is still running; refusing to start a "
                    "second batcher over the same queue — wait for it to "
                    "exit (then start() again) or recreate the Server")
            self._leaked_thread = None
        _configure_compile_cache()
        with self._cond:
            entries = list(self._models.values())
            engines = list(self._generation.values())
        for entry in entries:
            self._compile_entry(entry)
        for engine in engines:
            engine.start()
        # lifecycle flags flip under _cond: _enqueue and the batcher read
        # them under the same lock, so a submit racing start() sees either
        # the fully-started server or the stopped one — never a torn state
        with self._cond:
            self._stopping = False
            self._batcher_dead = None
            self._last_dispatch_done = _time.perf_counter()
            self._started = True
            # wrap_context: dispatch spans keep the starter's trace
            # parentage across the thread hop (the io.prefetch pattern)
            self._thread = threading.Thread(
                target=_tracing.wrap_context(self._supervise), daemon=True,
                name="mx-serving-batcher")
        self._thread.start()
        _tracing.register_stall_probe(self._probe_name, self._stall_probe)
        _obs.register_health_source(self._probe_name, self._health)
        return self

    def stop(self, drain=True, timeout_s=30.0):
        """Stop the server.  New submits fail immediately; with ``drain``
        (default) every already-queued request is dispatched before the
        batcher exits, so no accepted future is left unresolved; with
        ``drain=False`` pending futures fail promptly with ServingError.
        A batcher that misses the join deadline is remembered — a later
        ``start()`` refuses while it is still alive."""
        with self._cond:
            if not self._started:
                return
            self._stopping = True
            if not drain:
                abandoned = list(self._pending)
                self._pending.clear()
                _telemetry.gauge("serving.pending").set(0)
            else:
                abandoned = []
            self._cond.notify_all()
        for req in abandoned:
            if not req.future.done():
                req.future.set_exception(
                    ServingError("server stopped without drain"))
                _obs.log_access(req.model, "error",
                                request_id=req.trace_id,
                                error="ServingError: server stopped "
                                "without drain")
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout_s)
            if thread.is_alive():
                _telemetry.counter("serving.stop_timeout").inc()
                self._leaked_thread = thread
                _LOG.warning(
                    "serving: batcher did not drain within %.1fs and was "
                    "leaked; start() will refuse until it exits",
                    timeout_s)
        from . import tracing as _tracing
        _tracing.unregister_stall_probe(self._probe_name)
        _obs.unregister_health_source(self._probe_name)
        with self._cond:
            engines = list(self._generation.values())
        for engine in engines:
            engine.stop(drain=drain, timeout_s=timeout_s)
        with self._cond:
            self._started = False
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------ submit
    def _validate(self, entry, arr):
        if arr.ndim != len(entry.item_shape) + 1:
            raise ValueError(
                "model %r: request rank mismatch — exported signature is "
                "%s, got shape %s" % (entry.name,
                                      entry.predictor.signature(),
                                      tuple(arr.shape)))
        if tuple(arr.shape[1:]) != entry.item_shape:
            raise ValueError(
                "model %r: request item shape %s does not match the "
                "exported signature %s" % (entry.name, tuple(arr.shape),
                                           entry.predictor.signature()))
        if arr.dtype != entry.in_dtype:
            raise ValueError(
                "model %r: request dtype %s does not match the exported "
                "dtype %s" % (entry.name, arr.dtype, entry.in_dtype))
        if arr.shape[0] < 1:
            raise ValueError("model %r: empty request" % (entry.name,))

    def submit(self, name, data, deadline_ms=None):
        """Enqueue one request (any row count) for model ``name``; returns
        a ``concurrent.futures.Future`` resolving to the host numpy output
        rows for exactly the submitted rows (padding is invisible).

        ``deadline_ms`` (default: the ``serving.default_deadline_ms``
        knob; 0 = none) bounds how long the request may sit in queue: a
        request still queued past it completes with
        :class:`DeadlineExceededError` and is never dispatched.  Raises
        :class:`ServerOverloadedError` when the pending queue is at
        ``serving.max_pending`` and :class:`CircuitOpenError` while the
        model's breaker is open."""
        from . import tracing as _tracing
        from .ndarray.ndarray import NDArray
        with _tracing.span("serving.submit", cat="serving",
                           model=name) as sp:
            # the submit span's trace_id rides the request so the access
            # log joins the Chrome trace (None while tracing is off)
            trace_id = sp.trace_id
            entry = self._entry(name)
            arr = _np.asarray(data._data if isinstance(data, NDArray)
                              else data)
            self._validate(entry, arr)
            _telemetry.counter("serving.requests").inc()
            breaker = entry.breaker
            if breaker is not None and breaker.rejects_submit():
                _telemetry.counter("serving.breaker_rejected").inc()
                _obs.log_access(name, "breaker", request_id=trace_id)
                raise CircuitOpenError(
                    "model %r circuit breaker is OPEN after %d "
                    "consecutive dispatch failure(s); failing fast for "
                    "%.0fms more — other models keep serving, retry "
                    "after the cooldown"
                    % (name, breaker.failures,
                       breaker.cooldown_remaining_ms()))
            if deadline_ms is None:
                deadline_ms = self.default_deadline_ms
            deadline_ms = float(deadline_ms or 0.0)
            cap = entry.capacity
            if arr.shape[0] <= cap:
                req = _Request(name, arr, Future(), deadline_ms,
                               trace_id=trace_id)
                fut = self._enqueue(req)
                fut._mx_requests = (req,)
                return fut
            # oversized request: split into cap-row chunks, re-concatenate
            # (each admitted chunk gets its own access record, all sharing
            # the submit span's request_id)
            chunks = [arr[i:i + cap] for i in range(0, arr.shape[0], cap)]
            _telemetry.counter("serving.request_chunks").inc(len(chunks))
            reqs = [_Request(name, c, Future(), deadline_ms,
                             trace_id=trace_id)
                    for c in chunks]
            enqueued = []
            try:
                for r in reqs:
                    self._enqueue(r)
                    enqueued.append(r)
            except BaseException:
                # admission failed mid-way: unwind the sibling chunks so
                # no queued orphan is dispatched for a dead combined future
                self._cancel_queued(enqueued, ServingError(
                    "sibling chunk was rejected; oversized request "
                    "aborted"))
                raise
            futures = [r.future for r in reqs]
            combined = Future()
            remaining = [len(futures)]
            lock = threading.Lock()

            def _one_done(_f):
                with lock:
                    remaining[0] -= 1
                    last = remaining[0] == 0
                if not last or combined.done():
                    return
                try:
                    combined.set_result(_np.concatenate(
                        [f.result() for f in futures], axis=0))
                except BaseException as exc:  # noqa: BLE001
                    combined.set_exception(exc)

            for f in futures:
                f.add_done_callback(_one_done)
            combined._mx_requests = tuple(reqs)
            return combined

    def _enqueue(self, req):
        shed = False
        with self._cond:
            if self._batcher_dead is not None:
                exc = self._batcher_dead
                raise ServingError(
                    "batcher thread crashed (%s: %s) and exhausted its "
                    "restart budget (resilience.retry_attempts); submit() "
                    "rejected — recreate the Server"
                    % (type(exc).__name__, exc))
            if self._stopping or not self._started:
                raise ServingError(
                    "server is %s; submit() rejected"
                    % ("stopping" if self._stopping else "not started"))
            if self.max_pending > 0 \
                    and len(self._pending) >= self.max_pending:
                entry = self._models.get(req.model)
                if entry is not None:
                    entry.shed += 1
                shed = True
            else:
                self._pending.append(req)
                _telemetry.gauge("serving.pending").set(len(self._pending))
                self._cond.notify_all()
        if shed:
            _telemetry.counter("serving.shed_requests").inc()
            _telemetry.counter("serving.shed_requests.%s" % req.model).inc()
            _obs.log_access(req.model, "shed", request_id=req.trace_id)
            raise ServerOverloadedError(
                "server overloaded: %d request(s) already pending "
                "(serving.max_pending=%d); request shed — back off and "
                "retry" % (self.max_pending, self.max_pending))
        return req.future

    def _cancel_queued(self, reqs, exc):
        """Remove still-queued requests and fail their futures with
        ``exc``; requests already popped into a forming batch are left to
        complete.  Returns the list actually cancelled."""
        removed = []
        with self._cond:
            for req in reqs:
                try:
                    self._pending.remove(req)
                except ValueError:
                    continue
                removed.append(req)
            if removed:
                _telemetry.gauge("serving.pending").set(len(self._pending))
        outcome = _access_outcome(exc)
        for req in removed:
            if not req.future.done():
                req.future.set_exception(exc)
                if _obs.access_log_enabled():
                    _obs.log_access(
                        req.model, outcome, request_id=req.trace_id,
                        queue_ms=(_time.perf_counter() - req.t_submit)
                        * 1e3,
                        error="%s: %s" % (type(exc).__name__, exc)
                        if outcome == "error" else None)
        return removed

    def predict(self, name, data, timeout=None, deadline_ms=None):
        """Synchronous convenience: ``submit(...).result(timeout)``.  On
        timeout the queued request is CANCELLED (completed with
        :class:`DeadlineExceededError`, never dispatched) instead of
        left to burn compute for a caller that gave up; a request
        already mid-dispatch completes normally but the call still raises
        DeadlineExceededError."""
        fut = self.submit(name, data, deadline_ms=deadline_ms)
        try:
            return fut.result(timeout)
        except _FutureTimeout:
            reqs = getattr(fut, "_mx_requests", ())
            cancelled = self._cancel_queued(reqs, DeadlineExceededError(
                "predict(%r) timed out after %.3fs; queued request "
                "cancelled before dispatch" % (name, timeout)))
            for req in cancelled:
                self._count_deadline_exceeded(req.model)
            raise DeadlineExceededError(
                "predict(%r) timed out after %.3fs (%d queued chunk(s) "
                "cancelled undispatched)"
                % (name, timeout, len(cancelled))) from None

    # -------------------------------------------------------- generation
    def submit_generate(self, name, prompt, max_new_tokens, eos_id=None,
                        deadline_ms=None, temperature=0.0, top_k=0,
                        top_p=1.0, seed=None):
        """Enqueue one prompt on generation model ``name``; returns a
        Future resolving to the generated token ids (np.int32, EOS
        included when hit).  With ``temperature`` 0 (the default) that
        is bitwise the eager ``greedy_decode`` stream regardless of
        co-scheduled traffic; ``temperature`` > 0 samples with optional
        ``top_k`` / ``top_p`` truncation under a per-request ``seed``
        (sampling-enabled v5 artifacts only — fresh entropy when the
        seed is None, a fixed seed replays one deterministic stream).

        The request joins the model's per-iteration scheduler: it
        prefills into a free decode slot as soon as the KV page pool
        covers ``prompt + max_new_tokens``, decodes alongside whatever
        else is in flight and exits mid-flight on EOS/budget.  The PR-7
        admission semantics apply: sheds past ``serving.max_pending``
        (:class:`ServerOverloadedError`), ``deadline_ms`` bounds QUEUE
        time (:class:`DeadlineExceededError`, never prefilled), an open
        breaker fails fast (:class:`CircuitOpenError`)."""
        from . import tracing as _tracing
        with _tracing.span("serving.submit", cat="serving", model=name):
            return self._engine(name).submit(
                prompt, max_new_tokens, eos_id=eos_id,
                deadline_ms=deadline_ms, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed)

    def generate(self, name, prompt, max_new_tokens, eos_id=None,
                 timeout=None, deadline_ms=None, temperature=0.0,
                 top_k=0, top_p=1.0, seed=None):
        """Synchronous convenience:
        ``submit_generate(...).result(timeout)``."""
        fut = self.submit_generate(name, prompt, max_new_tokens,
                                   eos_id=eos_id, deadline_ms=deadline_ms,
                                   temperature=temperature, top_k=top_k,
                                   top_p=top_p, seed=seed)
        try:
            return fut.result(timeout)
        except _FutureTimeout:
            raise DeadlineExceededError(
                "generate(%r) timed out after %.3fs (the sequence keeps "
                "decoding; resubmit with deadline_ms to bound queue "
                "time)" % (name, timeout)) from None

    def _count_deadline_exceeded(self, model):
        _telemetry.counter("serving.deadline_exceeded").inc()
        _telemetry.counter("serving.deadline_exceeded.%s" % model).inc()
        with self._cond:
            entry = self._models.get(model)
            if entry is not None:
                entry.deadline_exceeded += 1

    # ----------------------------------------------------------- batcher
    def _take_fitting(self, model, budget):  # mxlint: holds(_cond)
        """Pop the first queued request for ``model`` with rows <=
        ``budget`` (caller holds the condition lock).  Queued requests
        whose deadline has expired are harvested as a second return value —
        the caller completes them typed, they are never dispatched."""
        now = _time.perf_counter()
        take = None
        dead = []
        for req in self._pending:
            if req.expired(now):
                dead.append(req)
                continue
            if take is None and req.model == model and req.rows <= budget:
                take = req
        for req in dead:
            self._pending.remove(req)
        if take is not None:
            self._pending.remove(take)
        if dead or take is not None:
            _telemetry.gauge("serving.pending").set(len(self._pending))
        return take, dead

    def _expire(self, reqs, reason="expired in queue before dispatch"):
        """Complete deadline-expired requests with the typed error; they
        never reach a program — no compute is wasted on them."""
        for req in reqs:
            self._count_deadline_exceeded(req.model)
            if not req.future.done():
                queued_ms = (_time.perf_counter() - req.t_submit) * 1e3
                req.future.set_exception(DeadlineExceededError(
                    "request for model %r %s (queued %.1fms, deadline "
                    "passed)" % (req.model, reason, queued_ms)))
                _obs.log_access(req.model, "deadline",
                                request_id=req.trace_id,
                                queue_ms=queued_ms)

    def _supervise(self):
        """Batcher supervisor (the thread target): runs ``_loop`` under
        the ``mx.resilience`` retry budget.  Each crash fails the pending
        futures with the causal exception and restarts the loop after
        backoff; once the budget is exhausted the server is marked dead —
        ``submit()`` then fails fast instead of hanging forever."""
        from . import resilience as _resilience
        try:
            _resilience.call_with_retry(self._run_batcher,
                                        kind="serving_batcher")
        except BaseException as exc:  # noqa: BLE001 — budget exhausted
            cause = exc.__cause__ if exc.__cause__ is not None else exc
            with self._cond:
                self._batcher_dead = cause
                pending = list(self._pending)
                self._pending.clear()
                _telemetry.gauge("serving.pending").set(0)
                self._cond.notify_all()
            for req in pending:
                if not req.future.done():
                    req.future.set_exception(cause)
                    _obs.log_access(req.model, "error",
                                    request_id=req.trace_id,
                                    error="%s: %s"
                                    % (type(cause).__name__, cause))
            _LOG.error(
                "serving: batcher crashed and exhausted its restart "
                "budget (%s: %s); all submits now fail fast — recreate "
                "the Server", type(cause).__name__, cause)

    def _run_batcher(self):
        """One supervised batcher incarnation: a clean ``_loop`` return
        (stop/drain) ends the thread; a crash fails every pending future
        with the CAUSAL exception, counts ``serving.batcher_crashes``,
        flight-records the crash, and re-raises as a retryable wrapper so
        the supervisor's ``call_with_retry`` restarts it with backoff."""
        try:
            self._loop()
        except BaseException as exc:  # noqa: BLE001 — supervised crash
            _telemetry.counter("serving.batcher_crashes").inc()
            try:
                from . import tracing as _tracing
                _tracing.record_event(
                    "serving", "batcher_crash",
                    error="%s: %s" % (type(exc).__name__, exc))
            except Exception:  # noqa: BLE001
                pass
            with self._cond:
                pending = list(self._pending)
                self._pending.clear()
                _telemetry.gauge("serving.pending").set(0)
            for req in pending:
                if not req.future.done():
                    req.future.set_exception(exc)
                    _obs.log_access(req.model, "error",
                                    request_id=req.trace_id,
                                    error="%s: %s"
                                    % (type(exc).__name__, exc))
            _LOG.warning(
                "serving: batcher thread crashed (%s: %s); %d pending "
                "future(s) failed with the causal exception; restarting "
                "under the resilience retry budget",
                type(exc).__name__, exc, len(pending))
            raise _BatcherCrashError(
                "serving batcher crashed: %s: %s"
                % (type(exc).__name__, exc)) from exc

    def _loop(self):
        while True:
            with self._cond:
                while not self._pending:
                    if self._stopping:
                        return
                    self._cond.wait(timeout=0.05)
                first = self._pending.popleft()
                _telemetry.gauge("serving.pending").set(len(self._pending))
                entry = self._models.get(first.model)
            if entry is None:  # model evicted with requests in flight
                first.future.set_exception(ServingError(
                    "model %r was evicted while queued" % (first.model,)))
                continue
            if first.expired():
                self._expire([first])
                continue
            batch = [first]
            rows = first.rows
            cap = entry.capacity
            deadline = first.t_submit + self.max_queue_delay_ms * 1e-3
            while rows < cap:
                with self._cond:
                    req, expired = self._take_fitting(first.model,
                                                      cap - rows)
                    wait = None
                    if req is None:
                        remaining = deadline - _time.perf_counter()
                        if remaining <= 0 or self._stopping:
                            wait = 0.0
                        else:
                            wait = min(remaining, 0.005)
                if expired:
                    self._expire(expired)
                if req is not None:
                    batch.append(req)
                    rows += req.rows
                    continue
                if wait == 0.0:
                    break
                with self._cond:
                    self._cond.wait(timeout=wait)
            # batch-formation deadline check: anything that expired while
            # the coalescing window was open completes typed, undispatched
            now = _time.perf_counter()
            dead = [r for r in batch if r.expired(now)]
            if dead:
                self._expire(dead)
                batch = [r for r in batch if not r.expired(now)]
                if not batch:
                    continue
                rows = sum(r.rows for r in batch)
            self._dispatch(entry, batch, rows)

    def _dispatch(self, entry, batch, rows):
        from . import resilience as _resilience
        from . import tracing as _tracing
        t0 = _time.perf_counter()
        bucket = _io.pick_bucket(entry.buckets, rows) or entry.capacity
        for req in batch:
            _telemetry.timer("serving.queue_delay_ms").observe(
                (t0 - req.t_submit) * 1e3)
        breaker = entry.breaker
        if breaker is not None and not breaker.allow_dispatch():
            # open breaker, cooldown still running: fail the batch fast
            # (requests admitted before the breaker opened)
            _telemetry.counter("serving.breaker_rejected").inc(len(batch))
            exc = CircuitOpenError(
                "model %r circuit breaker is OPEN (%d consecutive "
                "dispatch failure(s)); batch failed fast, retry after "
                "the cooldown" % (entry.name, breaker.failures))
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
                    _obs.log_access(req.model, "breaker",
                                    request_id=req.trace_id,
                                    queue_ms=(t0 - req.t_submit) * 1e3)
            with self._cond:
                self._last_dispatch_done = _time.perf_counter()
            return
        try:
            if _resilience.faults_active("serving_slow") \
                    and _resilience.should_inject("serving_slow"):
                _time.sleep(_SLOW_DISPATCH_S)
            _resilience.inject("serving_dispatch")
            cat = batch[0].data if len(batch) == 1 else \
                _np.concatenate([req.data for req in batch], axis=0)
            padded = _io.pad_rows_to(cat, bucket) if bucket > rows else cat
            with _tracing.span("serving.dispatch", cat="serving",
                               model=entry.name, requests=len(batch),
                               rows=rows, bucket=bucket):
                program = entry.programs.get(bucket)
                if program is None:
                    # a bucket registered after start(), or a fixed-batch
                    # artifact's single shape — compile once, then cached
                    program = entry.programs[bucket] = \
                        self._compile(entry, bucket)
                out = program(entry.predictor._params, padded)
            if isinstance(out, (tuple, list)):
                out = out[0]
            host = _np.asarray(out)
        except BaseException as exc:  # noqa: BLE001 — fail the batch's
            # futures (and feed the breaker), never the batcher thread
            _telemetry.counter("serving.dispatch_errors").inc()
            if breaker is not None:
                breaker.record_failure()
            err = "%s: %s" % (type(exc).__name__, exc)
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
                    _obs.log_access(req.model, "error",
                                    request_id=req.trace_id,
                                    queue_ms=(t0 - req.t_submit) * 1e3,
                                    error=err)
            with self._cond:
                self._last_dispatch_done = _time.perf_counter()
            return
        if breaker is not None:
            breaker.record_success()
        t1 = _time.perf_counter()
        access_on = _obs.access_log_enabled()
        row_nbytes = host.nbytes // max(1, host.shape[0]) if access_on \
            else 0
        ofs = 0
        for req in batch:
            if not req.future.done():
                req.future.set_result(host[ofs:ofs + req.rows])
                if access_on:
                    _obs.log_access(req.model, "ok",
                                    request_id=req.trace_id,
                                    queue_ms=(t0 - req.t_submit) * 1e3,
                                    dispatch_ms=(t1 - t0) * 1e3,
                                    bytes=req.rows * row_nbytes)
            ofs += req.rows
            _telemetry.timer("serving.request_ms").observe(
                (t1 - req.t_submit) * 1e3)
        _telemetry.counter("serving.batch_dispatches").inc()
        if entry.quantized:
            _telemetry.counter("serving.quantized_dispatches").inc()
            try:
                self._maybe_sample_drift(entry, padded)
            except Exception as exc:  # noqa: BLE001 — the probe is
                # observability; it must never fail a served batch
                _LOG.warning("serving: drift probe failed for %r: %s: %s",
                             entry.name, type(exc).__name__, exc)
        _telemetry.timer("serving.batch_fill").observe(rows / bucket)
        _telemetry.timer("serving.dispatch_ms").observe((t1 - t0) * 1e3)
        with self._cond:
            self._last_dispatch_done = t1
        # one JSONL record per dispatch (no-op when the sink is off);
        # tools/telemetry_report.py folds these into the serving table,
        # the queue-delay anomaly and the overload-shedding anomaly
        if _telemetry.enabled():
            cost = entry.cost_per_item
            _telemetry.log_event(
                "serving", model=entry.name, requests=len(batch),
                rows=rows, bucket=bucket, quantized=entry.quantized,
                fill=round(rows / bucket, 4),
                queue_delay_ms=round(max(
                    (t0 - req.t_submit) * 1e3 for req in batch), 4),
                wall_ms=round((t1 - t0) * 1e3, 4),
                budget_ms=self.max_queue_delay_ms,
                shed=entry.shed,
                deadline_exceeded=entry.deadline_exceeded,
                # useful work in this dispatch, from the registered
                # program's compile-time cost analysis (mx.perf)
                flops=round(rows * cost["flops"], 1)
                if cost is not None else None,
                bytes=round(rows * cost["bytes"], 1)
                if cost is not None else None,
                breaker=breaker.state if breaker is not None else "closed")

    # ---------------------------------------------------------- watchdog
    def _stall_probe(self, interval_s):
        """PR-3 watchdog hook (``tracing.register_stall_probe``): when
        the queue is non-empty but no dispatch has completed within the
        watchdog interval, return a flight-recordable snapshot — open
        requests, breaker states, batcher liveness.  None while
        healthy."""
        now = _time.perf_counter()
        with self._cond:
            if not self._pending:
                return None
            stalled_s = now - self._last_dispatch_done
            if stalled_s < interval_s:
                return None
            open_reqs = [
                {"model": r.model, "rows": r.rows,
                 "queued_s": round(now - r.t_submit, 4),
                 "deadline_in_s": round(r.deadline - now, 4)
                 if r.deadline is not None else None}
                for r in list(self._pending)[:16]]
            pending = len(self._pending)
            breakers = {name: e.breaker.state if e.breaker is not None
                        else "closed"
                        for name, e in self._models.items()}
            thread = self._thread
        return {"pending": pending,
                "since_last_dispatch_s": round(stalled_s, 4),
                "batcher_alive": bool(thread is not None
                                      and thread.is_alive()),
                "open_requests": open_reqs,
                "breakers": breakers}

    def _health(self):
        """mx.obs health source (registered in :meth:`start`): the
        ``/healthz`` slice of this server — batcher liveness, per-model
        breaker state, per-engine decode-loop liveness and KV-pool
        saturation.  KV saturation is reported but does NOT flip
        ``healthy`` (transient pool exhaustion under load is expected
        back-pressure, not an outage)."""
        with self._cond:
            breakers = {name: e.breaker.state if e.breaker is not None
                        else "closed"
                        for name, e in self._models.items()}
            batcher_dead = self._batcher_dead
            started = self._started
            thread = self._thread
            pending = len(self._pending)
            engines = dict(self._generation)
        reasons = []
        if batcher_dead is not None:
            reasons.append("batcher_dead")
        batcher_alive = bool(thread is not None and thread.is_alive())
        if started and not batcher_alive:
            reasons.append("batcher_thread_dead")
        for name, state in breakers.items():
            if state == "open":
                reasons.append("breaker_open:%s" % name)
        generation = {}
        for name, eng in engines.items():
            s = eng.stats()
            if started and not s["engine_alive"]:
                reasons.append("engine_dead:%s" % name)
            if s["breaker"] == "open":
                reasons.append("breaker_open:%s" % name)
            generation[name] = {
                "engine_alive": s["engine_alive"],
                "breaker": s["breaker"],
                "queued": s["queued"],
                "active": s["active"],
                "kv_pages": s["kv_pages"],
                "kv_pages_free": s["kv_pages_free"],
                "kv_saturated": s["kv_pages_free"] == 0,
            }
        return {
            "healthy": not reasons,
            "reasons": reasons,
            "started": started,
            "pending": pending,
            "batcher_alive": batcher_alive,
            "breakers": breakers,
            "generation": generation,
        }

    # ------------------------------------------------------------- stats
    def stats(self):
        """Serving-slice snapshot of the telemetry registry (counters,
        gauges and timer histograms whose names start with ``serving.``)
        plus live server state: registered models, queue depth, breaker
        states, batcher liveness."""
        snap = _telemetry.snapshot()
        with self._cond:
            breakers = {name: e.breaker.state if e.breaker is not None
                        else "closed"
                        for name, e in self._models.items()}
            quantized = {name: e.quantized
                         for name, e in self._models.items()}
            cost_per_item = {name: dict(e.cost_per_item)
                             if e.cost_per_item is not None else None
                             for name, e in self._models.items()}
            pending = len(self._pending)
            thread = self._thread
            engines = dict(self._generation)
        generation = {name: eng.stats() for name, eng in engines.items()}
        return {
            "counters": {k: v for k, v in snap["counters"].items()
                         if k.startswith("serving.")},
            "generation": generation,
            "gauges": {k: v for k, v in snap["gauges"].items()
                       if k.startswith("serving.")},
            "timers": {k: v for k, v in snap["timers"].items()
                       if k.startswith("serving.")},
            "models": self.models(),
            "quantized": quantized,
            "cost_per_item": cost_per_item,
            "pending": pending,
            "breakers": breakers,
            "batcher_alive": bool(thread is not None and thread.is_alive()),
        }


def load_server(prefixes, **kwargs):
    """Convenience: build, register and start a server from
    ``{name: prefix}``.  All-or-nothing: if any ``register()`` (or the
    ``start()``) raises, previously registered models — and with them any
    staged params / compiled programs — are unwound before the exception
    propagates, so a partial failure cannot keep device memory alive
    through the raised traceback."""
    srv = Server(**kwargs)
    registered = []
    try:
        for name, prefix in dict(prefixes).items():
            srv.register(name, prefix)
            registered.append(name)
        return srv.start()
    except BaseException:
        for name in registered:
            try:
                srv.unregister(name)
            except Exception:  # noqa: BLE001 — unwind is best-effort
                pass
        raise
