"""``mx.serving`` — continuous-batching inference over the StableHLO
export path.

Reference deployment story: the C predict API served one process-local
model per handle (include/mxnet/c_predict_api.h) and TensorRT subgraph
serving owned the batched GPU path (SURVEY §2, §5).  The TPU-native analog
is a REQUEST QUEUE in front of the ``mx.deploy`` artifact: concurrent
``submit()`` calls coalesce into batches padded up to the shared
``io.pad_buckets`` bucket set, so a SMALL, FIXED family of AOT-compiled
programs (one per ``(model, bucket)``) serves every request size — the
same pad-bucket policy the PR-5 input pipeline uses to keep training
compiles flat now keeps serving compiles flat.

Architecture (one background batcher thread per :class:`Server`):

  submit(name, x) ──► per-server FIFO ──► batcher loop:
                                            take first request
                                            coalesce same-model requests
                                              until rows == max_batch or
                                              max_queue_delay_ms elapses
                                            concat + wrap-pad → bucket
                                            AOT program(params, batch)
                                            scatter rows → caller futures

Key properties:

  * **Bitwise-stable batching** — each output row of a bucketed dispatch
    equals the row the unbatched ``StableHLOPredictor.predict`` produces
    (row-independent inference math; ``tools/check_serving.py`` proves it
    under concurrent ragged traffic).
  * **Zero steady-state compiles** — every ``(model, bucket)`` program is
    compiled eagerly at :meth:`Server.start`; ragged request sizes never
    reach the compiler.  ``serving.compile_cache_dir`` wires jax's
    persistent compilation cache so a RESTARTED server skips even those
    (near-zero cold start).
  * **Device-resident params** — uploaded once at ``register()`` (by the
    underlying :class:`~mxnet_tpu.deploy.StableHLOPredictor`), never per
    request.
  * **Multi-model** — a bounded LRU table of registered models; the least
    recently used model (programs + device params) is evicted when
    ``max_models`` is exceeded.
  * **Telemetry** — ``serving.requests`` / ``serving.batch_dispatches`` /
    ``serving.compiles`` counters, ``serving.queue_delay_ms`` /
    ``serving.batch_fill`` / ``serving.dispatch_ms`` /
    ``serving.request_ms`` timer histograms (p99 end-to-end latency =
    ``timer("serving.request_ms").stats()["p99"]``), one ``serving`` JSONL
    record per dispatch on the telemetry sink, and ``serving.submit`` /
    ``serving.dispatch`` spans with cross-thread parentage (the batcher
    runs under ``tracing.wrap_context``, the ``io.prefetch`` pattern).

Knobs (config.py): ``serving.max_batch`` (MXNET_TPU_SERVING_MAX_BATCH),
``serving.max_queue_delay_ms`` (MXNET_TPU_SERVING_MAX_QUEUE_DELAY_MS),
``serving.compile_cache_dir`` (MXNET_TPU_SERVING_COMPILE_CACHE_DIR); the
bucket POLICY is the shared ``io.pad_buckets`` knob.  docs/SERVING.md has
the full architecture note.
"""
from __future__ import annotations

import logging
import threading
import time as _time
from collections import OrderedDict, deque
from concurrent.futures import Future

import numpy as _np

import jax

from . import config as _config
from . import io as _io
from . import telemetry as _telemetry

__all__ = ["Server", "ServingError", "load_server"]

_LOG = logging.getLogger("mxnet_tpu.serving")


class ServingError(RuntimeError):
    """Raised for serving lifecycle errors (stopped server, evicted or
    unknown model, oversized request on a fixed-batch artifact)."""


class _Request:
    """One caller request: host-side rows plus the future its output rows
    resolve, stamped with the submit time for queue-delay accounting."""

    __slots__ = ("model", "data", "rows", "future", "t_submit")

    def __init__(self, model, data, future):
        self.model = model
        self.data = data
        self.rows = int(data.shape[0])
        self.future = future
        self.t_submit = _time.perf_counter()


class _ModelEntry:
    """A registered model: reloaded artifact, device-resident params, and
    the per-bucket AOT program table."""

    __slots__ = ("name", "prefix", "predictor", "buckets", "programs",
                 "item_shape", "in_dtype")

    def __init__(self, name, prefix, predictor, buckets):
        self.name = name
        self.prefix = prefix
        self.predictor = predictor
        self.buckets = tuple(buckets)
        self.programs = {}
        shape = predictor.meta.get("input_shape") or []
        self.item_shape = tuple(int(s) for s in shape[1:])
        self.in_dtype = _np.dtype(predictor.meta.get("input_dtype",
                                                     "float32"))

    @property
    def capacity(self):
        return self.buckets[-1]


_CACHE_DIR_APPLIED = [None]


def _configure_compile_cache():
    """Wire jax's persistent compilation cache from the
    ``serving.compile_cache_dir`` knob (idempotent).  With the cache dir
    set, a restarted server's eager ``start()`` compiles hit disk instead
    of XLA — the near-zero cold-start contract."""
    cache_dir = (_config.get("serving.compile_cache_dir") or "").strip()
    if not cache_dir:
        return False
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # serving programs are small and fast-compiling on CPU; without these
    # floors the cache would skip exactly the programs we want to persist
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    if _CACHE_DIR_APPLIED[0] != cache_dir:
        # jax initializes its cache object on the FIRST compile of the
        # process; a dir set after that (the common case — params staged
        # and models warmed before start()) is silently ignored until the
        # cache is re-initialized
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — older jax: dir applies lazily
            pass
        _CACHE_DIR_APPLIED[0] = cache_dir
    return True


class Server:
    """Continuous-batching inference server over ``mx.deploy`` artifacts.

    Usage::

        srv = mx.serving.Server(max_batch=32, max_queue_delay_ms=2.0)
        srv.register("resnet", "/models/resnet50")   # params → device
        srv.start()                                  # AOT-compile buckets
        fut = srv.submit("resnet", batch_of_images)  # any request size
        probs = fut.result()                         # host numpy rows
        srv.stop()                                   # graceful drain

    ``submit`` is thread-safe; requests from any number of caller threads
    coalesce into bucketed batches on the single batcher thread.  Requests
    larger than the biggest bucket are transparently split into chunks and
    their outputs re-concatenated.  ``Server`` is also a context manager
    (``with Server() as srv: ...`` starts and drains it).
    """

    def __init__(self, max_batch=None, max_queue_delay_ms=None,
                 buckets=None, max_models=8):
        if max_batch is None:
            max_batch = _config.get("serving.max_batch")
        if max_queue_delay_ms is None:
            max_queue_delay_ms = _config.get("serving.max_queue_delay_ms")
        if buckets is None:
            buckets = _config.get("io.pad_buckets")
        self.max_batch = int(max_batch)
        self.max_queue_delay_ms = float(max_queue_delay_ms)
        self._bucket_policy = buckets
        self.max_models = int(max_models)
        self._models = OrderedDict()     # name -> _ModelEntry (LRU order)
        self._pending = deque()
        self._cond = threading.Condition()
        self._thread = None
        self._started = False
        self._stopping = False

    # ------------------------------------------------------------ models
    def _policy_buckets(self, cap):
        sizes = _io.bucket_sizes(self._bucket_policy, cap)
        # serving must always have at least one compiled shape; policy
        # 'off' (natural shapes) degenerates to the single full bucket
        return sizes or (cap,)

    def register(self, name, prefix):
        """Load the ``mx.deploy`` artifact at ``prefix`` under ``name``:
        params go device-resident now; bucket programs compile now if the
        server is already started (else at :meth:`start`).  Re-registering
        a name replaces the entry.  The table is LRU-bounded at
        ``max_models`` — registering past it evicts the least recently
        used model (its programs and device params become collectable)."""
        from . import deploy as _deploy
        predictor = _deploy.StableHLOPredictor(prefix)
        if predictor._params is None:
            raise ServingError(
                "model %r: artifact %r was exported with "
                "include_params=False; serving needs shipped params"
                % (name, prefix))
        if predictor.dynamic_batch:
            buckets = self._policy_buckets(self.max_batch)
        else:
            # fixed-shape artifact (v1, or a model whose lowering
            # constrains the batch dim): its one exported batch size IS
            # the bucket set
            fixed = int(predictor.meta["input_shape"][0])
            buckets = (fixed,)
        entry = _ModelEntry(name, prefix, predictor, buckets)
        with self._cond:
            self._models.pop(name, None)
            self._models[name] = entry
            evicted = []
            while len(self._models) > self.max_models:
                victim, _ = self._models.popitem(last=False)
                evicted.append(victim)
        for victim in evicted:
            _telemetry.counter("serving.models_evicted").inc()
            _LOG.info("serving: evicted LRU model %r (max_models=%d)",
                      victim, self.max_models)
        if self._started:
            self._compile_entry(entry)
        return entry

    def unregister(self, name):
        with self._cond:
            self._models.pop(name, None)

    def models(self):
        """Registered model names, least recently used first."""
        with self._cond:
            return list(self._models)

    def _entry(self, name):
        with self._cond:
            entry = self._models.get(name)
            if entry is not None:
                self._models.move_to_end(name)  # LRU touch
        if entry is None:
            raise ServingError(
                "unknown model %r (registered: %s — evicted models must "
                "be register()ed again)" % (name, self.models()))
        return entry

    # ----------------------------------------------------------- compile
    def _compile_entry(self, entry):
        for bucket in entry.buckets:
            if bucket not in entry.programs:
                entry.programs[bucket] = self._compile(entry, bucket)

    def _compile(self, entry, bucket):
        from . import tracing as _tracing
        exported = entry.predictor._exported
        params = entry.predictor._params
        fn = jax.jit(lambda ps, x: exported.call(ps, x))
        pspec = tuple(jax.ShapeDtypeStruct(p.shape, p.dtype)
                      for p in params)
        xspec = jax.ShapeDtypeStruct((bucket,) + entry.item_shape,
                                     entry.in_dtype)
        t0 = _time.perf_counter()
        with _tracing.span("serving.compile", cat="serving",
                           model=entry.name, bucket=bucket):
            program = fn.lower(pspec, xspec).compile()
        _telemetry.counter("serving.compiles").inc()
        _telemetry.timer("serving.compile_ms").observe(
            (_time.perf_counter() - t0) * 1e3)
        return program

    # --------------------------------------------------------- lifecycle
    def start(self):
        """Compile every registered ``(model, bucket)`` program eagerly
        (restart-warm via the persistent compile cache when
        ``serving.compile_cache_dir`` is set) and start the batcher
        thread.  Idempotent while running; restartable after ``stop``."""
        from . import tracing as _tracing
        if self._started:
            return self
        _configure_compile_cache()
        with self._cond:
            entries = list(self._models.values())
        for entry in entries:
            self._compile_entry(entry)
        self._stopping = False
        self._started = True
        # wrap_context: dispatch spans keep the starter's trace parentage
        # across the thread hop (the io.prefetch pattern)
        self._thread = threading.Thread(
            target=_tracing.wrap_context(self._loop), daemon=True,
            name="mx-serving-batcher")
        self._thread.start()
        return self

    def stop(self, drain=True, timeout_s=30.0):
        """Stop the server.  New submits fail immediately; with ``drain``
        (default) every already-queued request is dispatched before the
        batcher exits, so no accepted future is left unresolved."""
        with self._cond:
            if not self._started:
                return
            self._stopping = True
            if not drain:
                abandoned = list(self._pending)
                self._pending.clear()
            else:
                abandoned = []
            self._cond.notify_all()
        for req in abandoned:
            req.future.set_exception(
                ServingError("server stopped without drain"))
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout_s)
            if thread.is_alive():
                _telemetry.counter("serving.stop_timeout").inc()
                _LOG.warning("serving: batcher did not drain within %.1fs",
                             timeout_s)
        self._started = False
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------ submit
    def _validate(self, entry, arr):
        if arr.ndim != len(entry.item_shape) + 1:
            raise ValueError(
                "model %r: request rank mismatch — exported signature is "
                "%s, got shape %s" % (entry.name,
                                      entry.predictor.signature(),
                                      tuple(arr.shape)))
        if tuple(arr.shape[1:]) != entry.item_shape:
            raise ValueError(
                "model %r: request item shape %s does not match the "
                "exported signature %s" % (entry.name, tuple(arr.shape),
                                           entry.predictor.signature()))
        if arr.dtype != entry.in_dtype:
            raise ValueError(
                "model %r: request dtype %s does not match the exported "
                "dtype %s" % (entry.name, arr.dtype, entry.in_dtype))
        if arr.shape[0] < 1:
            raise ValueError("model %r: empty request" % (entry.name,))

    def submit(self, name, data):
        """Enqueue one request (any row count) for model ``name``; returns
        a ``concurrent.futures.Future`` resolving to the host numpy output
        rows for exactly the submitted rows (padding is invisible)."""
        from . import tracing as _tracing
        from .ndarray.ndarray import NDArray
        with _tracing.span("serving.submit", cat="serving", model=name):
            entry = self._entry(name)
            arr = _np.asarray(data._data if isinstance(data, NDArray)
                              else data)
            self._validate(entry, arr)
            _telemetry.counter("serving.requests").inc()
            cap = entry.capacity
            if arr.shape[0] <= cap:
                return self._enqueue(_Request(name, arr, Future()))
            # oversized request: split into cap-row chunks, re-concatenate
            chunks = [arr[i:i + cap] for i in range(0, arr.shape[0], cap)]
            _telemetry.counter("serving.request_chunks").inc(len(chunks))
            futures = [self._enqueue(_Request(name, c, Future()))
                       for c in chunks]
            combined = Future()
            remaining = [len(futures)]
            lock = threading.Lock()

            def _one_done(_f):
                with lock:
                    remaining[0] -= 1
                    last = remaining[0] == 0
                if not last or combined.done():
                    return
                try:
                    combined.set_result(_np.concatenate(
                        [f.result() for f in futures], axis=0))
                except BaseException as exc:  # noqa: BLE001
                    combined.set_exception(exc)

            for f in futures:
                f.add_done_callback(_one_done)
            return combined

    def _enqueue(self, req):
        with self._cond:
            if self._stopping or not self._started:
                raise ServingError(
                    "server is %s; submit() rejected"
                    % ("stopping" if self._stopping else "not started"))
            self._pending.append(req)
            _telemetry.gauge("serving.pending").set(len(self._pending))
            self._cond.notify_all()
        return req.future

    def predict(self, name, data, timeout=None):
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(name, data).result(timeout)

    # ----------------------------------------------------------- batcher
    def _take_fitting(self, model, budget):
        """Pop the first queued request for ``model`` with rows <=
        ``budget`` (caller holds the condition lock)."""
        for i, req in enumerate(self._pending):
            if req.model == model and req.rows <= budget:
                del self._pending[i]
                return req
        return None

    def _loop(self):
        while True:
            with self._cond:
                while not self._pending:
                    if self._stopping:
                        return
                    self._cond.wait(timeout=0.05)
                first = self._pending.popleft()
                _telemetry.gauge("serving.pending").set(len(self._pending))
                entry = self._models.get(first.model)
            if entry is None:  # model evicted with requests in flight
                first.future.set_exception(ServingError(
                    "model %r was evicted while queued" % (first.model,)))
                continue
            batch = [first]
            rows = first.rows
            cap = entry.capacity
            deadline = first.t_submit + self.max_queue_delay_ms * 1e-3
            while rows < cap:
                with self._cond:
                    req = self._take_fitting(first.model, cap - rows)
                    if req is None:
                        remaining = deadline - _time.perf_counter()
                        if remaining <= 0 or self._stopping:
                            break
                        self._cond.wait(timeout=min(remaining, 0.005))
                        continue
                if req is not None:
                    batch.append(req)
                    rows += req.rows
            self._dispatch(entry, batch, rows)

    def _dispatch(self, entry, batch, rows):
        from . import tracing as _tracing
        t0 = _time.perf_counter()
        bucket = _io.pick_bucket(entry.buckets, rows) or entry.capacity
        for req in batch:
            _telemetry.timer("serving.queue_delay_ms").observe(
                (t0 - req.t_submit) * 1e3)
        try:
            cat = batch[0].data if len(batch) == 1 else \
                _np.concatenate([req.data for req in batch], axis=0)
            padded = _io.pad_rows_to(cat, bucket) if bucket > rows else cat
            with _tracing.span("serving.dispatch", cat="serving",
                               model=entry.name, requests=len(batch),
                               rows=rows, bucket=bucket):
                program = entry.programs.get(bucket)
                if program is None:
                    # a bucket registered after start(), or a fixed-batch
                    # artifact's single shape — compile once, then cached
                    program = entry.programs[bucket] = \
                        self._compile(entry, bucket)
                out = program(entry.predictor._params, padded)
            if isinstance(out, (tuple, list)):
                out = out[0]
            host = _np.asarray(out)
        except BaseException as exc:  # noqa: BLE001 — fail the batch's
            # futures, never the batcher thread itself
            _telemetry.counter("serving.dispatch_errors").inc()
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
            return
        t1 = _time.perf_counter()
        ofs = 0
        for req in batch:
            req.future.set_result(host[ofs:ofs + req.rows])
            ofs += req.rows
            _telemetry.timer("serving.request_ms").observe(
                (t1 - req.t_submit) * 1e3)
        _telemetry.counter("serving.batch_dispatches").inc()
        _telemetry.timer("serving.batch_fill").observe(rows / bucket)
        _telemetry.timer("serving.dispatch_ms").observe((t1 - t0) * 1e3)
        # one JSONL record per dispatch (no-op when the sink is off);
        # tools/telemetry_report.py folds these into the serving table and
        # the queue-delay anomaly check
        if _telemetry.enabled():
            _telemetry.log_event(
                "serving", model=entry.name, requests=len(batch),
                rows=rows, bucket=bucket,
                fill=round(rows / bucket, 4),
                queue_delay_ms=round(max(
                    (t0 - req.t_submit) * 1e3 for req in batch), 4),
                wall_ms=round((t1 - t0) * 1e3, 4),
                budget_ms=self.max_queue_delay_ms)

    # ------------------------------------------------------------- stats
    def stats(self):
        """Serving-slice snapshot of the telemetry registry (counters and
        timer histograms whose names start with ``serving.``)."""
        snap = _telemetry.snapshot()
        return {
            "counters": {k: v for k, v in snap["counters"].items()
                         if k.startswith("serving.")},
            "timers": {k: v for k, v in snap["timers"].items()
                       if k.startswith("serving.")},
            "models": self.models(),
        }


def load_server(prefixes, **kwargs):
    """Convenience: build, register and start a server from
    ``{name: prefix}``."""
    srv = Server(**kwargs)
    for name, prefix in dict(prefixes).items():
        srv.register(name, prefix)
    return srv.start()
