"""``mx.registry`` — generic class-registry helpers (reference:
python/mxnet/registry.py get_register_func/get_create_func, the machinery
behind the optimizer/initializer/lr_scheduler registries)."""
from __future__ import annotations

import json

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]

_REGISTRIES = {}


def _registry(base_class, nickname):
    return _REGISTRIES.setdefault((base_class, nickname), {})


def get_register_func(base_class, nickname):
    reg = _registry(base_class, nickname)

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            "cannot register %s as %s" % (klass, nickname)
        key = (name or klass.__name__).lower()
        reg[key] = klass
        return klass

    register.__doc__ = "Register a %s subclass." % nickname
    return register


def get_alias_func(base_class, nickname):
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def wrap(klass):
            for a in aliases:
                register(klass, a)
            return klass
        return wrap

    return alias


def get_create_func(base_class, nickname):
    reg = _registry(base_class, nickname)

    def create(*args, **kwargs):
        if args:
            name, args = args[0], args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            if args or kwargs:
                raise ValueError(
                    "%s is already an instance; extra arguments %r %r "
                    "would be silently dropped" % (nickname, args, kwargs))
            return name
        if name.startswith("{"):  # json spec {"nickname": ..., params...}
            spec = json.loads(name)
            name = spec.pop(nickname)
            kwargs.update(spec)
        key = name.lower()
        if key not in reg:
            raise ValueError("unknown %s %r (registered: %s)"
                             % (nickname, name, sorted(reg)))
        return reg[key](*args, **kwargs)

    create.__doc__ = "Create a %s instance by name." % nickname
    return create
