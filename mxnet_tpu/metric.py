"""Evaluation metrics.

Capability parity with ``python/mxnet/metric.py`` (EvalMetric registry:
Accuracy/TopK/F1/MCC/MAE/MSE/RMSE/CrossEntropy/NLL/Pearson/Perplexity/
Composite/Custom), re-designed around three pieces of shared machinery
instead of the reference's per-class accumulation fields:

* ``_Tally`` — one weighted-sum accumulator kept at two scopes (the
  resettable local window and the whole run), replacing the duplicated
  sum_metric/global_sum_metric bookkeeping;
* ``_Confusion`` — binary confusion COUNTS as 2x2 matrices per scope;
  precision/recall/F1/MCC are pure functions of a matrix;
* ``EvalMetric.update`` iterates (label, pred) pairs once and defers the
  per-pair math to ``_measure``, so most metrics are a single method.

Metric math runs on host numpy: updates are small reductions over already
materialized outputs, so keeping them off-device avoids recompiles and
device syncs in the training hot loop.
"""
from __future__ import annotations

import math

import numpy

from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "PCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def _alias(*names):
    def deco(klass):
        for n in names:
            _METRIC_REGISTRY[n.lower()] = klass
        return klass
    return deco


def create(metric, *args, **kwargs):
    """Create a metric from a name, callable, list, or instance."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        bundle = CompositeEvalMetric()
        for item in metric:
            bundle.add(create(item, *args, **kwargs))
        return bundle
    if isinstance(metric, str):
        klass = _METRIC_REGISTRY.get(metric.lower())
        if klass is None:
            raise ValueError("unknown metric %r (registered: %s)"
                             % (metric, sorted(_METRIC_REGISTRY)))
        return klass(*args, **kwargs)
    raise TypeError("metric should be str, callable, list or EvalMetric")


def _host(x):
    return x.asnumpy() if isinstance(x, NDArray) else numpy.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """Validate that label/pred collections (or arrays) line up."""
    a = labels.shape if shape else len(labels)
    b = preds.shape if shape else len(preds)
    if a != b:
        raise ValueError("labels %s do not match predictions %s" % (a, b))
    if wrap:
        labels = [labels] if isinstance(labels, NDArray) else labels
        preds = [preds] if isinstance(preds, NDArray) else preds
    return labels, preds


def _paired(labels, preds):
    """Yield (label, pred) numpy pairs from parallel collections."""
    if isinstance(labels, NDArray):
        labels = [labels]
    if isinstance(preds, NDArray):
        preds = [preds]
    if len(labels) != len(preds):
        raise ValueError("got %d labels for %d predictions"
                         % (len(labels), len(preds)))
    for label, pred in zip(labels, preds):
        yield _host(label), _host(pred)


class _Tally:
    """A weighted sum kept at two scopes: the resettable window ('local'
    in the reference API) and the whole run ('global')."""

    __slots__ = ("wsum", "n", "run_wsum", "run_n")

    def __init__(self):
        self.clear_all()

    def add(self, value, weight):
        self.wsum += value
        self.n += weight
        self.run_wsum += value
        self.run_n += weight

    def mean(self):
        return self.wsum / self.n if self.n else float("nan")

    def run_mean(self):
        return self.run_wsum / self.run_n if self.run_n else float("nan")

    def clear_window(self):
        self.wsum = 0.0
        self.n = 0

    def clear_all(self):
        self.wsum = 0.0
        self.n = 0
        self.run_wsum = 0.0
        self.run_n = 0


class EvalMetric:
    """Base metric.  Reference API surface (metric.py:43): update/
    update_dict, get/get_global, get_name_value, reset/reset_local; the
    accumulator behind it is a `_Tally` exposed through compatibility
    properties (sum_metric & co.)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._has_global_stats = kwargs.pop("has_global_stats", False)
        self._kwargs = kwargs
        self._tally = _Tally()
        self.reset()

    # -- compatibility accessors onto the tally ---------------------------
    @property
    def sum_metric(self):
        return self._tally.wsum

    @sum_metric.setter
    def sum_metric(self, v):
        self._tally.wsum = v

    @property
    def num_inst(self):
        return self._tally.n

    @num_inst.setter
    def num_inst(self, v):
        self._tally.n = v

    @property
    def global_sum_metric(self):
        return self._tally.run_wsum

    @global_sum_metric.setter
    def global_sum_metric(self, v):
        self._tally.run_wsum = v

    @property
    def global_num_inst(self):
        return self._tally.run_n

    @global_num_inst.setter
    def global_num_inst(self, v):
        self._tally.run_n = v

    # ---------------------------------------------------------------------
    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = dict(self._kwargs)
        config.update(metric=self.__class__.__name__, name=self.name,
                      output_names=self.output_names,
                      label_names=self.label_names)
        return config

    def update_dict(self, label, pred):
        pred = ([pred[n] for n in self.output_names]
                if self.output_names is not None else list(pred.values()))
        label = ([label[n] for n in self.label_names]
                 if self.label_names is not None else list(label.values()))
        self.update(label, pred)

    def update(self, labels, preds):
        """Default path: per-pair `_measure` -> weighted tally."""
        for label, pred in _paired(labels, preds):
            value, weight = self._measure(label, pred)
            self._tally.add(value, weight)

    def _measure(self, label, pred):
        """Return (value_sum, weight) for one label/pred pair."""
        raise NotImplementedError()

    def reset(self):
        self._tally.clear_all()

    def reset_local(self):
        self._tally.clear_window()

    def get(self):
        return (self.name, self._tally.mean())

    def get_global(self):
        if self._has_global_stats:
            return (self.name, self._tally.run_mean())
        return self.get()

    @staticmethod
    def _listify(pair):
        name, value = pair
        name = name if isinstance(name, list) else [name]
        value = value if isinstance(value, list) else [value]
        return list(zip(name, value))

    def get_name_value(self):
        return self._listify(self.get())

    def get_global_name_value(self):
        if self._has_global_stats:
            return self._listify(self.get_global())
        return self.get_name_value()

    # kept for subclasses/backwards-compat with the reference's protected API
    def _update(self, metric, inst):
        self._tally.add(metric, inst)


@register
@_alias("composite")
class CompositeEvalMetric(EvalMetric):
    """Several metrics updated and reported together."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("metric index %d out of range [0, %d)"
                              % (index, len(self.metrics)))

    def update_dict(self, labels, preds):
        if self.label_names is not None:
            labels = dict(zip(self.label_names, labels))
        if self.output_names is not None:
            preds = dict(zip(self.output_names, preds))
        for m in self.metrics:
            m.update_dict(labels, preds)

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def reset_local(self):
        for m in getattr(self, "metrics", []):
            m.reset_local()

    def _collect(self, getter):
        names, values = [], []
        for m in self.metrics:
            for n, v in self._listify(getter(m)):
                names.append(n)
                values.append(v)
        return (names, values)

    def get(self):
        return self._collect(lambda m: m.get())

    def get_global(self):
        return self._collect(lambda m: m.get_global())

    def get_config(self):
        config = super().get_config()
        config["metrics"] = [m.get_config() for m in self.metrics]
        return config


@register
@_alias("acc")
class Accuracy(EvalMetric):
    """Fraction of samples whose argmax prediction equals the label."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.axis = axis

    def _measure(self, label, pred):
        if pred.ndim > label.ndim:
            pred = numpy.argmax(pred, axis=self.axis)
        pred = pred.astype("int64").ravel()
        label = label.astype("int64").ravel()
        check_label_shapes(label, pred)
        return float((pred == label).sum()), label.size


@register
@_alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    """Fraction of samples whose label lands in the k highest scores.

    Ties are broken toward LOWER class indices (matching a stable
    descending sort of the scores), so the result is deterministic.
    """

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        if top_k <= 1:
            raise ValueError("TopKAccuracy needs top_k > 1 "
                             "(k==1 is plain Accuracy)")
        super().__init__("%s_%d" % (name, top_k), top_k=top_k,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self.top_k = top_k

    def _measure(self, label, pred):
        if pred.ndim == 1:
            pred = pred[None, :]
        if pred.ndim != 2:
            raise ValueError("TopKAccuracy expects (N,) or (N, C) scores, "
                             "got %s" % (pred.shape,))
        label = label.astype("int64").ravel()
        if label.shape[0] != pred.shape[0]:
            raise ValueError("label/pred batch mismatch: %d vs %d"
                             % (label.shape[0], pred.shape[0]))
        k = min(self.top_k, pred.shape[1])
        # stable argsort on the negated scores -> deterministic tie-breaks
        ranked = numpy.argsort(-pred.astype("float64"), axis=1,
                               kind="stable")[:, :k]
        hits = (ranked == label[:, None]).any(axis=1)
        return float(hits.sum()), label.shape[0]


# ----------------------------------------------------------- confusion f1

def _confusion_precision(m):
    tp, fp = m[1, 1], m[0, 1]
    return tp / (tp + fp) if tp + fp else 0.0


def _confusion_recall(m):
    tp, fn = m[1, 1], m[1, 0]
    return tp / (tp + fn) if tp + fn else 0.0


def _confusion_f1(m):
    p, r = _confusion_precision(m), _confusion_recall(m)
    return 2 * p * r / (p + r) if p + r else 0.0


def _confusion_mcc(m):
    tn, fp, fn, tp = m[0, 0], m[0, 1], m[1, 0], m[1, 1]
    if not m.sum():
        return 0.0
    denom = 1.0
    for t in ((tp + fp), (tp + fn), (tn + fp), (tn + fn)):
        if t:
            denom *= t
    return (tp * tn - fp * fn) / math.sqrt(denom)


class _Confusion:
    """Binary confusion counts, rows=truth cols=decision, window + run."""

    def __init__(self):
        self.window = numpy.zeros((2, 2))
        self.run = numpy.zeros((2, 2))

    def observe(self, label, pred):
        label = label.astype("int64").ravel()
        decided = pred.argmax(axis=1).astype("int64").ravel() \
            if pred.ndim == 2 else (pred.ravel() > 0.5).astype("int64")
        if label.shape != decided.shape:
            raise ValueError("label/pred shape mismatch: %s vs %s"
                             % (label.shape, decided.shape))
        if label.min(initial=0) < 0 or label.max(initial=0) > 1:
            raise ValueError("binary metrics need labels in {0, 1}")
        counts = numpy.zeros((2, 2))
        numpy.add.at(counts, (label, decided), 1)
        self.window += counts
        self.run += counts

    def clear_window(self):
        self.window[:] = 0

    def clear_all(self):
        self.window[:] = 0
        self.run[:] = 0


class _ConfusionMetric(EvalMetric):
    """Shared frame for F1 and MCC: feed the confusion object, then either
    average per-batch scores (macro) or score the cumulative matrix
    (micro)."""

    _score = None  # staticmethod(matrix -> float), set by subclass

    def __init__(self, name, output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self._conf = _Confusion()
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        for label, pred in _paired(labels, preds):
            self._conf.observe(label, pred)
        score = type(self)._score
        if self.average == "macro":
            # one data point per update() call; run scope scores the
            # cumulative matrix (reference semantics)
            self._tally.wsum += score(self._conf.window)
            self._tally.n += 1
            self._tally.run_wsum += score(self._conf.run)
            self._tally.run_n += 1
            self._conf.clear_window()
        else:
            self._tally.n = self._conf.window.sum()
            self._tally.run_n = self._conf.run.sum()

    def get(self):
        if self.average == "macro":
            return (self.name, self._tally.mean())
        if not self._conf.window.sum():
            return (self.name, float("nan"))
        return (self.name, type(self)._score(self._conf.window))

    def get_global(self):
        if self.average == "macro":
            return (self.name, self._tally.run_mean())
        if not self._conf.run.sum():
            return (self.name, float("nan"))
        return (self.name, type(self)._score(self._conf.run))

    def reset(self):
        super().reset()
        if hasattr(self, "_conf"):
            self._conf.clear_all()

    def reset_local(self):
        super().reset_local()
        self._conf.clear_window()


@register
class F1(_ConfusionMetric):
    """Binary F1 (harmonic mean of precision and recall)."""

    _score = staticmethod(_confusion_f1)

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, average=average)


@register
class MCC(_ConfusionMetric):
    """Matthews correlation coefficient over the binary confusion matrix."""

    _score = staticmethod(_confusion_mcc)

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, average=average)


# --------------------------------------------------------------- likelihood

def _picked_probs(label, pred):
    """Probability each sample's model assigned to its true class."""
    label = label.astype("int64").ravel()
    flat = pred.reshape(-1, pred.shape[-1])
    if label.shape[0] != flat.shape[0]:
        raise ValueError("label count %d != prediction rows %d"
                         % (label.shape[0], flat.shape[0]))
    return flat[numpy.arange(label.shape[0]), label], label


@register
class Perplexity(EvalMetric):
    """exp(mean negative log likelihood), optionally skipping a pad label."""

    def __init__(self, ignore_label, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label, axis=axis,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self.ignore_label = ignore_label
        self.axis = axis

    def _measure(self, label, pred):
        probs, label = _picked_probs(label, pred)
        if self.ignore_label is not None:
            keep = label != self.ignore_label
            probs = numpy.where(keep, probs, 1.0)
            count = int(keep.sum())
        else:
            count = label.size
        nll = -float(numpy.log(numpy.maximum(probs, 1e-10)).sum())
        return nll, count

    def get(self):
        m = self._tally.mean()
        return (self.name, math.exp(m) if m == m else float("nan"))

    def get_global(self):
        m = self._tally.run_mean()
        return (self.name, math.exp(m) if m == m else float("nan"))


@register
@_alias("ce")
class CrossEntropy(EvalMetric):
    """Mean -log p(true class) over predicted probability rows."""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.eps = eps

    def _measure(self, label, pred):
        probs, label = _picked_probs(label, pred)
        return float(-numpy.log(probs + self.eps).sum()), label.size


@register
@_alias("nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    """Alias semantics of CrossEntropy under the reference's nll name."""

    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


# --------------------------------------------------------------- regression

class _RegressionMetric(EvalMetric):
    """Per-batch error statistic of (label - pred)."""

    def __init__(self, name, output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    @staticmethod
    def _error(diff):
        raise NotImplementedError

    def _measure(self, label, pred):
        label = label.reshape(label.shape[0], -1)
        pred = pred.reshape(pred.shape[0], -1)
        n = pred.shape[0]
        return self._error(label - pred) * n, n


@register
class MAE(_RegressionMetric):
    """Mean absolute error."""

    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    @staticmethod
    def _error(diff):
        return float(numpy.abs(diff).mean())


@register
class MSE(_RegressionMetric):
    """Mean squared error."""

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    @staticmethod
    def _error(diff):
        return float((diff ** 2).mean())


@register
class RMSE(_RegressionMetric):
    """Root mean squared error (per batch, then averaged)."""

    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    @staticmethod
    def _error(diff):
        return float(numpy.sqrt((diff ** 2).mean()))


@register
@_alias("pearsonr")
class PearsonCorrelation(EvalMetric):
    """Pearson r; macro = mean per-batch r, micro = streaming moments."""

    def __init__(self, name="pearsonr", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def reset(self):
        super().reset()
        # shifted-moment accumulators for the micro (streaming) estimate;
        # moments are taken about a pivot (the first seen value) so the
        # n*Σxx - (Σx)² cancellation never sees large absolute magnitudes
        self._m = numpy.zeros(6)  # n, Σl, Σp, Σll, Σpp, Σlp  (pivot-shifted)
        self._pivot = None

    def update(self, labels, preds):
        for label, pred in _paired(labels, preds):
            check_label_shapes(label, pred, False, True)
            label = label.ravel().astype(numpy.float64)
            pred = pred.ravel().astype(numpy.float64)
            if self.average == "macro":
                self._tally.add(float(numpy.corrcoef(pred, label)[0, 1]), 1)
            else:
                if self._pivot is None:
                    self._pivot = (float(label[0]), float(pred[0])) \
                        if label.size else (0.0, 0.0)
                label = label - self._pivot[0]
                pred = pred - self._pivot[1]
                self._m += [label.size, label.sum(), pred.sum(),
                            (label * label).sum(), (pred * pred).sum(),
                            (label * pred).sum()]
                self._tally.add(0.0, 1)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        if self.average == "macro":
            return (self.name, self._tally.mean())
        n, sl, sp, sll, spp, slp = self._m
        cov = n * slp - sl * sp
        spread = math.sqrt(max(n * sll - sl * sl, 0.0)) * \
            math.sqrt(max(n * spp - sp * sp, 0.0))
        return (self.name, cov / spread if spread else float("nan"))


@register
class PCC(EvalMetric):
    """Multiclass Matthews/Pearson correlation from a streaming K x K
    confusion matrix (reference: metric.py:1473).

    Computed in the standard trace form: with s total samples, c the
    confusion trace, p_k predicted-class counts and t_k true-class counts,
    MCC = (c*s - p.t) / sqrt((s^2 - p.p)(s^2 - t.t)) — algebraically the
    K-class generalization of the binary MCC; the matrix grows on demand
    when new class ids appear."""

    def __init__(self, name="pcc", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def reset(self):
        super().reset()
        self._window = numpy.zeros((0, 0), numpy.float64)
        self._run = numpy.zeros((0, 0), numpy.float64)

    def reset_local(self):
        super().reset_local()
        self._window = numpy.zeros((0, 0), numpy.float64)

    @staticmethod
    def _grown(conf, k):
        if k <= conf.shape[0]:
            return conf
        out = numpy.zeros((k, k), numpy.float64)
        out[:conf.shape[0], :conf.shape[0]] = conf
        return out

    def update(self, labels, preds):
        for label, pred in _paired(labels, preds):
            label = numpy.asarray(_host(label)).ravel().astype(numpy.int64)
            p = numpy.asarray(_host(pred))
            pred_ids = p.argmax(-1).ravel().astype(numpy.int64) \
                if p.ndim > 1 and p.shape[-1] > 1 else \
                numpy.round(p.ravel()).astype(numpy.int64)
            check_label_shapes(label, pred_ids)
            k = int(max(label.max(), pred_ids.max())) + 1
            # each scope grows independently (after reset_local the window
            # is smaller than the run matrix), so scatter into each at its
            # own size
            self._window = self._grown(self._window, k)
            self._run = self._grown(self._run, k)
            numpy.add.at(self._window, (label, pred_ids), 1.0)
            numpy.add.at(self._run, (label, pred_ids), 1.0)
            self._tally.add(0.0, label.size)

    @staticmethod
    def _score(conf):
        s = conf.sum()
        if s == 0:
            return float("nan")
        c = numpy.trace(conf)
        t = conf.sum(axis=1)   # true-class counts
        p = conf.sum(axis=0)   # predicted-class counts
        denom = math.sqrt(max(s * s - (p * p).sum(), 0.0)) * \
            math.sqrt(max(s * s - (t * t).sum(), 0.0))
        return float((c * s - (t * p).sum()) / denom) if denom else 0.0

    def get(self):
        return (self.name, self._score(self._window))

    def get_global(self):
        return (self.name, self._score(self._run))


@register
class Loss(EvalMetric):
    """Average of an already-computed loss output."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            self._tally.add(float(_host(pred).sum()), pred.size)


@register
class Torch(Loss):
    """Compat alias kept for reference script parity."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    """Compat alias kept for reference script parity."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wraps feval(label, pred) -> value or (sum, count)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if "<" in name:
                name = "custom(%s)" % name
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels if isinstance(labels, list) else [labels],
                               preds if isinstance(preds, list) else [preds])
        for label, pred in _paired(labels, preds):
            out = self._feval(label, pred)
            if isinstance(out, tuple):
                self._tally.add(*out)
            else:
                self._tally.add(out, 1)

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Lift a bare numpy feval into a CustomMetric."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
