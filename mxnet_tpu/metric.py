"""Evaluation metrics.

Reference: ``python/mxnet/metric.py`` — ``EvalMetric`` registry with
Accuracy/TopK/F1/MCC/MAE/MSE/RMSE/CrossEntropy/NLL/Pearson/Perplexity/
Composite/Custom metrics, updated per batch by ``Module.update_metric`` or user
loops.  Metric math runs on host numpy: metric updates are small reductions
over already-materialized outputs, so keeping them off-device avoids recompiles
and device syncs in the training hot loop (compute the network on TPU, reduce
the scalar on host).
"""
from __future__ import annotations

import math

import numpy

from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def _alias(*names):
    def deco(klass):
        for n in names:
            _METRIC_REGISTRY[n.lower()] = klass
        return klass
    return deco


def create(metric, *args, **kwargs):
    """Create metric from name / callable / list / instance."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        name = metric.lower()
        if name not in _METRIC_REGISTRY:
            raise ValueError("Metric must be either callable or in registry; "
                             "got %s" % metric)
        return _METRIC_REGISTRY[name](*args, **kwargs)
    raise TypeError("metric should be str, callable, list or EvalMetric")


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return numpy.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(label_shape, pred_shape))
    if wrap:
        if isinstance(labels, NDArray):
            labels = [labels]
        if isinstance(preds, NDArray):
            preds = [preds]
    return labels, preds


class EvalMetric:
    """Base metric (reference: metric.py:43)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._has_global_stats = kwargs.pop("has_global_stats", False)
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            "metric": self.__class__.__name__,
            "name": self.name,
            "output_names": self.output_names,
            "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self._has_global_stats:
            if self.global_num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.global_sum_metric / self.global_num_inst)
        return self.get()

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_global_name_value(self):
        if self._has_global_stats:
            name, value = self.get_global()
            if not isinstance(name, list):
                name = [name]
            if not isinstance(value, list):
                value = [value]
            return list(zip(name, value))
        return self.get_name_value()

    def _update(self, metric, inst):
        self.sum_metric += metric
        self.num_inst += inst
        self.global_sum_metric += metric
        self.global_num_inst += inst


@register
@_alias("composite")
class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (reference: metric.py:369)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("Metric index {} is out of range 0 and {}".format(
                index, len(self.metrics)))

    def update_dict(self, labels, preds):
        if self.label_names is not None:
            labels = {name: label for name, label in zip(self.label_names, labels)}
        if self.output_names is not None:
            preds = {name: pred for name, pred in zip(self.output_names, preds)}
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def reset_local(self):
        try:
            for metric in self.metrics:
                metric.reset_local()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_global(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get_global()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_config(self):
        config = super().get_config()
        config.update({"metrics": [i.get_config() for i in self.metrics]})
        return config


@register
@_alias("acc")
class Accuracy(EvalMetric):
    """Classification accuracy (reference: metric.py:493)."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred_label in zip(labels, preds):
            pred_label = _as_numpy(pred_label)
            label = _as_numpy(label)
            if pred_label.ndim > label.ndim:
                pred_label = numpy.argmax(pred_label, axis=self.axis)
            pred_label = pred_label.astype("int32").ravel()
            label = label.astype("int32").ravel()
            check_label_shapes(label, pred_label)
            num_correct = (pred_label == label).sum()
            self._update(float(num_correct), len(pred_label))


@register
@_alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    """Top-k accuracy (reference: metric.py:560)."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, top_k=top_k, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) <= 2, "Predictions should be no more than 2 dims"
            pred_label = numpy.argsort(-_as_numpy(pred_label).astype("float32"),
                                       axis=-1, kind="stable")
            label = _as_numpy(label).astype("int32")
            check_label_shapes(label, pred_label)
            num_samples = pred_label.shape[0]
            num_dims = len(pred_label.shape)
            if num_dims == 1:
                num_correct = (pred_label.ravel() == label.ravel()).sum()
                self._update(float(num_correct), 0)
            elif num_dims == 2:
                num_classes = pred_label.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    num_correct = (pred_label[:, j].ravel() == label.ravel()).sum()
                    self._update(float(num_correct), 0)
            self._update(0.0, num_samples)


class _BinaryClassificationMetrics:
    """Running TP/FP/TN/FN tallies (reference: metric.py:640)."""

    def __init__(self):
        self.reset_stats()

    def update_binary_stats(self, label, pred):
        pred = _as_numpy(pred)
        label = _as_numpy(label).astype("int32")
        pred_label = numpy.argmax(pred, axis=1)
        check_label_shapes(label, pred)
        if len(numpy.unique(label)) > 2:
            raise ValueError("%s currently only supports binary classification."
                             % self.__class__.__name__)
        pred_true = (pred_label == 1)
        pred_false = 1 - pred_true
        label_true = (label == 1)
        label_false = 1 - label_true

        true_pos = (pred_true * label_true).sum()
        false_pos = (pred_true * label_false).sum()
        false_neg = (pred_false * label_true).sum()
        true_neg = (pred_false * label_false).sum()
        self.true_positives += true_pos
        self.global_true_positives += true_pos
        self.false_positives += false_pos
        self.global_false_positives += false_pos
        self.false_negatives += false_neg
        self.global_false_negatives += false_neg
        self.true_negatives += true_neg
        self.global_true_negatives += true_neg

    @property
    def precision(self):
        if self.true_positives + self.false_positives > 0:
            return float(self.true_positives) / (
                self.true_positives + self.false_positives)
        return 0.0

    @property
    def global_precision(self):
        if self.global_true_positives + self.global_false_positives > 0:
            return float(self.global_true_positives) / (
                self.global_true_positives + self.global_false_positives)
        return 0.0

    @property
    def recall(self):
        if self.true_positives + self.false_negatives > 0:
            return float(self.true_positives) / (
                self.true_positives + self.false_negatives)
        return 0.0

    @property
    def global_recall(self):
        if self.global_true_positives + self.global_false_negatives > 0:
            return float(self.global_true_positives) / (
                self.global_true_positives + self.global_false_negatives)
        return 0.0

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return 2 * self.precision * self.recall / (self.precision + self.recall)
        return 0.0

    @property
    def global_fscore(self):
        if self.global_precision + self.global_recall > 0:
            return (2 * self.global_precision * self.global_recall
                    / (self.global_precision + self.global_recall))
        return 0.0

    def matthewscc(self, use_global=False):
        if use_global:
            if not self.global_total_examples:
                return 0.0
            true_pos = float(self.global_true_positives)
            false_pos = float(self.global_false_positives)
            false_neg = float(self.global_false_negatives)
            true_neg = float(self.global_true_negatives)
        else:
            if not self.total_examples:
                return 0.0
            true_pos = float(self.true_positives)
            false_pos = float(self.false_positives)
            false_neg = float(self.false_negatives)
            true_neg = float(self.true_negatives)
        terms = [(true_pos + false_pos),
                 (true_pos + false_neg),
                 (true_neg + false_pos),
                 (true_neg + false_neg)]
        denom = 1.0
        for t in filter(lambda t: t != 0.0, terms):
            denom *= t
        return ((true_pos * true_neg) - (false_pos * false_neg)) / math.sqrt(denom)

    @property
    def total_examples(self):
        return (self.false_negatives + self.false_positives
                + self.true_negatives + self.true_positives)

    @property
    def global_total_examples(self):
        return (self.global_false_negatives + self.global_false_positives
                + self.global_true_negatives + self.global_true_positives)

    def reset_stats(self):
        self.false_positives = 0
        self.false_negatives = 0
        self.true_positives = 0
        self.true_negatives = 0
        self.global_false_positives = 0
        self.global_false_negatives = 0
        self.global_true_positives = 0
        self.global_true_negatives = 0

    def local_reset_stats(self):
        self.false_positives = 0
        self.false_negatives = 0
        self.true_positives = 0
        self.true_negatives = 0


@register
class F1(EvalMetric):
    """Binary F1 (reference: metric.py:761)."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        EvalMetric.__init__(self, name=name, output_names=output_names,
                            label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(label, pred)
        if self.average == "macro":
            self.sum_metric += self.metrics.fscore
            self.global_sum_metric += self.metrics.global_fscore
            self.num_inst += 1
            self.global_num_inst += 1
            self.metrics.local_reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * self.metrics.total_examples
            self.global_sum_metric = (self.metrics.global_fscore
                                      * self.metrics.global_total_examples)
            self.num_inst = self.metrics.total_examples
            self.global_num_inst = self.metrics.global_total_examples

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0.0
        self.global_num_inst = 0.0
        self.global_sum_metric = 0.0
        self.metrics.reset_stats()

    def reset_local(self):
        self.sum_metric = 0.0
        self.num_inst = 0.0
        self.metrics.local_reset_stats()


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (reference: metric.py:838)."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        self._average = average
        self._metrics = _BinaryClassificationMetrics()
        EvalMetric.__init__(self, name=name, output_names=output_names,
                            label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self._metrics.update_binary_stats(label, pred)
        if self._average == "macro":
            self.sum_metric += self._metrics.matthewscc()
            self.global_sum_metric += self._metrics.matthewscc(use_global=True)
            self.num_inst += 1
            self.global_num_inst += 1
            self._metrics.local_reset_stats()
        else:
            self.sum_metric = (self._metrics.matthewscc()
                               * self._metrics.total_examples)
            self.global_sum_metric = (self._metrics.matthewscc(use_global=True)
                                      * self._metrics.global_total_examples)
            self.num_inst = self._metrics.total_examples
            self.global_num_inst = self._metrics.global_total_examples

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0.0
        self.global_sum_metric = 0.0
        self.global_num_inst = 0.0
        self._metrics.reset_stats()

    def reset_local(self):
        self.sum_metric = 0.0
        self.num_inst = 0.0
        self._metrics.local_reset_stats()


@register
class Perplexity(EvalMetric):
    """Perplexity (reference: metric.py:941)."""

    def __init__(self, ignore_label, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label, axis=axis,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            assert label.size == pred.size / pred.shape[-1], \
                "shape mismatch: %s vs. %s" % (label.shape, pred.shape)
            label = label.reshape((label.size,)).astype("int32")
            probs = pred.reshape(-1, pred.shape[-1])[
                numpy.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                num -= int(numpy.sum(ignore))
                probs = probs * (1 - ignore) + ignore
            loss -= float(numpy.sum(numpy.log(numpy.maximum(1e-10, probs))))
            num += label.size
        self._update(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.global_sum_metric / self.global_num_inst))


@register
class MAE(EvalMetric):
    """Mean absolute error (reference: metric.py:1025)."""

    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            num = len(pred)
            mae = numpy.abs(label - pred).mean()
            self._update(mae * num, num)


@register
class MSE(EvalMetric):
    """Mean squared error (reference: metric.py:1079)."""

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            num = len(pred)
            mse = ((label - pred) ** 2.0).mean()
            self._update(mse * num, num)


@register
class RMSE(EvalMetric):
    """Root mean squared error (reference: metric.py:1133)."""

    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            num = len(pred)
            rmse = numpy.sqrt(((label - pred) ** 2.0).mean())
            self._update(rmse * num, num)


@register
@_alias("ce")
class CrossEntropy(EvalMetric):
    """Cross-entropy of predicted probabilities (reference: metric.py:1188)."""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            cross_entropy = (-numpy.log(prob + self.eps)).sum()
            self._update(cross_entropy, label.shape[0])


@register
@_alias("nll_loss")
class NegativeLogLikelihood(EvalMetric):
    """NLL (reference: metric.py:1254)."""

    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            label = label.ravel()
            num_examples = pred.shape[0]
            assert label.shape[0] == num_examples, (label.shape[0], num_examples)
            prob = pred[numpy.arange(num_examples), numpy.int64(label)]
            nll = (-numpy.log(prob + self.eps)).sum()
            self._update(nll, num_examples)


@register
@_alias("pearsonr")
class PearsonCorrelation(EvalMetric):
    """Pearson correlation (reference: metric.py:1320)."""

    def __init__(self, name="pearsonr", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        if self.average == "micro":
            self.reset_micro()

    def reset_micro(self):
        self._sse_p = 0
        self._mean_p = 0
        self._sse_l = 0
        self._mean_l = 0
        self._pred_nums = 0
        self._label_nums = 0
        self._conv = 0

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0
        if getattr(self, "average", None) == "micro":
            self.reset_micro()

    def update_variance(self, new_values, *aggregate):
        count = len(new_values)
        mean = numpy.mean(new_values)
        variance = numpy.sum((new_values - mean) ** 2)
        count_a, mean_a, var_a = aggregate
        delta = mean - mean_a
        m_a = var_a * (count_a - 1)
        m_b = variance * (count - 1)
        M2 = m_a + m_b + delta ** 2 * count_a * count / (count_a + count)
        count_a += count
        mean_a = (count_a * mean_a + count * mean) / count_a
        var_a = M2 / (count_a - 1)
        return count_a, mean_a, var_a

    def update_cov(self, label, pred):
        self._conv = self._conv + numpy.sum(
            (label - self._mean_l) * (pred - self._mean_p))

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            check_label_shapes(label, pred, False, True)
            label = _as_numpy(label).ravel().astype(numpy.float64)
            pred = _as_numpy(pred).ravel().astype(numpy.float64)
            if self.average == "macro":
                pearson_corr = numpy.corrcoef(pred, label)[0, 1]
                self._update(pearson_corr, 1)
            else:
                self.global_num_inst += 1
                self.num_inst += 1
                self._label_nums, self._mean_l, self._sse_l = \
                    self.update_variance(label, self._label_nums,
                                         self._mean_l, self._sse_l)
                self.update_cov(label, pred)
                self._pred_nums, self._mean_p, self._sse_p = \
                    self.update_variance(pred, self._pred_nums,
                                         self._mean_p, self._sse_p)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        if self.average == "macro":
            return (self.name, self.sum_metric / self.num_inst)
        n = self._label_nums
        numerator = self._conv
        denominator = (n - 1) * numpy.sqrt(self._sse_p) * numpy.sqrt(self._sse_l)
        pearsonr = numerator / denominator
        return (self.name, pearsonr)


@register
class Loss(EvalMetric):
    """Dummy metric averaging a loss output (reference: metric.py:1439)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = float(numpy.sum(_as_numpy(pred)))
            self._update(loss, pred.size)


@register
class Torch(Loss):
    """Compat alias (reference: metric.py:1466)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    """Compat alias (reference: metric.py:1474)."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Metric from a feval function (reference: metric.py:1482)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self._update(sum_metric, num_inst)
            else:
                self._update(reval, 1)

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval as a metric (reference: metric.py:1551)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
