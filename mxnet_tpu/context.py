"""Device contexts.

Reference design: ``include/mxnet/base.h`` Context {devtype, devid} with
``mx.cpu()/mx.gpu(i)`` constructors threaded through every NDArray and
executor.  TPU-native re-design: a Context is a *view onto a jax.Device*.
``mx.tpu(i)`` is the native accelerator context; ``mx.gpu(i)`` is kept as an
alias for accelerator i so reference training scripts (``ctx=mx.gpu(0)``) run
unmodified.  ``mx.cpu()`` maps to the host platform.

Unlike the reference there is no per-context stream/thread pool: XLA owns
scheduling on-device, and jax's async dispatch replaces the ThreadedEngine
(reference src/engine/threaded_engine_perdevice.cc:47-120).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context:
    """A device context. Compares by (device_type, device_id)."""

    # devtype codes kept for serialization parity (include/mxnet/base.h)
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in self.devstr2type:
            raise ValueError("unknown device type %r" % (device_type,))
        self.device_type = device_type
        self.device_id = device_id

    # -- jax bridge ---------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devstr2type[self.device_type]

    @property
    def jax_device(self) -> jax.Device:
        """Resolve to a concrete jax.Device."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = _platform_devices("cpu")
            if devs is None:
                # no cpu platform registered (rare) — fall back to default
                return jax.devices()[0]
            return devs[self.device_id % len(devs)]
        # 'gpu' is an accelerator alias: scripts written for mx.gpu(i) get chip i
        devs = _accelerator_devices()
        if not devs:
            raise MXNetErrorNoDevice(
                "no accelerator devices visible for ctx %r" % (self,)
            )
        if self.device_id >= len(devs):
            raise MXNetErrorNoDevice(
                "ctx %r out of range: %d accelerator device(s)" % (self, len(devs))
            )
        return devs[self.device_id]

    # -- context manager ----------------------------------------------------
    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default_ctx.stack.pop()

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    @classmethod
    def default_ctx(cls) -> "Context":
        stack = getattr(cls._default_ctx, "stack", None)
        if stack:
            return stack[-1]
        return _DEFAULT


class MXNetErrorNoDevice(RuntimeError):
    pass


def _platform_devices(platform: str):
    try:
        return jax.devices(platform)
    except RuntimeError:
        return None


_ACCEL_CACHE: Optional[list] = None


def _accelerator_devices():
    """All non-cpu devices; falls back to cpu devices when running CPU-only
    (e.g. the 8-virtual-device test mesh), so mx.tpu()/mx.gpu() still work."""
    global _ACCEL_CACHE
    if _ACCEL_CACHE is None:
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        _ACCEL_CACHE = devs if devs else list(jax.devices())
    return _ACCEL_CACHE


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Accelerator context; alias of tpu() for reference-script parity."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    return len(_accelerator_devices())


def num_tpus() -> int:
    return len(_accelerator_devices())


_DEFAULT = Context("tpu", 0)


def current_context() -> Context:
    return Context.default_ctx()


def ctx_from_device(dev: jax.Device) -> Context:
    if dev.platform == "cpu" and _accelerator_devices()[0].platform != "cpu":
        return Context("cpu", dev.id)
    # accelerator (or cpu-only world where cpu devices *are* the accelerators)
    accels = _accelerator_devices()
    try:
        return Context("tpu", accels.index(dev))
    except ValueError:
        return Context("cpu", dev.id)
