"""Autograd user API — record/pause scopes, backward, grad, custom Function.

Reference: python/mxnet/autograd.py:93-452 over MXAutograd* C API and
src/imperative/imperative.cc.  See _tape.py for the TPU-native tape design.
"""
from __future__ import annotations

from contextlib import contextmanager

import jax

from . import _tape
from .ndarray.ndarray import NDArray, _wrap

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "set_recording", "set_training"]


is_recording = _tape.is_recording
is_training = _tape.is_training
set_recording = _tape.set_recording
set_training = _tape.set_training


class _RecordingStateScope:
    def __init__(self, is_record, train_mode_):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode_
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = _tape.set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = _tape.set_training(self._enter_train_mode)

    def __exit__(self, *exc):
        if self._enter_is_record is not None:
            _tape.set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            _tape.set_training(self._prev_train_mode)


def record(train_mode=True):
    """Scope: ops executed inside are taped for backward()."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write"):
    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, r in zip(variables, gradients, grad_reqs):
        _tape.mark_variable(v, g, r)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and isinstance(head_grads, NDArray):
            head_grads = [head_grads]
    _tape.backward(heads, head_grads, retain_graph, train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return gradients of heads w.r.t. variables (without touching .grad).

    With ``create_graph=True`` the returned NDArrays are themselves on the
    tape, so they can be differentiated again (grad-of-grad; reference
    contract tests/python/unittest/test_higher_order_grad.py).  Without it
    the results are detached: re-recording on them treats them as constants
    w.r.t. the original inputs — use create_graph=True when a second-order
    gradient is wanted.
    """
    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
    if head_grads is not None and isinstance(head_grads, NDArray):
        head_grads = [head_grads]
    for v in variables:
        if not v._is_leaf:
            raise ValueError("variables passed to grad() must have attach_grad() "
                             "called or be marked variables")
    retain = retain_graph if retain_graph is not None else create_graph
    outs = _tape.grad_arrays(heads, variables, head_grads,
                             retain_graph=retain, create_graph=create_graph)
    import jax.numpy as jnp
    outs = [o if o is not None else _wrap(jnp.zeros(v.shape, v.dtype))
            for o, v in zip(outs, variables)]
    return outs


class Function:
    """User-defined differentiable function (reference autograd.Function,
    python/mxnet/autograd.py:370-452): subclass, implement forward(ctx-less)
    and backward; gradients flow through the tape."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        nd_inputs = [x for x in inputs if isinstance(x, NDArray)]
        outputs = self.forward(*inputs)
        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]
        if _tape.is_recording():
            def vjp_fn(cotangents):
                gs = self.backward(*[_wrap(c) for c in cotangents])
                if isinstance(gs, NDArray):
                    gs = [gs]
                return tuple(g._data if isinstance(g, NDArray) else g for g in gs)
            _tape.record_node(nd_inputs, outs, vjp_fn,
                              name=type(self).__name__)
        return outputs if multi else outs[0]
