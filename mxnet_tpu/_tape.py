"""Autograd tape.

Reference design: the imperative runtime records an NNVM node per op while
``autograd.record()`` is active (src/imperative/imperative.cc:193 RecordOp) and
builds + runs a backward graph on ``backward()`` (imperative.cc:280).

TPU-native re-design: instead of an NNVM graph replayed through a dependency
engine, each recorded eager op captures its cotangent function *at record time*
via ``jax.vjp`` — forward residuals live on-device as part of the vjp closure,
and ``backward()`` is a reverse topological walk accumulating cotangents with
``jnp.add``.  This keeps MXNet's define-by-run UX while the actual math is pure
XLA.  Whole hybridized blocks (CachedOp analog) record as a *single* node whose
vjp is the jit-compiled backward, mirroring CachedOp::Backward
(src/imperative/cached_op.cc).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

__all__ = [
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "Node",
    "record_node",
    "backward",
    "mark_variable",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _State()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    prev = _STATE.recording
    _STATE.recording = flag
    return prev


def set_training(flag: bool) -> bool:
    prev = _STATE.training
    _STATE.training = flag
    return prev


class Node:
    """One recorded op: inputs (NDArrays), outputs (NDArrays), vjp closure.

    ``vjp_fn(cotangents_tuple) -> tuple(input_cotangents)`` where cotangents
    align 1:1 with outputs/inputs.  ``None`` cotangents are allowed and mean
    "no gradient flows here".

    ``primal_fn`` (optional) is the pure jax function of the node's NDArray
    inputs.  It is what makes ``create_graph=True`` possible: the backward
    pass re-derives the vjp *as a jax function of (primals, cotangents)* and
    records its application as a fresh tape node, so gradient outputs stay
    differentiable to arbitrary order (reference contract:
    tests/python/unittest/test_higher_order_grad.py).
    """

    __slots__ = ("inputs", "outputs", "vjp_fn", "name", "_visited",
                 "primal_fn", "primal_multi", "hogr_error")

    def __init__(self, inputs, outputs, vjp_fn, name="", primal_fn=None,
                 primal_multi=False, hogr_error=None):
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.vjp_fn = vjp_fn
        self.name = name
        self._visited = False
        self.primal_fn = primal_fn
        self.primal_multi = primal_multi
        # set → this node cannot participate in create_graph=True: raising
        # beats the silent zero higher-order grads it would produce
        self.hogr_error = hogr_error


def record_node(inputs, outputs, vjp_fn, name="", primal_fn=None,
                primal_multi=False, hogr_error=None) -> Node:
    """Attach a new tape node to its output arrays."""
    node = Node(inputs, outputs, vjp_fn, name, primal_fn, primal_multi,
                hogr_error)
    for i, out in enumerate(node.outputs):
        out._tape_node = node
        out._tape_index = i
    return node


def mark_variable(arr, grad, grad_req="write"):
    arr._tape_node = None
    arr._tape_index = 0
    arr._grad = grad
    arr._grad_req = grad_req
    arr._is_leaf = True


def _toposort(roots: Sequence[Any]) -> List[Node]:
    """Reverse-topological order of tape nodes reachable from root arrays."""
    order: List[Node] = []
    seen = set()

    # iterative DFS to survive deep graphs (RNN unrolls)
    for root in roots:
        node = getattr(root, "_tape_node", None)
        if node is None or id(node) in seen:
            continue
        stack = [(node, iter(node.inputs))]
        seen.add(id(node))
        while stack:
            cur, it = stack[-1]
            advanced = False
            for inp in it:
                child = getattr(inp, "_tape_node", None)
                if child is not None and id(child) not in seen:
                    seen.add(id(child))
                    stack.append((child, iter(child.inputs)))
                    advanced = True
                    break
            if not advanced:
                order.append(cur)
                stack.pop()
    order.reverse()  # now parents (outputs) before children (inputs)
    return order


def _key(arr):
    node = getattr(arr, "_tape_node", None)
    return (id(node), arr._tape_index) if node is not None \
        else ("leaf", id(arr))


def _reverse_walk(outputs, head_grads, retain_graph, create_graph):
    """The single reverse-accumulation engine behind both ``backward`` and
    ``grad_arrays``.

    Returns (cotan, leaf_by_id): cotangents keyed by ``_key`` and every
    reachable leaf array.  In create_graph mode cotangents are NDArrays and
    all backward math is itself recorded on the tape (see
    ``_recorded_node_backward``); otherwise they are raw jax arrays.
    """
    import jax
    import jax.numpy as jnp
    from .ndarray.ndarray import _wrap

    outputs = list(outputs)
    if head_grads is None:
        head_grads = [None] * len(outputs)
    else:
        head_grads = list(head_grads)
        if len(head_grads) != len(outputs):
            raise ValueError("head_grads length mismatch")

    cotan = {}
    leaf_by_id = {}

    def _to_device_of(anchor, val):
        """Cross-device cotangent accumulation: shards computed on other
        devices (gluon split_and_load emulation over virtual cpus) meet
        here — insert the transfer the reference's per-ctx grad buffers +
        kvstore reduce performed (comm.h:451); same-device is a no-op."""
        try:
            a = anchor._data if hasattr(anchor, "_data") else anchor
            v = val._data if hasattr(val, "_data") else val
            if isinstance(a, jax.Array) and isinstance(v, jax.Array):
                ad, vd = a.devices(), v.devices()
                if ad != vd:
                    moved = jax.device_put(v, next(iter(ad)))
                    return _wrap(moved) if hasattr(val, "_data") else moved
        except Exception:  # noqa: BLE001 — tracers/uncommitted values
            pass
        return val

    def _acc(key, val):
        if val is None:
            return
        if key in cotan:
            prev = cotan[key]
            val = _to_device_of(prev, val)
            from .ndarray.sparse import RowSparseTangent
            if isinstance(prev, RowSparseTangent) or \
                    isinstance(val, RowSparseTangent):
                if isinstance(prev, RowSparseTangent) and \
                        isinstance(val, RowSparseTangent):
                    # sparse + sparse: concatenation IS the sum (duplicate
                    # rows are combined at consumption time)
                    cotan[key] = prev.concat(val)
                else:
                    sp, dn = (prev, val) if isinstance(
                        prev, RowSparseTangent) else (val, prev)
                    dn = dn._data if hasattr(dn, "_data") else dn
                    cotan[key] = jnp.add(sp.densify(), dn)
            else:
                cotan[key] = prev + val if create_graph else jnp.add(prev, val)
        else:
            cotan[key] = val

    for out, hg in zip(outputs, head_grads):
        if getattr(out, "_tape_node", None) is None and \
                not getattr(out, "_is_leaf", False):
            raise ValueError(
                "cannot differentiate output: it was not computed inside "
                "autograd.record() (reference: mxnet.autograd same contract)")
        g = hg if hg is not None else \
            _wrap(jnp.ones(out.shape, out._data.dtype))
        if create_graph and not hasattr(g, "_data"):
            g = _wrap(g)
        elif not create_graph and hasattr(g, "_data"):
            g = g._data
        _acc(_key(out), g)
        if getattr(out, "_is_leaf", False):
            leaf_by_id[id(out)] = out

    for node in _toposort(outputs):
        out_cts = [cotan.get((id(node), i))
                   for i in range(len(node.outputs))]
        if all(c is None for c in out_cts):
            continue
        # fill zeros for missing output cotangents (vjp needs a full tuple)
        from .ndarray.sparse import RowSparseTangent
        filled = []
        for arr, c in zip(node.outputs, out_cts):
            if c is not None and not isinstance(c, RowSparseTangent):
                # a vjp closure's residuals live on the node's OUTPUT
                # device; a cotangent accumulated on another (virtual)
                # device must transfer before the closure runs, or any
                # order of backward (incl. create_graph re-tapes) mixes
                # committed devices inside one jitted computation
                c = _to_device_of(arr, c)
            if c is None:
                z = jnp.zeros(arr.shape, arr._data.dtype)
                filled.append(_wrap(z) if create_graph else z)
            elif isinstance(c, RowSparseTangent):
                # a sparse cotangent reaching a generic vjp densifies at the
                # boundary (only the Embedding-weight leaf consumes sparse)
                d = c.densify()
                filled.append(_wrap(d) if create_graph else d)
            else:
                filled.append(c)
        if create_graph and node.primal_fn is not None:
            in_cts = _recorded_node_backward(node, filled)
        else:
            if create_graph and node.hogr_error:
                raise NotImplementedError(node.hogr_error)
            raw = tuple(f._data if hasattr(f, "_data") else f
                        for f in filled)
            in_cts = node.vjp_fn(raw)
            if create_graph:
                # opaque node (user Function / cached graph): values are
                # correct but the second-order chain detaches here
                in_cts = [None if c is None else _wrap(c) for c in in_cts]
        if len(in_cts) != len(node.inputs):
            raise RuntimeError(
                "vjp for %s returned %d cotangents for %d inputs"
                % (node.name, len(in_cts), len(node.inputs)))
        for inp, ct in zip(node.inputs, in_cts):
            if ct is None:
                continue
            if getattr(inp, "_is_leaf", False):
                leaf_by_id[id(inp)] = inp
                _acc(("leaf", id(inp)), ct)
            elif getattr(inp, "_tape_node", None) is not None:
                _acc(_key(inp), ct)
        if not (retain_graph or create_graph):
            node.vjp_fn = _freed_vjp(node.name)
    return cotan, leaf_by_id


def backward(outputs, head_grads=None, retain_graph=False, train_mode=True):
    """Run reverse accumulation from ``outputs``.

    Populates ``arr._grad`` on every reachable leaf marked via
    ``mark_variable`` (i.e. ``attach_grad``), honoring grad_req write/add.
    """
    import jax.numpy as jnp

    cotan, leaf_by_id = _reverse_walk(outputs, head_grads, retain_graph,
                                      create_graph=False)
    from .ndarray.sparse import (RowSparseTangent, RowSparseNDArray,
                                 _dedupe_rows)
    for arr in leaf_by_id.values():
        g = cotan.get(("leaf", id(arr)))
        if g is None:
            continue
        if arr._grad is None:
            continue  # marked with grad_req='null'
        if isinstance(g, RowSparseTangent):
            if isinstance(arr._grad, RowSparseNDArray):
                # sparse grad buffer (Parameter grad_stype="row_sparse"):
                # only the touched rows are ever stored
                if arr._grad_req == "add":
                    arr._grad._refresh_sparse()
                    idx = jnp.concatenate([arr._grad._indices, g.indices])
                    vals = jnp.concatenate([
                        jnp.reshape(arr._grad._values,
                                    (-1,) + g.values.shape[1:]),
                        g.values])
                    arr._grad._set_rows(*_dedupe_rows(idx, vals))
                else:
                    arr._grad._set_rows(*_dedupe_rows(g.indices, g.values))
                continue
            g = g.densify()
        # grads land on the LEAF's device: a cotangent computed on another
        # (virtual) device would otherwise poison the optimizer's eager
        # update with a mixed-device op
        try:
            import jax as _jax
            if isinstance(g, _jax.Array) and \
                    isinstance(arr._data, _jax.Array) and \
                    g.devices() != arr._data.devices():
                g = _jax.device_put(g, next(iter(arr._data.devices())))
        except Exception:  # noqa: BLE001 — uncommitted values
            pass
        if arr._grad_req == "add":
            arr._grad._data = jnp.add(arr._grad._data, g)
        else:
            arr._grad._data = jnp.asarray(g, dtype=arr._grad._data.dtype)


def _freed_vjp(name):
    def _raise(*_):
        raise RuntimeError(
            "graph for op %r already freed; pass retain_graph=True to backward() "
            "to backprop twice" % (name,)
        )

    return _raise


def _recorded_node_backward(node, filled_cts):
    """Apply one node's backward AS A RECORDED OP (create_graph path).

    Builds ``bwd(primals..., cotangents...) -> input_cotangents`` from the
    node's primal function, executes it, and records the application as a
    new tape node — its own vjp (via jax.vjp of bwd) differentiates through
    both the residuals and the cotangents, which is exactly what second-
    order gradients need.  Returns the input cotangents as NDArrays.
    """
    import jax
    import jax.numpy as jnp
    from .ndarray.ndarray import _wrap

    n_primal = len(node.inputs)
    primal_fn = node.primal_fn
    multi = node.primal_multi
    primal_dtypes = [inp._data.dtype for inp in node.inputs]

    def bwd(*args):
        primals, cts = args[:n_primal], args[n_primal:]
        _, vjp = jax.vjp(primal_fn, *primals)
        in_cts = vjp(tuple(cts) if multi else cts[0])
        # keep output arity/dtypes stable for jax.vjp over bwd itself:
        # float0 (int inputs) becomes a zeros placeholder
        return tuple(
            jnp.zeros(jnp.shape(p), jnp.float32)
            if getattr(c, "dtype", None) == jax.dtypes.float0 else c
            for c, p in zip(in_cts, args[:n_primal]))

    arg_vals = [inp._data for inp in node.inputs] + \
        [c._data for c in filled_cts]
    out_vals, vjp2 = jax.vjp(bwd, *arg_vals)
    outs = [_wrap(v) for v in out_vals]

    def vjp_fn(cotangents, _vjp=vjp2):
        in_cts = _vjp(tuple(cotangents))
        return tuple(None if getattr(c, "dtype", None) == jax.dtypes.float0
                     else c for c in in_cts)

    record_node(list(node.inputs) + list(filled_cts), outs, vjp_fn,
                name=node.name + "_backward", primal_fn=bwd,
                primal_multi=True)
    # int-dtype inputs get no gradient
    return [None if not jnp.issubdtype(dt, jnp.inexact) else o
            for o, dt in zip(outs, primal_dtypes)]


def grad_arrays(outputs, variables, head_grads=None, retain_graph=False,
                create_graph=False):
    """Reverse accumulation returning cotangents for ``variables`` directly.

    With ``create_graph=True`` every backward computation is itself recorded
    on the tape (accumulating adds included), so the returned NDArrays can be
    differentiated again — the TPU-native analog of the reference's
    ``MXAutogradBackwardEx(create_graph=1)``.  Nodes recorded without a
    primal function (user autograd.Function, cached hybrid graphs) fall back
    to their opaque vjp and DETACH the second-order chain at that point.
    """
    from .ndarray.ndarray import _wrap

    variables = list(variables)
    prev_rec = set_recording(True) if create_graph else None
    try:
        cotan, _ = _reverse_walk(outputs, head_grads, retain_graph,
                                 create_graph)
    finally:
        if prev_rec is not None:
            set_recording(prev_rec)
    from .ndarray.sparse import (RowSparseTangent, RowSparseNDArray,
                                 _dedupe_rows)
    results = []
    for v in variables:
        ct = cotan.get(("leaf", id(v)))
        if isinstance(ct, RowSparseTangent):
            idx, vals = _dedupe_rows(ct.indices, ct.values)
            ct = RowSparseNDArray(vals, idx, ct.shape)
        results.append(None if ct is None
                       else (ct if hasattr(ct, "_data") else _wrap(ct)))
    return results
