"""Autograd tape.

Reference design: the imperative runtime records an NNVM node per op while
``autograd.record()`` is active (src/imperative/imperative.cc:193 RecordOp) and
builds + runs a backward graph on ``backward()`` (imperative.cc:280).

TPU-native re-design: instead of an NNVM graph replayed through a dependency
engine, each recorded eager op captures its cotangent function *at record time*
via ``jax.vjp`` — forward residuals live on-device as part of the vjp closure,
and ``backward()`` is a reverse topological walk accumulating cotangents with
``jnp.add``.  This keeps MXNet's define-by-run UX while the actual math is pure
XLA.  Whole hybridized blocks (CachedOp analog) record as a *single* node whose
vjp is the jit-compiled backward, mirroring CachedOp::Backward
(src/imperative/cached_op.cc).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

__all__ = [
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "Node",
    "record_node",
    "backward",
    "mark_variable",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _State()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    prev = _STATE.recording
    _STATE.recording = flag
    return prev


def set_training(flag: bool) -> bool:
    prev = _STATE.training
    _STATE.training = flag
    return prev


class Node:
    """One recorded op: inputs (NDArrays), outputs (NDArrays), vjp closure.

    ``vjp_fn(cotangents_tuple) -> tuple(input_cotangents)`` where cotangents
    align 1:1 with outputs/inputs.  ``None`` cotangents are allowed and mean
    "no gradient flows here".
    """

    __slots__ = ("inputs", "outputs", "vjp_fn", "name", "_visited")

    def __init__(self, inputs, outputs, vjp_fn, name=""):
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.vjp_fn = vjp_fn
        self.name = name
        self._visited = False


def record_node(inputs, outputs, vjp_fn, name="") -> Node:
    """Attach a new tape node to its output arrays."""
    node = Node(inputs, outputs, vjp_fn, name)
    for i, out in enumerate(node.outputs):
        out._tape_node = node
        out._tape_index = i
    return node


def mark_variable(arr, grad, grad_req="write"):
    arr._tape_node = None
    arr._tape_index = 0
    arr._grad = grad
    arr._grad_req = grad_req
    arr._is_leaf = True


def _toposort(roots: Sequence[Any]) -> List[Node]:
    """Reverse-topological order of tape nodes reachable from root arrays."""
    order: List[Node] = []
    seen = set()

    # iterative DFS to survive deep graphs (RNN unrolls)
    for root in roots:
        node = getattr(root, "_tape_node", None)
        if node is None or id(node) in seen:
            continue
        stack = [(node, iter(node.inputs))]
        seen.add(id(node))
        while stack:
            cur, it = stack[-1]
            advanced = False
            for inp in it:
                child = getattr(inp, "_tape_node", None)
                if child is not None and id(child) not in seen:
                    seen.add(id(child))
                    stack.append((child, iter(child.inputs)))
                    advanced = True
                    break
            if not advanced:
                order.append(cur)
                stack.pop()
    order.reverse()  # now parents (outputs) before children (inputs)
    return order


def backward(outputs, head_grads=None, retain_graph=False, train_mode=True):
    """Run reverse accumulation from ``outputs``.

    Populates ``arr._grad`` on every reachable leaf marked via
    ``mark_variable`` (i.e. ``attach_grad``), honoring grad_req write/add.
    """
    import jax.numpy as jnp

    outputs = list(outputs)
    if head_grads is None:
        head_grads = [None] * len(outputs)
    else:
        head_grads = list(head_grads)
        if len(head_grads) != len(outputs):
            raise ValueError("head_grads length mismatch")

    # cotangent accumulator keyed by (id(node), out_index) plus leaves by id(arr)
    cotan = {}

    def _key(arr):
        return (id(arr._tape_node), arr._tape_index) if arr._tape_node is not None else ("leaf", id(arr))

    def _acc(key, val):
        if val is None:
            return
        if key in cotan:
            cotan[key] = jnp.add(cotan[key], val)
        else:
            cotan[key] = val

    leaf_by_id = {}

    for out, hg in zip(outputs, head_grads):
        if getattr(out, "_tape_node", None) is None and not getattr(out, "_is_leaf", False):
            raise ValueError(
                "cannot differentiate output: it was not computed inside "
                "autograd.record() (reference: mxnet.autograd same contract)"
            )
        g = hg._data if hasattr(hg, "_data") else hg
        if g is None:
            # MXNet defaults the head gradient to ones (autograd.py backward)
            g = jnp.ones(out.shape, out._data.dtype)
        _acc(_key(out), g)
        if getattr(out, "_is_leaf", False):
            leaf_by_id[id(out)] = out

    order = _toposort(outputs)

    for node in order:
        out_cts = tuple(cotan.get((id(node), i)) for i in range(len(node.outputs)))
        if all(c is None for c in out_cts):
            continue
        # fill zeros for missing output cotangents (vjp needs full tuple)
        filled = []
        for arr, c in zip(node.outputs, out_cts):
            if c is None:
                filled.append(jnp.zeros(arr.shape, arr._data.dtype))
            else:
                filled.append(c)
        in_cts = node.vjp_fn(tuple(filled))
        if len(in_cts) != len(node.inputs):
            raise RuntimeError(
                "vjp for %s returned %d cotangents for %d inputs"
                % (node.name, len(in_cts), len(node.inputs))
            )
        for inp, ct in zip(node.inputs, in_cts):
            if ct is None:
                continue
            if getattr(inp, "_is_leaf", False):
                leaf_by_id[id(inp)] = inp
                _acc(("leaf", id(inp)), ct)
            elif getattr(inp, "_tape_node", None) is not None:
                _acc((id(inp._tape_node), inp._tape_index), ct)
        if not retain_graph:
            node.vjp_fn = _freed_vjp(node.name)

    # write grads into leaves
    for arr in leaf_by_id.values():
        g = cotan.get(("leaf", id(arr)))
        if g is None:
            continue
        if arr._grad is None:
            continue  # marked with grad_req='null'
        if arr._grad_req == "add":
            arr._grad._data = jnp.add(arr._grad._data, g)
        else:
            arr._grad._data = jnp.asarray(g, dtype=arr._grad._data.dtype)


def _freed_vjp(name):
    def _raise(*_):
        raise RuntimeError(
            "graph for op %r already freed; pass retain_graph=True to backward() "
            "to backprop twice" % (name,)
        )

    return _raise
