"""mx.kernels — routing tier for the hand-written Pallas kernels.

The raw kernels live in ``ops/pallas_kernels.py`` and stay policy-free;
this module owns WHEN they run.  Reference analog: the graph optimizer
deciding when to swap a library op for a hand-fused RTC kernel
(src/common/rtc.cc + graph passes) — here the decision is an explicit
config knob plus a shape/platform feasibility check, because silent
kernel swaps are how frameworks grow haunted performance.

Routing contract (docs/PERF_NOTES.md "Kernel tier" + "Autotune"):

* the tier is ON by default since round 16, but a *default-source* knob
  is GATED: each routed site only takes a kernel after mx.perf.autotune
  proves bitwise-or-tolerance parity plus a measured speedup >= 1.0x on
  this device (``kernels.gated_fallback`` counts losing sites, which
  fall back to the XLA lowering permanently — the PR 11 AOT-rejection
  contract).  On interpreted backends the gate statically routes to
  XLA, so default-knob CPU programs stay byte-identical to the
  pre-tier lowering;
* an EXPLICIT ``kernels.enabled`` (env var or ``config.set``) bypasses
  the gate: off traces the exact pre-tier XLA ops (byte-identical
  programs); on routes supported shapes through the Pallas kernel
  (``kernels.flash_attention`` counter) with tuned block sizes when a
  winner is cached, falling back only on infeasible shapes
  (``kernels.fallback`` counter) — never an error;
* the decision is trace-time python, so a jitted program contains one
  path only; toggling the knob or landing a new autotune winner
  retraces (config epoch / autotune generation in the cache keys).

On CPU the kernels run through the Pallas interpreter — same numerics,
no TPU needed — which is what the parity gates in
``tools/check_kernels.py`` rely on.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

import contextlib

from . import config as _config
from . import telemetry as _telemetry
from .ops.pallas_kernels import (flash_attention, fused_adam_step,
                                 fused_sgd_step, pallas_paged_attention)

__all__ = ["enabled", "attention", "paged_attention",
           "flash_unsupported_reason", "paged_unsupported_reason",
           "record_paged_routes", "fused_step_enabled",
           "flash_attention", "pallas_paged_attention",
           "fused_sgd_step", "fused_adam_step", "measure"]

# one-row VMEM feasibility: a q block keeps its head's full K and V
# resident, so 2 * Skv * D * itemsize must fit the budget
_MAX_HEAD_DIM = 512


def enabled():
    """True when the kernel tier is switched on (``kernels.enabled`` /
    MXNET_TPU_KERNELS)."""
    return bool(_config.get("kernels.enabled"))


def fused_step_enabled(optimizer):
    """True when ``optimizer`` should update through its fused
    Pallas epilogue: tier on + the optimizer implements ``step_fused``
    + its step math is jit-safe + the autotune gate agrees (a
    default-source tier only fuses where the measured epilogue won;
    see mx.perf.autotune)."""
    if not (enabled()
            and getattr(optimizer, "fused_step", False)
            and getattr(optimizer, "jit_safe", True)):
        return False
    from . import autotune as _autotune
    pick = _autotune.fused_step_pick(optimizer)
    return pick is None or pick.get("impl") == "fused"


def note_fused_step():
    """Count one fused optimizer-epilogue launch (trace-time — counts
    program builds, not steps; the per-step signal is the program key)."""
    _telemetry.counter("kernels.fused_step").inc()


def flash_unsupported_reason(q, k, v, causal):
    """Why flash attention can NOT take this call, or None if it can.

    Trace-time shape/dtype checks only — everything here must be static
    under jit.  A non-None reason routes to the XLA fallback."""
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        return "rank != 4 (got q%s k%s v%s)" % (q.ndim, k.ndim, v.ndim)
    # jax.export shape polymorphism: symbolic dims can't answer the
    # block/budget comparisons below, and a kernel specialized to one
    # concrete shape defeats the point of a polymorphic artifact
    if not all(isinstance(d, int)
               for d in tuple(q.shape) + tuple(k.shape) + tuple(v.shape)):
        return "symbolic shape (q%s kv%s)" % (q.shape, k.shape)
    if k.shape != v.shape:
        return "k/v shapes differ: %s vs %s" % (k.shape, v.shape)
    if q.shape[:2] != k.shape[:2]:
        return "q/kv batch-head mismatch: %s vs %s" % (
            q.shape[:2], k.shape[:2])
    if q.shape[3] != k.shape[3]:
        return "q/kv head dim mismatch: %d vs %d" % (
            q.shape[3], k.shape[3])
    if causal and q.shape[2] != k.shape[2]:
        return "causal needs Sq == Skv, got %d vs %d" % (
            q.shape[2], k.shape[2])
    if q.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
        return "unsupported dtype %s" % q.dtype
    if q.shape[3] > _MAX_HEAD_DIM:
        return "head dim %d > %d" % (q.shape[3], _MAX_HEAD_DIM)
    # K + V of one (batch, head) slice must fit the per-block VMEM budget
    kv_bytes = 2 * k.shape[2] * k.shape[3] * k.dtype.itemsize
    budget = _config.get("kernels.vmem_budget")
    if kv_bytes > budget:
        return "kv slice %d bytes > vmem budget %d" % (kv_bytes, budget)
    return None


def attention(q, k, v, causal=False, scale=None):
    """Dot-product attention with kernel routing.

    Tier off → the plain XLA lowering (parallel.ring_attention.attention),
    traced identically to the pre-kernel-tier program.  Tier on →
    the fused Pallas flash kernel when the shape qualifies
    (``kernels.flash_attention`` counter; the tuned ``block_q`` applies
    when mx.perf.autotune has a winner for this site), the XLA lowering
    when the shape can't take the kernel (``kernels.fallback``) or when
    the default-source gate measured the kernel slower / not bit-close
    (``kernels.gated_fallback``)."""
    from .parallel.ring_attention import attention as _xla_attention
    if enabled():
        q = jnp.asarray(q)
        k = jnp.asarray(k)
        v = jnp.asarray(v)
        reason = flash_unsupported_reason(q, k, v, causal)
        if reason is None:
            from . import autotune as _autotune
            pick = _autotune.attention_pick(tuple(q.shape), tuple(k.shape),
                                            str(q.dtype), causal, scale)
            if pick is None or pick.get("impl") == "flash":
                _telemetry.counter("kernels.flash_attention").inc()
                bq = int(pick.get("block_q") or 128) if pick else 128
                return flash_attention(q, k, v, causal=causal,
                                       scale=scale, block_q=bq)
            # the measured gate lost (or the platform statically can't
            # win): the XLA lowering IS the winner for this site
            _telemetry.counter("kernels.gated_fallback").inc()
        else:
            _telemetry.counter("kernels.fallback").inc()
    return _xla_attention(q, k, v, causal=causal, scale=scale)


def paged_unsupported_reason(q, k, v, valid, quantized=False):
    """Why the Pallas paged-attention kernel can NOT take this decode
    call, or None if it can.  Trace-time shape/dtype checks only —
    everything here must be static under jit.  A non-None reason routes
    to the XLA lowering (``kernels.paged_fallback``) and is surfaced on
    the ``kernels.paged`` tracing span so perf_report can attribute
    decode time to kernel-vs-XLA."""
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        return "rank != 4 (got q%s k%s v%s)" % (q.ndim, k.ndim, v.ndim)
    # jax.export shape polymorphism: a symbolic batch/pool dim can't
    # answer the block/budget arithmetic below — decode programs that
    # want the kernel export with a concrete decode_batch (deploy v5)
    if not all(isinstance(d, int)
               for d in tuple(q.shape) + tuple(k.shape) + tuple(v.shape)
               + tuple(valid.shape)):
        return "symbolic shape (q%s kv%s)" % (q.shape, k.shape)
    if q.shape[2] != 1:
        return "needs one query row per sequence, got Sq=%d" % q.shape[2]
    if k.shape != v.shape:
        return "k/v shapes differ: %s vs %s" % (k.shape, v.shape)
    if q.shape[:2] != k.shape[:2]:
        return "q/kv batch-head mismatch: %s vs %s" % (
            q.shape[:2], k.shape[:2])
    if q.shape[3] != k.shape[3]:
        return "q/kv head dim mismatch: %d vs %d" % (
            q.shape[3], k.shape[3])
    if valid.shape != (q.shape[0], k.shape[2]):
        return "valid mask shape %s != (B, K)=%s" % (
            tuple(valid.shape), (q.shape[0], k.shape[2]))
    if q.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
        return "unsupported dtype %s" % q.dtype
    if quantized:
        if k.dtype != jnp.int8:
            return "quantized pages must be int8, got %s" % k.dtype
    elif k.dtype != q.dtype:
        return "q/kv dtype mismatch: %s vs %s" % (q.dtype, k.dtype)
    if q.shape[3] > _MAX_HEAD_DIM:
        return "head dim %d > %d" % (q.shape[3], _MAX_HEAD_DIM)
    # one (batch, head) row keeps its full gathered K and V resident
    kv_bytes = 2 * k.shape[2] * k.shape[3] * k.dtype.itemsize
    budget = _config.get("kernels.vmem_budget")
    if kv_bytes > budget:
        return "kv slice %d bytes > vmem budget %d" % (kv_bytes, budget)
    return None


# Export-time route capture: deploy.export_generation traces the decode
# program family under record_paged_routes() and lands the impl/reason of
# every routed paged site in the artifact meta — the serve path then
# counts kernels.paged_attention / paged_fallback per dispatch without
# re-tracing (the program is AOT; trace-time counters fire at export).
_PAGED_ROUTE_SINK = []


@contextlib.contextmanager
def record_paged_routes():
    """Collect ``{"impl", "reason", "quantized"}`` dicts for every paged
    route decision made while tracing under this context."""
    routes = []
    _PAGED_ROUTE_SINK.append(routes)
    try:
        yield routes
    finally:
        _PAGED_ROUTE_SINK.remove(routes)


def _note_paged_route(impl, reason, quantized):
    for routes in _PAGED_ROUTE_SINK:
        routes.append({"impl": impl, "reason": reason,
                       "quantized": bool(quantized)})


def _paged_attention_xla(q, k, v, valid, scale=None, k_scale=None,
                         v_scale=None):
    """The XLA paged-attention lowering — the pre-kernel-tier op
    sequence, byte-identical to what every release before the paged
    kernel traced.  The math mirrors ``parallel.ring_attention
    ._block_attn``: masked scores pin to the same ``-1e30`` floor, so
    masked keys contribute an EXACT ``0.0`` to both the softmax
    denominator and the value sum.  With ``k_scale``/``v_scale`` the
    int8 pages dequantize up front (one f32 broadcast multiply), the
    same f32 operands the kernel reconstructs in VMEM."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if k_scale is not None:
        k = k.astype(jnp.float32) * k_scale[..., None]
        v = v.astype(jnp.float32) * v_scale[..., None]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", e.astype(v.dtype), v)
    return (o / l.astype(o.dtype)).astype(q.dtype)


def paged_attention(q, k, v, valid, scale=None, k_scale=None,
                    v_scale=None):
    """Decode-step attention over a page-gathered context window.

    ``q`` is the single new query ``[B, H, 1, Dh]``; ``k``/``v`` are the
    context gathered through a request's page table ``[B, H, K, Dh]``
    (``K = page_table_width * page_size``, so slots past the sequence's
    true length hold stale or clipped-sentinel data); ``valid`` ``[B, K]``
    masks exactly the real positions.  With ``k_scale``/``v_scale``
    (``[B, H, K]`` f32 per-row scales from ``mx.quantization
    .quantize_rows``) the K/V operands are int8 KV pages and dequantize
    in the consumer — inside the kernel's VMEM pass, or up front on the
    XLA path.  Both lowerings pin masked scores to the ``-1e30`` floor
    of ``parallel.ring_attention._block_attn`` and track an unpadded
    forward bitwise-closely enough for greedy token parity
    (tools/check_generation.py enforces it).

    Routing (mirrors :func:`attention`): tier off → the plain XLA
    lowering, traced identically to the pre-kernel-tier program.  Tier
    on → the Pallas paged kernel when the shape qualifies
    (``kernels.paged_attention`` counter; the tuned ``block_bh`` applies
    when mx.perf.autotune has a "paged" winner for this site), the XLA
    lowering when the shape can't take the kernel
    (``kernels.paged_fallback``) or when the default-source gate
    measured the kernel slower / not bit-close
    (``kernels.gated_fallback``).  The decision and its reason land on a
    ``kernels.paged`` tracing span and, under
    :func:`record_paged_routes`, in the export route sink."""
    from . import tracing as _tracing
    quant = k_scale is not None
    if enabled():
        q = jnp.asarray(q)
        k = jnp.asarray(k)
        v = jnp.asarray(v)
        reason = paged_unsupported_reason(q, k, v, valid, quantized=quant)
        if reason is None:
            from . import autotune as _autotune
            pick = _autotune.paged_pick(tuple(q.shape), tuple(k.shape),
                                        str(q.dtype), quant, scale)
            if pick is None or pick.get("impl") == "paged":
                _telemetry.counter("kernels.paged_attention").inc()
                _note_paged_route("paged", None, quant)
                bb = pick.get("block_bh") if pick else None
                with _tracing.span("kernels.paged", cat="kernels",
                                   impl="paged", quantized=quant):
                    return pallas_paged_attention(
                        q, k, v, valid, scale=scale, k_scale=k_scale,
                        v_scale=v_scale,
                        block_bh=int(bb) if bb else None)
            # the measured gate lost (or the platform statically can't
            # win): the XLA lowering IS the winner for this site
            reason = pick.get("reason") or "autotune gate: xla won"
            _telemetry.counter("kernels.gated_fallback").inc()
        else:
            _telemetry.counter("kernels.paged_fallback").inc()
        with _tracing.span("kernels.paged", cat="kernels", impl="xla",
                           reason=reason, quantized=quant):
            _note_paged_route("xla", reason, quant)
            return _paged_attention_xla(q, k, v, valid, scale=scale,
                                        k_scale=k_scale, v_scale=v_scale)
    _note_paged_route("xla", "tier off", quant)
    return _paged_attention_xla(q, k, v, valid, scale=scale,
                                k_scale=k_scale, v_scale=v_scale)


def measure(key, fn, *args):
    """Register ``fn(*args)`` with mx.perf under the "kernels" family and
    run it once: returns ``(outputs, program_record)`` where the record
    carries cost_analysis FLOPs, phase times and the roofline bound.
    This is how bench/opperf secondaries report achieved FLOPs per op."""
    from . import perf as _perf
    wrapped = _perf.wrap(jax.jit(fn), "kernels", key)
    out = wrapped(*args)
    jax.block_until_ready(out)
    return out, _perf.program("kernels", key)
