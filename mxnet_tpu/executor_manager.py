"""``mx.executor_manager`` — legacy multi-device executor slicing helpers
(reference: python/mxnet/executor_manager.py DataParallelExecutorManager).

TPU-native: batch slicing across executors collapsed into the sharded jit
step (the mesh 'dp' axis); only `_split_input_slice` — the host-side batch
partitioner reference scripts import directly — keeps a real body.  The
manager class is Module's ExecutorGroup here (mxnet_tpu/module/).
"""
from __future__ import annotations

__all__ = ["_split_input_slice"]


def _split_input_slice(batch_size, work_load_list):
    """Slice a batch by per-device workloads (reference
    executor_manager.py:33)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        end = batch_size if i == len(work_load_list) - 1 else \
            start + int(round(batch_size * w / total))
        if end <= start:
            raise ValueError("too many slices: batch_size %d cannot cover "
                             "workloads %r" % (batch_size, work_load_list))
        slices.append(slice(start, end))
        start = end
    return slices
