"""Base utilities: dtype registry, error types, naming helpers.

TPU-native re-design of the dmlc/mshadow dtype plumbing the reference threads
through ``include/mxnet/base.h`` and ``3rdparty/mshadow/mshadow/base.h``.  Here
a dtype is simply a numpy/jax dtype; the integer type codes are kept only for
serialization parity with the reference's NDArray save format
(/root/reference/src/ndarray/ndarray.cc Save/Load).
"""
from __future__ import annotations

import numpy as _np

__all__ = [
    "MXNetError",
    "DTYPE_TO_CODE",
    "CODE_TO_DTYPE",
    "string_types",
    "numeric_types",
    "integer_types",
]


class MXNetError(RuntimeError):
    """Framework-level error (parity with the reference's dmlc::Error)."""


# Type codes follow mshadow/base.h kFloat32=0, kFloat64=1, kFloat16=2, kUint8=3,
# kInt32=4, kInt8=5, kInt64=6  (+ TPU-era addition: bfloat16=12 like MXNet 2.x).
DTYPE_TO_CODE = {
    _np.dtype("float32"): 0,
    _np.dtype("float64"): 1,
    _np.dtype("float16"): 2,
    _np.dtype("uint8"): 3,
    _np.dtype("int32"): 4,
    _np.dtype("int8"): 5,
    _np.dtype("int64"): 6,
    _np.dtype("bool"): 7,
}
try:  # bfloat16 is first-class on TPU
    import ml_dtypes as _ml

    DTYPE_TO_CODE[_np.dtype(_ml.bfloat16)] = 12
except Exception:  # pragma: no cover
    pass

CODE_TO_DTYPE = {v: k for k, v in DTYPE_TO_CODE.items()}

string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)


def dtype_np(dtype):
    """Normalize a user-provided dtype (str/np.dtype/None) to np.dtype.

    64-bit dtype posture (docs/MIGRATION.md): with x64 off (the TPU-native
    default — f64 has no MXU path), a requested int64/uint64/float64 is
    canonicalized to its 32-bit twin HERE, deliberately and silently; jax
    would otherwise truncate it anyway, with a warning per call site.
    ``mx.config.enable_x64()`` (MXTPU_ENABLE_X64) restores true 64-bit,
    matching the reference's MXNET_USE_INT64_TENSOR_SIZE build flag.
    """
    if dtype is None:
        return _np.dtype("float32")
    if isinstance(dtype, str) and dtype == "bfloat16":
        import ml_dtypes

        return _np.dtype(ml_dtypes.bfloat16)
    dt = _np.dtype(dtype)
    if dt.itemsize == 8 and dt.kind in "iuf":
        import jax

        if not jax.config.jax_enable_x64:
            dt = _np.dtype({"i": "int32", "u": "uint32",
                            "f": "float32"}[dt.kind])
    return dt
