"""mx.contrib.ndarray — alias of nd.contrib (reference keeps both paths)."""
from ..ndarray.contrib import __getattr__  # noqa: F401
