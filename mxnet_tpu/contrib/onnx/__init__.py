"""``mx.contrib.onnx`` — ONNX interchange with NO external onnx package.

Reference: python/mxnet/contrib/onnx/ (mx2onnx/export_model.py,
onnx2mx/import_model.py).  Like the reference — which implements its own
mx->onnx conversion rather than shelling out — this package carries its
own serialization: a vendored minimal ONNX schema (onnx_minimal.proto;
field numbers follow the public spec, so exported files load in any ONNX
runtime and standard ONNX files import here).

google.protobuf backs the (generated) serialization, so the submodules
load lazily: importing mxnet_tpu works on protobuf-less installs, and
only calling an ONNX function requires the runtime.

The TPU-native *deployment* format remains StableHLO
(mx.deploy.export_model / load_model — serialized XLA program + params);
ONNX is the cross-framework interchange surface.
"""
from __future__ import annotations

__all__ = ["import_model", "export_model", "get_model_metadata"]


def __getattr__(name):
    if name == "export_model":
        from .mx2onnx import export_model
        return export_model
    if name in ("import_model", "get_model_metadata"):
        from . import onnx2mx
        return getattr(onnx2mx, name)
    raise AttributeError(name)
