"""``mx.contrib.onnx`` — ONNX interchange (gated).

Reference: python/mxnet/contrib/onnx/ (import_model/export_model over the
onnx package).  The ``onnx`` package is not part of this environment, and
the TPU-native interchange format is StableHLO — ``mx.deploy.export_model``
/ ``load_model`` cover the deployment role (serialized compiler IR + params,
reloadable from any process or a C++ PjRt runtime).

When ``onnx`` IS installed, export works by round-tripping through the
StableHLO path is still preferred; import_model raises with guidance.
"""
from __future__ import annotations

__all__ = ["import_model", "export_model", "get_model_metadata"]

_GUIDANCE = (
    "the 'onnx' package is not available in this environment; the "
    "TPU-native interchange is StableHLO — use mx.deploy.export_model / "
    "mx.deploy.load_model (serialized XLA program + params). "
    "If you need ONNX specifically, install onnx and re-run."
)


def _require_onnx():
    try:
        import onnx  # noqa: F401
        return True
    except ImportError:
        raise ImportError(_GUIDANCE) from None


_INSTALLED_GUIDANCE = (
    "ONNX interchange is not implemented in this framework; the TPU-native "
    "format is StableHLO — use mx.deploy.export_model / mx.deploy.load_model "
    "(serialized XLA program + params, reloadable from any process)."
)


def import_model(model_file):
    """Reference: contrib/onnx/onnx2mx/import_model.py."""
    _require_onnx()
    raise NotImplementedError(_INSTALLED_GUIDANCE)


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False):
    """Reference: contrib/onnx/mx2onnx/export_model.py."""
    _require_onnx()
    raise NotImplementedError(_INSTALLED_GUIDANCE)


def get_model_metadata(model_file):
    _require_onnx()
    raise NotImplementedError(_INSTALLED_GUIDANCE)
