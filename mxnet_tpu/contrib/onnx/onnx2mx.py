"""ONNX ModelProto -> (Symbol, arg_params, aux_params).

Reference: python/mxnet/contrib/onnx/onnx2mx/import_model.py + the
_convert_map.  Parses through the vendored minimal schema — no onnx
package needed — and rebuilds a Symbol graph with mx.sym builders, so the
imported model runs through the ordinary Executor / SymbolBlock path.
"""
from __future__ import annotations

import numpy as _np

from . import onnx_minimal_pb2 as O

_ONNX_TO_NP = {1: _np.float32, 2: _np.uint8, 3: _np.int8, 6: _np.int32,
               7: _np.int64, 9: _np.bool_, 10: _np.float16,
               11: _np.float64}


def _tensor_to_np(t):
    dt = _ONNX_TO_NP.get(t.data_type, _np.float32)
    shape = tuple(t.dims)
    if t.raw_data:
        return _np.frombuffer(t.raw_data, dt).reshape(shape).copy()
    if t.float_data:
        return _np.asarray(t.float_data, dt).reshape(shape)
    if t.int64_data:
        return _np.asarray(t.int64_data, dt).reshape(shape)
    if t.int32_data:
        return _np.asarray(t.int32_data, dt).reshape(shape)
    return _np.zeros(shape, dt)


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == 1:
            out[a.name] = a.f
        elif a.type == 2:
            out[a.name] = a.i
        elif a.type == 3:
            out[a.name] = a.s.decode()
        elif a.type == 4:
            out[a.name] = _tensor_to_np(a.t)
        elif a.type == 6:
            out[a.name] = list(a.floats)
        elif a.type == 7:
            out[a.name] = list(a.ints)
        elif a.type == 8:
            out[a.name] = [s.decode() for s in a.strings]
    return out


def _halve_pads(pads):
    if not pads:
        return None
    k = len(pads) // 2
    begin, end = pads[:k], pads[k:]
    if list(begin) != list(end):
        raise NotImplementedError("asymmetric ONNX pads %r" % (pads,))
    return list(begin)


def _check_auto_pad(at, op):
    ap = at.get("auto_pad", "NOTSET")
    if ap not in ("", "NOTSET", "VALID"):  # VALID == explicit zero pads
        # SAME_UPPER/SAME_LOWER/VALID would import to wrong numerics if
        # silently dropped (ADVICE r4): the exporter must bake explicit pads.
        raise NotImplementedError(
            "ONNX import: %s auto_pad=%r is not supported (re-export with "
            "explicit pads)" % (op, ap))


def _imp_conv(node, sym_ins, at, mx, shapes):
    _check_auto_pad(at, "Conv")
    kernel = at["kernel_shape"]
    kw = dict(kernel=tuple(kernel),
              stride=tuple(at.get("strides", [1] * len(kernel))),
              dilate=tuple(at.get("dilations", [1] * len(kernel))),
              pad=tuple(_halve_pads(at.get("pads")) or [0] * len(kernel)),
              num_group=int(at.get("group", 1)),
              no_bias=len(sym_ins) < 3)
    w_shape = shapes.get(node.input[1])
    kw["num_filter"] = int(w_shape[0]) if w_shape else 0
    return mx.sym.Convolution(*sym_ins, **kw)


def _imp_gemm(node, sym_ins, at, mx, shapes):
    if int(at.get("transB", 0)) != 1 or at.get("alpha", 1.0) != 1.0 or \
            at.get("beta", 1.0) != 1.0:
        raise NotImplementedError("Gemm with nonstandard alpha/beta/trans")
    w_shape = shapes.get(node.input[1])
    return mx.sym.FullyConnected(
        *sym_ins, num_hidden=int(w_shape[0]) if w_shape else 0,
        no_bias=len(sym_ins) < 3, flatten=False)


def _imp_bn(node, sym_ins, at, mx, shapes):
    return mx.sym.BatchNorm(*sym_ins,
                            eps=float(at.get("epsilon", 1e-5)),
                            momentum=float(at.get("momentum", 0.9)),
                            fix_gamma=False)


def _imp_pool(op):
    def f(node, sym_ins, at, mx, shapes):
        if op.startswith("Global"):
            return mx.sym.Pooling(
                sym_ins[0], kernel=(1, 1), global_pool=True,
                pool_type="avg" if "Average" in op else "max")
        _check_auto_pad(at, op)
        if int(at.get("ceil_mode", 0)) != 0:
            raise NotImplementedError(
                "ONNX import: %s ceil_mode=1 is not supported (output "
                "shape would differ from floor-mode pooling)" % op)
        kernel = at["kernel_shape"]
        return mx.sym.Pooling(
            sym_ins[0], kernel=tuple(kernel),
            stride=tuple(at.get("strides", [1] * len(kernel))),
            pad=tuple(_halve_pads(at.get("pads")) or [0] * len(kernel)),
            pool_type="avg" if op == "AveragePool" else "max",
            # ONNX spec default EXCLUDES padding from the average (0)
            count_include_pad=bool(at.get("count_include_pad", 0)))
    return f


def _imp_act(mx_act):
    def f(node, sym_ins, at, mx, shapes):
        return mx.sym.Activation(sym_ins[0], act_type=mx_act)
    return f


def _imp_binary(mx_op):
    def f(node, sym_ins, at, mx, shapes):
        return getattr(mx.sym, mx_op)(sym_ins[0], sym_ins[1])
    return f


def _imp_softmax(node, sym_ins, at, mx, shapes):
    return mx.sym.softmax(sym_ins[0], axis=int(at.get("axis", -1)))


def _imp_flatten(node, sym_ins, at, mx, shapes):
    if int(at.get("axis", 1)) != 1:
        raise NotImplementedError(
            "ONNX import: Flatten axis=%d (only the default axis=1 maps "
            "to mx Flatten)" % int(at["axis"]))
    return mx.sym.Flatten(sym_ins[0])


def _imp_identity(node, sym_ins, at, mx, shapes):
    return mx.sym.identity(sym_ins[0])


def _imp_concat(node, sym_ins, at, mx, shapes):
    return mx.sym.Concat(*sym_ins, dim=int(at.get("axis", 1)))


def _imp_reshape(node, sym_ins, at, mx, shapes):
    shape = at.get("shape")
    return mx.sym.Reshape(sym_ins[0], shape=tuple(int(s) for s in shape))


def _imp_transpose(node, sym_ins, at, mx, shapes):
    return mx.sym.transpose(sym_ins[0], axes=tuple(at.get("perm", ())))


def _imp_leaky(node, sym_ins, at, mx, shapes):
    return mx.sym.LeakyReLU(sym_ins[0],
                            slope=float(at.get("alpha", 0.01)))


def _imp_gather(node, sym_ins, at, mx, shapes):
    if int(at.get("axis", 0)) != 0:
        raise NotImplementedError(
            "ONNX import: Gather with axis=%d (only axis=0 embedding "
            "lookups are supported)" % int(at["axis"]))
    w_shape = shapes.get(node.input[0])
    return mx.sym.Embedding(
        sym_ins[1], sym_ins[0],
        input_dim=int(w_shape[0]) if w_shape else 0,
        output_dim=int(w_shape[1]) if w_shape else 0)


def _imp_cast(node, sym_ins, at, mx, shapes):
    np_dt = _ONNX_TO_NP.get(int(at.get("to", 1)), _np.float32)
    if _np.issubdtype(np_dt, _np.integer):
        # an integer Cast whose only consumers are Gather is index
        # plumbing (Embedding casts internally); any other consumer means
        # real integer arithmetic this importer would silently break
        consumers = shapes.get("__consumers__", {}).get(
            node.output[0], set())
        if consumers - {"Gather"}:
            raise NotImplementedError(
                "ONNX import: integer Cast consumed by %s is not "
                "supported (only Gather index plumbing)"
                % sorted(consumers - {"Gather"}))
        return sym_ins[0]
    return mx.sym.cast(sym_ins[0], dtype=_np.dtype(np_dt).name)


_IMPORTERS = {
    "Conv": _imp_conv,
    "Gemm": _imp_gemm,
    "BatchNormalization": _imp_bn,
    "MaxPool": _imp_pool("MaxPool"),
    "AveragePool": _imp_pool("AveragePool"),
    "GlobalAveragePool": _imp_pool("GlobalAveragePool"),
    "GlobalMaxPool": _imp_pool("GlobalMaxPool"),
    "Relu": _imp_act("relu"),
    "Sigmoid": _imp_act("sigmoid"),
    "Tanh": _imp_act("tanh"),
    "Softsign": _imp_act("softsign"),
    "Softplus": _imp_act("softrelu"),
    "Add": _imp_binary("broadcast_add"),
    "Sub": _imp_binary("broadcast_sub"),
    "Mul": _imp_binary("broadcast_mul"),
    "Div": _imp_binary("broadcast_div"),
    "Softmax": _imp_softmax,
    "Flatten": _imp_flatten,
    "Reshape": _imp_reshape,
    "Identity": _imp_identity,
    "Dropout": _imp_identity,
    "Concat": _imp_concat,
    "Transpose": _imp_transpose,
    "LeakyRelu": _imp_leaky,
    "Gather": _imp_gather,
    "Cast": _imp_cast,
}


def import_model(model_file):
    """Load an ONNX file into (sym, arg_params, aux_params) — the
    reference import_model contract (onnx2mx/import_model.py:21)."""
    import mxnet_tpu as mx

    model = O.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph

    opset = max((o.version for o in model.opset_import
                 if o.domain in ("", "ai.onnx")), default=0)
    if 0 < opset < 13 and any(n.op_type == "Softmax" for n in g.node):
        import warnings
        warnings.warn(
            "ONNX import: file declares opset %d; Softmax before opset 13 "
            "flattened to 2D (axis default 1) — importing with opset-13 "
            "elementwise semantics (axis default -1)" % opset, stacklevel=2)

    inits = {t.name: _tensor_to_np(t) for t in g.initializer}
    params = dict(inits)
    tensors = {}
    shapes = {name: tuple(arr.shape) for name, arr in params.items()}
    consumers = {}
    shape_inputs, data_inputs = set(), set()
    for node in g.node:
        for pos, i in enumerate(node.input):
            consumers.setdefault(i, set()).add(node.op_type)
            if node.op_type == "Reshape" and pos == 1:
                shape_inputs.add(i)
            else:
                data_inputs.add(i)
    shapes["__consumers__"] = consumers
    # Initializers consumed only as Reshape shape operands are graph
    # plumbing, not bindable parameters (ADVICE r4): they are folded into
    # the Reshape attrs below and must not surface as Variables/arg_params.
    shape_only = {n for n in shape_inputs & set(params)
                  if n not in data_inputs}
    for n in shape_only:
        del params[n]
    for vi in g.input:
        if vi.name in params or vi.name in shape_only:
            continue
        tensors[vi.name] = mx.sym.Variable(vi.name)
    for name in params:
        tensors[name] = mx.sym.Variable(name)

    consts = inits  # shape tensors for Reshape etc. (incl. shape_only)
    for node in g.node:
        imp = _IMPORTERS.get(node.op_type)
        if imp is None:
            raise NotImplementedError(
                "ONNX import: unsupported op %r (supported: %s)"
                % (node.op_type, sorted(_IMPORTERS)))
        at = _attrs(node)
        if node.op_type == "Reshape" and len(node.input) > 1:
            shape_t = consts.get(node.input[1])
            if shape_t is None:
                raise NotImplementedError("Reshape with dynamic shape")
            at["shape"] = [int(s) for s in _np.asarray(shape_t).ravel()]
            ins = [tensors[node.input[0]]]
        else:
            ins = [tensors[i] for i in node.input]
        out = imp(node, ins, at, mx, shapes)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for name, s in zip(node.output, outs):
            tensors[name] = s

    heads = [tensors[vo.name] for vo in g.output]
    sym = heads[0] if len(heads) == 1 else mx.sym.Group(heads)

    # split params into arg/aux by the symbol's own classification
    aux_names = set(sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for name, arr in params.items():
        nd = mx.nd.array(arr)
        (aux_params if name in aux_names else arg_params)[name] = nd
    return sym, arg_params, aux_params


def get_model_metadata(model_file):
    """Input/output descriptors of an ONNX file (reference:
    onnx2mx/import_model.py:60 get_model_metadata)."""
    model = O.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph
    inits = {t.name for t in g.initializer}

    def desc(vis):
        out = []
        for vi in vis:
            if vi.name in inits:
                continue
            shape = tuple(d.dim_value for d in
                          vi.type.tensor_type.shape.dim)
            out.append((vi.name, shape))
        return out

    return {"input_tensor_data": desc(g.input),
            "output_tensor_data": desc(g.output)}
