"""Symbol graph -> ONNX ModelProto, with no external onnx dependency.

Reference: python/mxnet/contrib/onnx/mx2onnx/export_model.py + the
_op_translations table.  This implementation serializes through the
vendored minimal ONNX schema (onnx_minimal.proto — field numbers follow
the public spec, so the output loads in any ONNX runtime) instead of
requiring the onnx package.

Per-op converters live in _CONVERTERS; each takes (node, ctx) and appends
NodeProtos.  ctx carries name resolution (mx node -> ONNX tensor name),
the initializer list, and a helper to emit constant tensors.
"""
from __future__ import annotations

import numpy as _np

from . import onnx_minimal_pb2 as O

OPSET = 13

# ONNX TensorProto.DataType
_DT_FLOAT, _DT_INT32, _DT_INT64, _DT_FLOAT16 = 1, 6, 7, 10
_NP_TO_ONNX = {"float32": _DT_FLOAT, "int32": _DT_INT32,
               "int64": _DT_INT64, "float16": _DT_FLOAT16}

# AttributeProto.AttributeType
_AT_FLOAT, _AT_INT, _AT_STRING, _AT_TENSOR = 1, 2, 3, 4
_AT_FLOATS, _AT_INTS, _AT_STRINGS = 6, 7, 8


def _attr(name, value):
    a = O.AttributeProto(name=name)
    if isinstance(value, bool):
        a.type = _AT_INT
        a.i = int(value)
    elif isinstance(value, int):
        a.type = _AT_INT
        a.i = value
    elif isinstance(value, float):
        a.type = _AT_FLOAT
        a.f = value
    elif isinstance(value, str):
        a.type = _AT_STRING
        a.s = value.encode()
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            a.type = _AT_FLOATS
            a.floats.extend(value)
        else:
            a.type = _AT_INTS
            a.ints.extend(int(v) for v in value)
    else:
        raise TypeError("attr %r: %r" % (name, value))
    return a


def _tensor(name, arr):
    arr = _np.asarray(arr)
    t = O.TensorProto(name=name)
    t.dims.extend(arr.shape)
    dt = _NP_TO_ONNX.get(str(arr.dtype))
    if dt is None:
        arr = arr.astype(_np.float32)
        dt = _DT_FLOAT
    t.data_type = dt
    t.raw_data = arr.tobytes()
    return t


class _Ctx:
    def __init__(self, graph):
        self.graph = graph
        self.names = {}          # (node_id, index) -> onnx tensor name
        self.counter = 0

    def out_name(self, node):
        key = (id(node), getattr(node, "index", 0))
        if key not in self.names:
            if node.kind == "var":
                self.names[key] = node.name
            else:
                self.names[key] = "%s_%d" % (node.op, self.counter)
                self.counter += 1
        return self.names[key]

    def add_node(self, op_type, inputs, outputs, attrs=None, name=None):
        n = self.graph.node.add()
        n.op_type = op_type
        n.name = name or (op_type + "_" + outputs[0])
        n.input.extend(inputs)
        n.output.extend(outputs)
        for k, v in (attrs or {}).items():
            n.attribute.append(_attr(k, v))
        return n

    def const(self, arr, dtype=None):
        cname = "const_%d" % self.counter
        self.counter += 1
        a = _np.asarray(arr, dtype)
        self.graph.initializer.append(_tensor(cname, a))
        return cname


def _pads2(p):
    p = list(p)
    return p + p  # ONNX wants begin+end per spatial axis


def _conv(node, ins, out, ctx):
    at = node.attrs
    attrs = {"kernel_shape": list(at["kernel"]),
             "strides": list(at.get("stride") or [1] * len(at["kernel"])),
             "dilations": list(at.get("dilate") or [1] * len(at["kernel"])),
             "pads": _pads2(at.get("pad") or [0] * len(at["kernel"])),
             "group": int(at.get("num_group", 1))}
    ctx.add_node("Conv", ins[:2] if at.get("no_bias") else ins[:3],
                 [out], attrs)


def _fc(node, ins, out, ctx):
    at = node.attrs
    data = ins[0]
    if at.get("flatten", True):
        flat = out + "_flat"
        ctx.add_node("Flatten", [data], [flat], {"axis": 1})
        data = flat
    inputs = [data, ins[1]]
    if not at.get("no_bias"):
        inputs.append(ins[2])
    ctx.add_node("Gemm", inputs, [out],
                 {"alpha": 1.0, "beta": 1.0, "transB": 1})


def _bn(node, ins, out, ctx):
    at = node.attrs
    ctx.add_node("BatchNormalization", ins[:5], [out],
                 {"epsilon": float(at.get("eps", 1e-5)),
                  "momentum": float(at.get("momentum", 0.9))})


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softsign": "Softsign", "softrelu": "Softplus"}


def _activation(node, ins, out, ctx):
    ctx.add_node(_ACT[node.attrs.get("act_type", "relu")], ins[:1], [out])


def _pooling(node, ins, out, ctx):
    at = node.attrs
    ptype = at.get("pool_type", "max")
    if at.get("global_pool"):
        ctx.add_node("GlobalAveragePool" if ptype == "avg"
                     else "GlobalMaxPool", ins[:1], [out])
        return
    attrs = {"kernel_shape": list(at["kernel"]),
             "strides": list(at.get("stride") or [1] * len(at["kernel"])),
             "pads": _pads2(at.get("pad") or [0] * len(at["kernel"]))}
    if ptype == "avg":
        attrs["count_include_pad"] = 1 if at.get(
            "count_include_pad", True) else 0
        ctx.add_node("AveragePool", ins[:1], [out], attrs)
    else:
        ctx.add_node("MaxPool", ins[:1], [out], attrs)


def _binary(onnx_op):
    def conv(node, ins, out, ctx):
        ctx.add_node(onnx_op, ins[:2], [out])
    return conv


def _softmax(node, ins, out, ctx):
    ctx.add_node("Softmax", ins[:1], [out],
                 {"axis": int(node.attrs.get("axis", -1))})


def _flatten(node, ins, out, ctx):
    ctx.add_node("Flatten", ins[:1], [out], {"axis": 1})


def _dropout(node, ins, out, ctx):
    # inference graph: dropout is identity
    ctx.add_node("Identity", ins[:1], [out])


def _concat(node, ins, out, ctx):
    ctx.add_node("Concat", ins, [out],
                 {"axis": int(node.attrs.get("dim", 1))})


def _reshape(node, ins, out, ctx):
    dims = list(node.attrs.get("shape", (-1,)))
    if any(d < -1 for d in dims):
        # mx's -2/-3/-4 split/merge codes have no ONNX encoding; emitting
        # them verbatim would produce files other runtimes reject
        raise NotImplementedError(
            "ONNX Reshape supports only 0/-1 shape codes, got %r" % (dims,))
    shape = ctx.const(dims, _np.int64)
    ctx.add_node("Reshape", [ins[0], shape], [out])


def _transpose(node, ins, out, ctx):
    ctx.add_node("Transpose", ins[:1], [out],
                 {"perm": list(node.attrs.get("axes", ()))})


def _embedding(node, ins, out, ctx):
    # mx Embedding(data, weight) == Gather(weight, indices)
    idx64 = out + "_idx"
    ctx.add_node("Cast", [ins[0]], [idx64], {"to": _DT_INT64})
    ctx.add_node("Gather", [ins[1], idx64], [out], {"axis": 0})


_CONVERTERS = {
    "Convolution": _conv,
    "FullyConnected": _fc,
    "BatchNorm": _bn,
    "Activation": _activation,
    "Pooling": _pooling,
    "Flatten": _flatten,
    "flatten": _flatten,
    "softmax": _softmax,
    "SoftmaxOutput": _softmax,
    "SoftmaxActivation": _softmax,
    "Dropout": _dropout,
    "Concat": _concat,
    "concat": _concat,
    "Reshape": _reshape,
    "reshape": _reshape,
    "transpose": _transpose,
    "Embedding": _embedding,
    "broadcast_add": _binary("Add"),
    "elemwise_add": _binary("Add"),
    "broadcast_sub": _binary("Sub"),
    "elemwise_sub": _binary("Sub"),
    "broadcast_mul": _binary("Mul"),
    "elemwise_mul": _binary("Mul"),
    "broadcast_div": _binary("Div"),
    "elemwise_div": _binary("Div"),
    "relu": _activation,
    "sigmoid": lambda n, i, o, c: c.add_node("Sigmoid", i[:1], [o]),
    "tanh": lambda n, i, o, c: c.add_node("Tanh", i[:1], [o]),
    "LeakyReLU": lambda n, i, o, c: c.add_node(
        "LeakyRelu", i[:1], [o],
        {"alpha": float(n.attrs.get("slope", 0.25))}),
}


def export_model(sym, params, input_shapes, input_type=_np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Serialize (Symbol, params) to an ONNX file (reference:
    mx2onnx/export_model.py:export_model same signature).  Returns the
    path.  `params` maps both arg and aux names (arg:/aux: prefixes are
    stripped like the reference does)."""
    from ...symbol.symbol import _topo
    from ...ndarray.ndarray import NDArray

    clean = {}
    for k, v in (params or {}).items():
        k = k.split(":", 1)[-1]
        clean[k] = v.asnumpy() if isinstance(v, NDArray) else _np.asarray(v)

    model = O.ModelProto(ir_version=8, producer_name="mxnet_tpu",
                         producer_version="1.0")
    ops = model.opset_import.add()
    ops.domain = ""
    ops.version = OPSET
    graph = model.graph
    graph.name = "mxnet_tpu_graph"
    ctx = _Ctx(graph)

    nodes = _topo(sym)
    if isinstance(input_shapes, dict):
        shape_map = dict(input_shapes)
    else:
        shape_map = None
        shapes_list = [tuple(s) for s in (
            input_shapes if isinstance(input_shapes[0], (list, tuple))
            else [input_shapes])]
    free_vars = [n for n in nodes
                 if n.kind == "var" and n.name not in clean]
    if shape_map is None and len(free_vars) != len(shapes_list):
        raise ValueError(
            "export_model: %d input shapes given for %d free inputs (%s)"
            % (len(shapes_list), len(free_vars),
               [v.name for v in free_vars]))
    if shape_map is not None:
        missing = [v.name for v in free_vars if v.name not in shape_map]
        if missing:
            raise ValueError(
                "export_model: input_shapes dict missing free inputs %s"
                % missing)
    free_idx = 0
    onnx_dt = _NP_TO_ONNX[str(_np.dtype(input_type))]
    for n in nodes:
        if n.kind != "var":
            continue
        if n.name in clean:
            graph.initializer.append(_tensor(n.name, clean[n.name]))
        else:
            vi = graph.input.add()
            vi.name = n.name
            vi.type.tensor_type.elem_type = onnx_dt
            shp = (shape_map.get(n.name) if shape_map is not None
                   else shapes_list[free_idx])
            free_idx += 1
            for s in shp:
                d = vi.type.tensor_type.shape.dim.add()
                d.dim_value = int(s)

    for n in nodes:
        if n.kind != "op":
            continue
        conv = _CONVERTERS.get(n.op)
        if conv is None:
            raise NotImplementedError(
                "ONNX export: no converter for op %r (supported: %s)"
                % (n.op, sorted(_CONVERTERS)))
        from ...symbol.symbol import Symbol
        # None input slots (e.g. the bias of a no_bias FullyConnected) must
        # not become initializers; converters skip them by arity/attrs
        ins = [ctx.out_name(x) if isinstance(x, Symbol) else
               (None if x is None else ctx.const(x)) for x in n.inputs]
        conv(n, ins, ctx.out_name(n), ctx)
        if verbose:
            print("converted %s -> %s" % (n.op, ctx.out_name(n)))

    for h in sym._heads():
        vo = graph.output.add()
        vo.name = ctx.out_name(h)
        vo.type.tensor_type.elem_type = onnx_dt

    # drop orphan initializers no node consumes — a consumer would surface
    # them as spurious bindable params on import
    used = {i for node in graph.node for i in node.input}
    used |= {o.name for o in graph.output}
    kept = [t for t in graph.initializer if t.name in used]
    if len(kept) != len(graph.initializer):
        del graph.initializer[:]
        graph.initializer.extend(kept)

    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path
