"""mx.contrib.io (reference: python/mxnet/contrib/io.py DataLoaderIter —
wraps a gluon DataLoader as a classic mx.io DataIter)."""
from __future__ import annotations

from ..io import DataBatch

__all__ = ["DataLoaderIter"]


class DataLoaderIter:
    """Adapts ``gluon.data.DataLoader`` to the DataIter protocol so Module
    fit loops can consume Gluon datasets."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        from ..io import DataDesc
        self._loader = loader
        self.data_name = data_name
        self.label_name = label_name
        # Module.bind reads provide_data/provide_label (DataDesc protocol,
        # module/base_module.py) — peek one batch for the shapes and YIELD
        # it first, so single-pass iterables (generators) lose nothing
        self._iter = iter(loader)
        self._pending = next(self._iter, None)
        first = self._pending
        if first is None:
            self.provide_data, self.provide_label = [], []
        else:
            d = first[0] if isinstance(first, (list, tuple)) else first
            self.provide_data = [DataDesc(data_name, tuple(d.shape))]
            self.provide_label = (
                [DataDesc(label_name, tuple(first[1].shape))]
                if isinstance(first, (list, tuple)) and len(first) > 1
                else [])

    def reset(self):
        new_it = iter(self._loader)
        if new_it is self._iter:
            # single-pass iterable (generator): a real reset is impossible;
            # keep the peeked batch queued so nothing is lost
            return
        self._iter = new_it
        self._pending = None

    def __iter__(self):
        return self

    def __next__(self):
        if self._pending is not None:
            batch, self._pending = self._pending, None
        else:
            batch = next(self._iter)
        data, label = (batch[0], batch[1]) if isinstance(
            batch, (list, tuple)) else (batch, None)
        return DataBatch(data=[data],
                         label=[label] if label is not None else [])

    next = __next__
