"""TensorRT integration point (reference: python/mxnet/contrib/tensorrt.py).

No TPU counterpart exists BY DESIGN: TensorRT is an NVIDIA inference
engine; on TPU the inference engine is XLA itself, and the deployment
artifact is serialized StableHLO (see mxnet_tpu.deploy.export_model — the
analog of the reference's trt graph conversion + c_predict_api).  The
reference entry points raise with that redirection instead of silently
doing nothing.
"""
from __future__ import annotations

__all__ = ["init_tensorrt_params", "tensorrt_bind", "set_use_fp16"]

_MSG = ("TensorRT has no TPU counterpart; XLA is the inference engine. "
        "Use mxnet_tpu.deploy.export_model / load_model (StableHLO) for "
        "deployment, and mx.amp for reduced-precision inference.")


def tensorrt_bind(*_a, **_k):
    raise NotImplementedError(_MSG)


def init_tensorrt_params(*_a, **_k):
    raise NotImplementedError(_MSG)


def set_use_fp16(*_a, **_k):
    raise NotImplementedError(_MSG)
