"""TensorRT integration point (reference: python/mxnet/contrib/tensorrt.py).

The reference's TensorRT path takes a trained (symbol, params), hands
subgraphs to an inference engine, and returns an executor running the
optimized graph (plus an FP16 toggle).  The TPU-native engine is XLA
itself, so the same contract is honored with real behavior:

* ``tensorrt_bind(sym, all_params=..., data=shape)`` returns an Executor
  whose forward is the jit-fused inference graph — XLA plays TensorRT.
* ``set_use_fp16(True)`` (env ``MXNET_TENSORRT_USE_FP16``, same knob
  name as the reference) makes ``tensorrt_bind`` amp-convert the graph
  and params to bfloat16 first — the TPU's reduced-precision inference
  mode (``mx.amp``), standing in for TRT's FP16 engine.
* ``init_tensorrt_params`` returns the params unchanged (copies): the
  reference strips weights absorbed into TRT engine nodes
  (contrib/tensorrt.py:37); XLA consumes every param through the
  ordinary executor, so nothing is absorbed.

StableHLO export (``mxnet_tpu.deploy``) remains the ahead-of-time
deployment artifact; this module is the *bind-time* optimized-inference
API for scripts written against the reference.
"""
from __future__ import annotations

import os

__all__ = ["init_tensorrt_params", "tensorrt_bind", "set_use_fp16",
           "get_use_fp16"]


def set_use_fp16(status):
    """Toggle reduced-precision inference for tensorrt_bind (reference
    knob name kept; on TPU 'fp16' means bfloat16 via mx.amp)."""
    os.environ["MXNET_TENSORRT_USE_FP16"] = str(int(bool(status)))


def get_use_fp16():
    return os.environ.get("MXNET_TENSORRT_USE_FP16", "0") == "1"


def _normalize_params(params):
    """One params dict in either convention -> plain-name dict (the
    canonical 'arg:'/'aux:' split lives in mxnet_tpu.model)."""
    if any(k.startswith(("arg:", "aux:")) for k in params):
        from .. import model as _model
        arg, aux = _model.unpack_params(params)
        return {**arg, **aux}
    return dict(params)


def init_tensorrt_params(sym, arg_params, aux_params):
    """Reference: strips params absorbed into TRT engine nodes and
    returns the remainder.  XLA absorbs nothing — every param stays a
    bindable input — so the remainder is the full set (copied and
    prefix-normalized, matching the reference's copy semantics)."""
    return _normalize_params(arg_params), _normalize_params(aux_params)


def tensorrt_bind(symbol, ctx=None, all_params=None, type_dict=None,
                  grad_req="null", **kwargs):
    """Bind ``symbol`` for optimized inference and load ``all_params``
    into the executor (the historical mx.contrib.tensorrt.tensorrt_bind
    contract: shapes for non-param inputs arrive as kwargs, e.g.
    ``data=(32, 3, 224, 224)``)."""
    all_params = _normalize_params(all_params or {})
    arg_names = set(symbol.list_arguments())
    aux_names = set(symbol.list_auxiliary_states())
    arg_params = {k: v for k, v in all_params.items() if k in arg_names}
    aux_params = {k: v for k, v in all_params.items() if k in aux_names}

    if get_use_fp16():
        from .. import amp
        symbol, arg_params, aux_params = amp.convert_model(
            symbol, arg_params, aux_params, target_dtype="bfloat16")

    shape_kwargs = dict(kwargs)
    for name, arr in arg_params.items():
        shape_kwargs.setdefault(name, tuple(arr.shape))
    ex = symbol.simple_bind(ctx=ctx, grad_req=grad_req,
                            type_dict=type_dict, **shape_kwargs)
    ex.copy_params_from(arg_params, aux_params, allow_extra_params=True)
    return ex
