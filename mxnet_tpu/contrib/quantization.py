"""INT8 post-training quantization.

Reference: python/mxnet/contrib/quantization.py `quantize_model` (calib_mode
'naive' min/max or 'entropy' KL, :443-576) driving the C++ graph pass
(src/operator/quantization/quantize_graph_pass.cc) + calibrate.cc (KL
histogram) + int8 kernels.

TPU-native re-design: quantized FullyConnected/Convolution nodes execute as
REAL int8 — both operands are rounded onto the int8 grid, contracted with
``lax.dot_general``/``conv_general_dilated`` at int8 with s32 accumulation
(the MXU's native int8 path), then rescaled to f32 (ops/contrib.py
``_contrib_quantized_*``).  The quantize→int8-GEMM→dequantize chain is fused
inside one pure op so int8 tensors never cross node boundaries and XLA keeps
them on-chip.  Thresholds come from naive min/max or KL-divergence
calibration over a calibration iterator — the same calib modes and workflow
as the reference.

This module is the SYMBOLIC-ERA surface.  The deployment pipeline
(calibration runner -> int8-recolored StableHLO export -> quantized
serving) lives in ``mx.quantization`` (mxnet_tpu/quantization.py) and
reuses the calibration core here (``calib_thresholds``/``_kl_threshold``);
``quantize_model`` below is kept as a thin legacy shim over that shared
backend.  docs/QUANTIZATION.md.
"""
from __future__ import annotations

import numpy as _np
import jax.numpy as jnp

from ..ops.registry import register as _register_op

__all__ = ["quantize_model", "calib_thresholds", "quantize", "dequantize",
           "QUANTIZABLE_OPS"]


# primitive quantize/dequantize/_sim_quant ops live in ops/contrib.py so
# they register with every registry consumer (nd/sym/np) at package import.

def quantize(x, amax):
    """f32 -> (int8 grid simulated in f32).  Symmetric per-tensor."""
    scale = 127.0 / max(float(amax), 1e-12)
    return jnp.clip(jnp.round(jnp.asarray(x) * scale), -127, 127) / scale


def dequantize(q, amax):
    return q  # simulated-affine: values already on the f32 grid


# --------------------------------------------------------------- calibration

def _calib_fallback(reason):
    """Count a degenerate-histogram fallback to the naive amax
    (quantization.calib_fallback[.<reason>]) — the KL search has no
    meaningful distribution to optimize over."""
    from .. import telemetry as _telemetry
    _telemetry.counter("quantization.calib_fallback").inc()
    _telemetry.counter("quantization.calib_fallback.%s" % reason).inc()


def _kl_threshold(hist, edges, num_quantized_bins=255):
    """KL-divergence threshold search (reference: calibrate.cc entropy
    mode): pick the clip range minimizing KL(P||Q) between the f32
    histogram P and its int8-requantized image Q.

    Degenerate inputs — an all-zero histogram (no observed mass) or a
    single-bin distribution (a constant activation) — have no KL
    landscape to search: they return the naive amax (``edges[-1]``)
    directly and count a ``quantization.calib_fallback`` telemetry
    counter instead of risking divide-by-zero / arbitrary thresholds."""
    hist = hist.astype(_np.float64)
    if hist.sum() == 0:
        _calib_fallback("all_zero")
        return float(edges[-1])
    if (hist > 0).sum() <= 1:
        _calib_fallback("single_bin")
        return float(edges[-1])
    n = len(hist)
    best_kl, best_t = _np.inf, edges[-1]
    # scan candidate clip points from 1/8 of the range up
    for i in range(num_quantized_bins // 2, n + 1, max(1, n // 64)):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()  # clip mass into the edge bin
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = i / num_quantized_bins
        q = _np.zeros(i)
        for j in range(num_quantized_bins):
            lo = int(_np.floor(j * factor))
            hi = max(int(_np.floor((j + 1) * factor)), lo + 1)
            mass = hist[lo:min(hi, i)].sum()
            nz = (hist[lo:min(hi, i)] > 0).sum()
            if nz:
                q[lo:min(hi, i)] = _np.where(hist[lo:min(hi, i)] > 0,
                                             mass / nz, 0)
        p_n = p / p.sum()
        qs = q.sum()
        if qs == 0:
            continue
        q_n = q / qs
        mask = (p_n > 0) & (q_n > 0)
        kl = _np.sum(p_n[mask] * _np.log(p_n[mask] / q_n[mask]))
        if kl < best_kl:
            best_kl = kl
            best_t = edges[i] if i < len(edges) else edges[-1]
    return float(best_t)


def calib_thresholds(activations, mode="entropy", num_bins=4001):
    """Per-tensor |max| clip thresholds from collected activations.

    activations: {name: np.ndarray of samples}.  mode: 'naive' (min/max) or
    'entropy' (KL) — the reference's calib_mode values."""
    out = {}
    for name, arr in activations.items():
        a = _np.abs(_np.asarray(arr).ravel())
        # non-finite samples (a NaN-poisoned calibration batch) would
        # crash np.histogram / pin amax to inf — drop them first
        if a.size and not _np.isfinite(a).all():
            a = a[_np.isfinite(a)]
        if mode == "naive" or a.size == 0:
            out[name] = float(a.max()) if a.size else 1.0
            continue
        amax = float(a.max())
        if amax == 0:
            out[name] = 1.0
            continue
        hist, edges = _np.histogram(a, bins=num_bins, range=(0, amax))
        kl_t = _kl_threshold(hist, edges)
        # percentile floor: never clip more than 0.01% of observed mass —
        # guards small/sensitive models where pure KL over-clips
        floor = float(_np.percentile(a, 99.99))
        out[name] = max(kl_t, floor)
    return out


# ---------------------------------------------------------------- graph pass

QUANTIZABLE_OPS = {"FullyConnected", "Convolution"}


_QUANTIZED_OP = {"FullyConnected": "_contrib_quantized_fully_connected",
                 "Convolution": "_contrib_quantized_conv"}


def _input_key(x):
    return x.name if x.kind == "var" else "%s_output" % x.name


def _quantize_symbol(sym, thresholds, excluded_names):
    """Graph pass replacing quantizable ops with their REAL int8 versions
    (the quantize_graph_pass.cc analog): FullyConnected / Convolution
    become _contrib_quantized_* ops that quantize both operands to int8,
    contract with s32 accumulation on the MXU, and rescale to f32
    (ops/contrib.py).  Runs through the pluggable pass machinery
    (symbol/subgraph.py)."""
    from ..symbol.symbol import Symbol
    from ..symbol.subgraph import rewrite_nodes

    def swap(node, new_inputs):
        if node.op not in _QUANTIZED_OP or node.name in excluded_names:
            return None
        keys = [_input_key(x) for x in new_inputs[:2]
                if isinstance(x, Symbol)]
        # weight threshold always exists (from arg_params); a missing
        # DATA threshold (calib_mode='none') becomes amax_data=0 =
        # runtime range inside the quantized op
        if len(keys) != 2 or not thresholds.get(keys[1]):
            return None
        attrs = dict(node.attrs)
        attrs["amax_data"] = float(thresholds.get(keys[0], 0.0))
        attrs["amax_weight"] = float(thresholds[keys[1]])
        out = Symbol(node.kind, node.name, _QUANTIZED_OP[node.op], attrs,
                     new_inputs, node.index)
        out._attr_map = dict(node._attr_map)
        return out

    return rewrite_nodes(sym, swap)


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, excluded_sym_names=(), calib_mode="entropy",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=None, **kwargs):
    """The reference's one-call PTQ driver (contrib/quantization.py:443):
    collect activations over calib_data, compute thresholds, return
    (quantized symbol, params).  With calib_mode='none', only weights get
    quantized (dynamic activation range at runtime).

    .. deprecated::
        This is the LEGACY symbolic shim, kept with its original return
        contract for existing Module callers.  New code should use the
        deployment-grade backend this wraps — ``mx.quantization``:
        ``calibrate()`` + ``export_quantized()`` produce an int8-recolored
        StableHLO artifact (deploy format v3) that ``mx.serving`` AOT-
        compiles per pad bucket (docs/QUANTIZATION.md).  Both paths share
        the same calibration core (``calib_thresholds``/``_kl_threshold``
        below)."""
    from ..symbol.symbol import _topo

    thresholds = {}
    # weight thresholds directly from params
    for name, arr in arg_params.items():
        a = _np.abs(arr.asnumpy() if hasattr(arr, "asnumpy")
                    else _np.asarray(arr))
        thresholds[name] = float(a.max()) if a.size else 1.0

    if calib_mode != "none" and calib_data is not None:
        # tap every quantizable op's data input by evaluating internals
        internals = sym.get_internals()
        want = []
        input_vars = []
        for node in _topo(sym):
            if node.kind == "op" and node.op in QUANTIZABLE_OPS:
                x = node.inputs[0]
                if hasattr(x, "kind"):
                    if x.kind != "var":
                        want.append("%s_output" % x.name)
                    else:
                        input_vars.append(x.name)
        want = sorted(set(want))
        input_vars = set(input_vars)
        taps = {}
        seen = 0
        mod_outputs = [internals[n] for n in want] if want else []
        if mod_outputs:
            from ..module import Module
            from ..symbol.symbol import Group
            tap_sym = Group(mod_outputs)
            mod = Module(tap_sym, data_names=data_names, label_names=[])
            first = next(iter(calib_data))
            calib_data.reset()
            mod.bind([(n, tuple(d.shape)) for n, d in
                      zip(data_names, first.data)], for_training=False)
            mod.set_params(arg_params, aux_params, allow_missing=True)
            for batch in calib_data:
                mod.forward(batch, is_train=False)
                for name, out in zip(want, mod.get_outputs()):
                    taps.setdefault(name, []).append(out.asnumpy())
                for dname, d in zip(data_names, batch.data):
                    if dname in input_vars:
                        taps.setdefault(dname, []).append(d.asnumpy())
                seen += batch.data[0].shape[0]
                if num_calib_examples and seen >= num_calib_examples:
                    break
            calib_data.reset()
        elif input_vars:
            # quantizable ops fed directly by graph inputs: calibrate the
            # input ranges from the calibration batches alone
            for batch in calib_data:
                for dname, d in zip(data_names, batch.data):
                    if dname in input_vars:
                        taps.setdefault(dname, []).append(d.asnumpy())
                seen += batch.data[0].shape[0]
                if num_calib_examples and seen >= num_calib_examples:
                    break
            calib_data.reset()
        acts = {k: _np.concatenate(v) for k, v in taps.items()}
        thresholds.update(calib_thresholds(acts, mode=calib_mode))

    qsym = _quantize_symbol(sym, thresholds, set(excluded_sym_names))
    return qsym, arg_params, aux_params


# register on the pluggable pass registry (symbol/subgraph.py) so scripts can
# run `mx.sym.subgraph.apply_pass(sym, "QuantizeGraph", thresholds=...)`
from ..symbol.subgraph import register_pass as _register_pass  # noqa: E402


@_register_pass("QuantizeGraph")
def _quantize_graph_pass(sym, thresholds=None, excluded_names=(), **_):
    return _quantize_symbol(sym, thresholds or {}, set(excluded_names))
