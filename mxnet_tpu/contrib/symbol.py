"""mx.contrib.symbol — alias of sym.contrib (reference keeps both paths)."""
from ..symbol.contrib import __getattr__  # noqa: F401
