"""mx.contrib.autograd — the reference keeps a deprecated contrib autograd
module forwarding to mxnet.autograd (python/mxnet/contrib/autograd.py);
same here."""
from ..autograd import *  # noqa: F401,F403
from ..autograd import record, pause, is_training, is_recording  # noqa: F401
