"""Pretrained token embeddings.

Reference: python/mxnet/contrib/text/embedding.py — a registry of embedding
formats (glove, fasttext) that download + parse pretrained vector files,
plus CustomEmbedding for local files and CompositeEmbedding.

TPU-native note: this environment has zero egress, so the download half of
the reference (``pretrained_file_name`` fetch) raises with guidance; the
FILE-parsing half — the part models actually consume — is fully functional:
any GloVe/fastText-format text file loads into a (vocab_size, dim) device
array aligned with a Vocabulary.
"""
from __future__ import annotations

import io
import os

import numpy as _np

from ...ndarray.ndarray import NDArray, _wrap

__all__ = ["register", "create", "list_embedding_names", "TokenEmbedding",
           "CustomEmbedding", "CompositeEmbedding", "GloVe", "FastText"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(embedding_name, **kwargs):
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise KeyError("unknown embedding %r (have %s)"
                       % (embedding_name, sorted(_REGISTRY)))
    return _REGISTRY[name](**kwargs)


def list_embedding_names():
    return sorted(_REGISTRY)


class TokenEmbedding:
    """Token -> vector lookup parsed from a text file of
    ``token v1 v2 ... vD`` lines (the GloVe/fastText interchange format)."""

    def __init__(self, pretrained_file_path=None, elem_delim=" ",
                 init_unknown_vec=None, vocabulary=None, **kwargs):
        import jax.numpy as jnp
        self._token_to_idx = {}
        self._idx_to_token = []
        self._vec_len = None
        self._init_unknown = init_unknown_vec or (lambda d: _np.zeros(d))
        vectors = []
        if pretrained_file_path is not None:
            if not os.path.exists(pretrained_file_path):
                raise OSError(
                    "pretrained file %r not found. This environment has no "
                    "network egress: download GloVe/fastText files "
                    "out-of-band and point pretrained_file_path at them "
                    "(the reference's auto-download cannot run here)."
                    % pretrained_file_path)
            def _num(s):
                try:
                    float(s)
                    return True
                except ValueError:
                    return False

            first = True
            with io.open(pretrained_file_path, encoding="utf-8") as f:
                for line in f:
                    parts = line.rstrip().split(elem_delim)
                    if first and len(parts) == 2 and all(map(_num, parts)):
                        first = False
                        continue  # fastText "count dim" header
                    first = False
                    if len(parts) < 2:
                        continue  # malformed line
                    token, vals = parts[0], parts[1:]
                    if self._vec_len is None:
                        self._vec_len = len(vals)
                    elif len(vals) != self._vec_len:
                        continue
                    if token in self._token_to_idx:
                        continue
                    self._token_to_idx[token] = len(self._idx_to_token)
                    self._idx_to_token.append(token)
                    vectors.append(_np.asarray(vals, _np.float32))
        self._mat = jnp.asarray(_np.stack(vectors)) if vectors else None
        self._vocab = vocabulary
        if vocabulary is not None:
            self._mat = self._build_for_vocab(vocabulary)

    def _build_for_vocab(self, vocab):
        import jax.numpy as jnp
        dim = self.vec_len
        # ONE device->host copy, then host-side row assembly (per-token
        # device gathers would be a round-trip per vocab entry)
        mat_np = _np.asarray(self._mat) if self._mat is not None else None
        rows = _np.zeros((len(vocab), dim), _np.float32)
        unk = _np.asarray(self._init_unknown(dim), _np.float32)
        for i, token in enumerate(vocab.idx_to_token):
            j = self._token_to_idx.get(token)
            rows[i] = mat_np[j] if j is not None else unk
        return jnp.asarray(rows)

    @property
    def vec_len(self):
        return self._vec_len or 0

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def idx_to_vec(self):
        return _wrap(self._mat) if self._mat is not None else None

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        import jax.numpy as jnp
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        rows = []
        lookup = self._vocab.token_to_idx if self._vocab is not None \
            else self._token_to_idx
        for t in toks:
            j = lookup.get(t)
            if j is None and lower_case_backup:
                j = lookup.get(t.lower())
            if j is None:
                rows.append(_np.asarray(self._init_unknown(self.vec_len),
                                        _np.float32))
            else:
                rows.append(_np.asarray(self._mat[j]))
        out = jnp.asarray(_np.stack(rows))
        return _wrap(out[0] if single else out)

    def update_token_vectors(self, tokens, new_vectors):
        import jax.numpy as jnp
        toks = [tokens] if isinstance(tokens, str) else tokens
        vecs = new_vectors._data if isinstance(new_vectors, NDArray) \
            else jnp.asarray(new_vectors)
        if vecs.ndim == 1:
            vecs = vecs[None, :]
        lookup = self._vocab.token_to_idx if self._vocab is not None \
            else self._token_to_idx
        idx = [lookup[t] for t in toks]
        self._mat = self._mat.at[jnp.asarray(idx)].set(vecs)


@register
class CustomEmbedding(TokenEmbedding):
    """Local-file embedding (reference embedding.py CustomEmbedding)."""


@register
class GloVe(TokenEmbedding):
    """GloVe-format loader; needs a local file (no egress here)."""

    def __init__(self, pretrained_file_name="glove.6B.50d.txt",
                 embedding_root=None, **kwargs):
        path = kwargs.pop("pretrained_file_path", None)
        if path is None:
            root = embedding_root or os.path.expanduser("~/.mxnet_tpu/emb")
            path = os.path.join(root, pretrained_file_name)
        super().__init__(pretrained_file_path=path, **kwargs)


@register
class FastText(GloVe):
    """fastText .vec loader (same line format; header line skipped)."""

    def __init__(self, pretrained_file_name="wiki.simple.vec", **kwargs):
        super().__init__(pretrained_file_name=pretrained_file_name, **kwargs)


class CompositeEmbedding(TokenEmbedding):
    """Concatenates several embeddings per token
    (reference embedding.py CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        import jax.numpy as jnp
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._vocab = vocabulary
        self._token_to_idx = vocabulary.token_to_idx
        self._idx_to_token = vocabulary.idx_to_token
        mats = []
        for emb in token_embeddings:
            mats.append(emb._build_for_vocab(vocabulary))
        self._mat = jnp.concatenate(mats, axis=1)
        self._vec_len = int(self._mat.shape[1])
        self._init_unknown = lambda d: _np.zeros(d)
