"""Vocabulary — token <-> index mapping.

Reference: python/mxnet/contrib/text/vocab.py:30 Vocabulary (counter-based
construction, most_freq_count/min_freq filters, unknown + reserved tokens).
"""
from __future__ import annotations

__all__ = ["Vocabulary"]


class Vocabulary:
    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens or \
                len(set(reserved_tokens)) != len(reserved_tokens):
            raise ValueError("reserved tokens must be unique and must not "
                             "contain the unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens or None
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._add_counter(counter, most_freq_count, min_freq)

    def _add_counter(self, counter, most_freq_count, min_freq):
        # frequency-sorted, ties broken alphabetically (reference order)
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        room = None if most_freq_count is None else most_freq_count
        for token, freq in pairs:
            if freq < min_freq or token in self._token_to_idx:
                continue
            if room is not None and room <= 0:
                break
            self._token_to_idx[token] = len(self._idx_to_token)
            self._idx_to_token.append(token)
            if room is not None:
                room -= 1

    def __len__(self):
        return len(self._idx_to_token)

    def __contains__(self, token):
        return token in self._token_to_idx

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise ValueError("token index %d out of range" % i)
        out = [self._idx_to_token[i] for i in idxs]
        return out[0] if single else out
