"""``mx.contrib.text`` — vocabulary + pretrained token embeddings.

Reference: python/mxnet/contrib/text/ (vocab.py Vocabulary, embedding.py
registered GloVe/fastText loaders + CustomEmbedding, utils.py).
"""
from . import embedding, utils, vocab
from .vocab import Vocabulary

__all__ = ["embedding", "utils", "vocab", "Vocabulary"]
