"""``mx.contrib`` — experimental/auxiliary subsystems.

Reference: python/mxnet/contrib/ (AMP, quantization driver, ONNX, TensorRT,
text, tensorboard, SVRG).  Here: quantization (INT8 PTQ with calibration) is
first-class; amp lives at mx.amp (TPU bf16 policy); accelerator-specific
inference engines (TensorRT) have no TPU counterpart — XLA is the inference
engine.
"""
from . import quantization  # noqa: F401
from .. import amp  # noqa: F401  (mx.contrib.amp parity alias)
# control-flow ops at their reference location (python/mxnet/ndarray/
# contrib.py foreach :216, while_loop :340, cond :480)
from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from . import text  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import onnx  # noqa: F401  (gated: StableHLO is the TPU interchange)
from . import tensorboard  # noqa: F401  (gated SummaryWriter hook)
from . import tensorrt  # noqa: F401  (documented: XLA is the engine)
from . import ndarray  # noqa: F401  (contrib op namespace alias)
from . import symbol  # noqa: F401  (contrib op namespace alias)
from . import io  # noqa: F401  (DataLoaderIter)
from . import autograd  # noqa: F401  (deprecated forwarding module)

__all__ = ["quantization", "amp", "foreach", "while_loop", "cond", "text",
           "svrg_optimization", "onnx", "tensorboard", "tensorrt",
           "ndarray", "symbol", "io", "autograd"]
