"""``mx.contrib`` — experimental/auxiliary subsystems.

Reference: python/mxnet/contrib/ (AMP, quantization driver, ONNX, TensorRT,
text, tensorboard, SVRG).  Here: quantization (INT8 PTQ with calibration) is
first-class; amp lives at mx.amp (TPU bf16 policy); accelerator-specific
inference engines (TensorRT) have no TPU counterpart — XLA is the inference
engine.
"""
from . import quantization  # noqa: F401
from .. import amp  # noqa: F401  (mx.contrib.amp parity alias)

__all__ = ["quantization", "amp"]
