"""SVRG optimization (reference: python/mxnet/contrib/svrg_optimization/)."""
from .svrg_module import SVRGModule
from .svrg_optimizer import SVRGOptimizer

__all__ = ["SVRGModule", "SVRGOptimizer"]
