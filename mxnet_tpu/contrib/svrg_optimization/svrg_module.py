"""SVRGModule — Module with stochastic variance-reduced gradients.

Reference: python/mxnet/contrib/svrg_optimization/svrg_module.py:30 —
keeps a snapshot of the weights every ``update_freq`` epochs, computes the
full-batch gradient mu at the snapshot (:292 update_full_grads), and
corrects every mini-batch gradient with ``g_i(w) - g_i(w_snap) + mu``
before the optimizer step.

TPU-native: the snapshot forward/backward reuses the same fused executor as
training (no special kernel path), and the correction is three fused
elementwise ops on device.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...module import Module
from ...ndarray.ndarray import _wrap
from .svrg_optimizer import SVRGOptimizer

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        self.update_freq = update_freq
        self._snapshot = None        # weights at last full-grad computation
        self._mu = None              # full-batch gradient at the snapshot

    # ------------------------------------------------------------ snapshot
    def take_snapshot(self):
        arg, _ = self.get_params()
        self._snapshot = {k: _wrap(jnp.asarray(v._data))
                          for k, v in arg.items()}

    def update_full_grads(self, train_data):
        """Full-batch gradient at the CURRENT weights, stored as mu
        (reference svrg_module.py:292)."""
        self.take_snapshot()
        sums = {}
        batches = 0
        train_data.reset()
        for batch in train_data:
            self.forward(batch, is_train=True)
            self.backward()
            for name, g in self._grad_arrays().items():
                sums[name] = g if name not in sums else sums[name] + g
            batches += 1
        train_data.reset()
        self._mu = {k: v / max(batches, 1) for k, v in sums.items()}

    def _grad_arrays(self):
        return {name: jnp.asarray(arr._data)
                for name, arr in self._exec_grads().items()}

    def _exec_grads(self):
        return {name: self._exec.grad_dict[name]
                for name in self._param_names
                if self._exec.grad_dict.get(name) is not None}

    # ------------------------------------------------------------ training
    def _svrg_corrected_update(self, batch):
        """One corrected step: needs grad at current w AND at snapshot w."""
        # gradient at current weights
        self.forward(batch, is_train=True)
        self.backward()
        cur = {k: jnp.asarray(v) for k, v in self._grad_arrays().items()}
        if self._mu is None:
            self.update()
            return
        # gradient of the SAME batch at the snapshot weights
        live = {k: _wrap(jnp.asarray(v._data))
                for k, v in self.get_params()[0].items()}
        self.set_params(self._snapshot, self.get_params()[1],
                        allow_missing=True)
        self.forward(batch, is_train=True)
        self.backward()
        snap = {k: jnp.asarray(v) for k, v in self._grad_arrays().items()}
        self.set_params(live, self.get_params()[1], allow_missing=True)
        # overwrite the executor grads with the corrected direction
        for name, g in self._exec_grads().items():
            g._data = SVRGOptimizer.correct(cur[name], snap[name],
                                            self._mu.get(name, 0.0))
        self.update()

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd", optimizer_params=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_init=False, begin_epoch=0,
            num_epoch=None, **kwargs):
        """Module.fit with the SVRG schedule: refresh mu every
        ``update_freq`` epochs (reference svrg_module.py:395)."""
        from ... import initializer as init_mod
        from ... import metric as metric_mod
        if not self.binded:
            first = next(iter(train_data))
            train_data.reset()
            self.bind([(n, tuple(d.shape)) for n, d in
                       zip(self._data_names, first.data)],
                      [(n, tuple(l.shape)) for n, l in
                       zip(self._label_names, first.label)])
        if not self.params_initialized or force_init:
            self.init_params(initializer or init_mod.Uniform(0.01),
                             arg_params, aux_params, allow_missing,
                             force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params or
                            {"learning_rate": 0.01})
        from ...callback import BatchEndParam
        em = metric_mod.create(eval_metric)
        for epoch in range(begin_epoch, num_epoch or 1):
            if (epoch - begin_epoch) % self.update_freq == 0:
                self.update_full_grads(train_data)
            em.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self._svrg_corrected_update(batch)
                self.update_metric(em, batch.label)
                if batch_end_callback:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=em, locals=locals())
                    cbs = batch_end_callback if isinstance(
                        batch_end_callback, (list, tuple)) else \
                        [batch_end_callback]
                    for cb in cbs:
                        cb(params)
            if epoch_end_callback:
                epoch_end_callback(epoch, self._symbol,
                                   *self.get_params())
            if eval_data is not None:
                res = self.score(eval_data, metric_mod.create(eval_metric))
                self.logger.info("Epoch[%d] validation: %s", epoch, res)
        return em
