"""SVRG variance-reduced gradient correction.

Reference: python/mxnet/contrib/svrg_optimization/svrg_optimizer.py — wraps
a base optimizer; the effective gradient for sample batch i is
``g_i(w) - g_i(w_snapshot) + mu`` where mu is the full-batch gradient at the
last snapshot (Johnson & Zhang 2013).

TPU-native: the correction is pure elementwise math on jax arrays, so it
fuses into the update; snapshot state lives beside the weights.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import optimizer as _opt

__all__ = ["SVRGOptimizer"]


@_opt.register
class SVRGOptimizer(_opt.Optimizer):
    """Dispatches corrected updates to an inner optimizer.

    The module feeds three aligned tensors per parameter: the current batch
    gradient, the SAME batch's gradient at the snapshot weights, and the
    full-batch snapshot gradient mu; `correct()` forms the SVRG direction.
    """

    def __init__(self, default_optimizer="sgd", **kwargs):
        # split kwargs: ours vs the wrapped optimizer's
        super().__init__(**{k: v for k, v in kwargs.items()
                            if k in ("learning_rate", "rescale_grad", "wd")})
        if isinstance(default_optimizer, str):
            inner_kwargs = dict(kwargs)
            self.default_opt = _opt.create(default_optimizer, **inner_kwargs)
        else:
            self.default_opt = default_optimizer

    @staticmethod
    def correct(grad, snapshot_grad, mu):
        return grad - snapshot_grad + mu

    def create_state(self, index, weight):
        return self.default_opt.create_state(index, weight)

    def step(self, weight, grad, state, lr, wd, t):
        return self.default_opt.step(weight, grad, state, lr, wd, t)

    def update(self, index, weight, grad, state):
        self.default_opt.update(index, weight, grad, state)
