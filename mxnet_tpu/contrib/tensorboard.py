"""TensorBoard logging hook (reference: python/mxnet/contrib/tensorboard.py
LogMetricsCallback over the `tensorboard` SummaryWriter).

Gated: the heavyweight SummaryWriter dependency is optional.  Without it
the callback degrades to buffering scalars in memory (inspectable via
`.history`), so training scripts keep running in the zero-egress image.
"""
from __future__ import annotations

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Batch/epoch callback that logs eval metrics as TB scalars."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.history = {}
        try:
            from tensorboardX import SummaryWriter  # optional
            self._writer = SummaryWriter(logging_dir)
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._writer = SummaryWriter(logging_dir)
            except Exception:  # noqa: BLE001 — no TB backend present
                self._writer = None
        self._step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self._step += 1
        for name, value in zip(*_as_lists(param.eval_metric.get())):
            if self.prefix:
                name = "%s-%s" % (self.prefix, name)
            self.history.setdefault(name, []).append(float(value))
            if self._writer is not None:
                self._writer.add_scalar(name, value, self._step)


def _as_lists(nv):
    name, value = nv
    if isinstance(name, str):
        return [name], [value]
    return list(name), list(value)
