"""The ``mx.sym`` namespace: Symbol + every registered op as a lazy builder.

Reference: python/mxnet/symbol/ — op functions code-generated from the NNVM
registry.  Here a module ``__getattr__`` resolves any registered op name to a
Symbol-node constructor, so ``sym.FullyConnected``, ``sym.relu`` etc. exist
without codegen and stay automatically in sync with the eager ``mx.nd``
namespace (same registry, one lowering per op).
"""
from __future__ import annotations

from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     Executor, zeros, ones, _make_op_node)
from . import subgraph  # noqa: F401  (pass registry / subgraph framework)
from . import contrib  # noqa: F401 — sym.contrib.* parity

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "Executor", "zeros", "ones", "subgraph"]

from ..ops import registry as _registry


def _export_hybrid_block(block, path, epoch=0, input_names=("data",),
                         fmt="native"):
    """HybridBlock.export backend: trace the block into a Symbol graph and
    write the deployment pair ``path-symbol.json`` +
    ``path-%04d.params`` (arg:/aux: packing, python/mxnet/gluon/block.py:1077
    + model.py:394) — reloadable with ``SymbolBlock.imports``.

    ``fmt="mxnet"`` writes the REFERENCE wire formats instead (NNVM graph
    JSON + binary .params via mxnet_tpu.compat), so the pair deploys on
    real Apache-MXNet infrastructure."""
    out = block(*[Variable(n) for n in input_names])
    if isinstance(out, (list, tuple)):
        out = Group(list(out))
    arg, aux = {}, {}
    for name, p in block.collect_params().items():
        (aux if p.grad_req == "null" else arg)[name] = p.data()
    from .. import model as _model
    if fmt == "mxnet":
        from .. import compat as _compat
        # serialize BEFORE truncating: the mxnet exporter raises on ops
        # the reference lacks, and a half-export must not destroy a
        # previous good symbol.json
        js = _compat.save_mxnet_symbol(out)
        with open("%s-symbol.json" % path, "w") as f:
            f.write(js)
        _compat.save_mxnet_params("%s-%04d.params" % (path, epoch),
                                  _model.pack_params(arg, aux))
    elif fmt == "native":
        _model.save_checkpoint(path, epoch, out, arg, aux)
    else:
        raise ValueError("export: unknown fmt %r (use 'native' or "
                         "'mxnet')" % (fmt,))
    return ["%s-symbol.json" % path, "%s-%04d.params" % (path, epoch)]


def __getattr__(name):
    try:
        _registry.get(name)
    except AttributeError:
        raise AttributeError(
            "module 'symbol' has no attribute %r" % (name,)) from None

    def build(*args, **kwargs):
        return _make_op_node(name, list(args), kwargs)

    build.__name__ = name
    return build
