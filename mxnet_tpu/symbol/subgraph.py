"""Pluggable graph-pass / subgraph-partition framework.

Reference: the NNVM pass registry (``nnvm::ApplyPass``) and the subgraph
framework (src/operator/subgraph/subgraph_property.h:86 SubgraphSelector,
:252-318 SubgraphProperty::CreateSubgraphNode; build_subgraph.cc invoked at
bind, graph_executor.cc:2015) that powers MKLDNN conv fusion, quantized-op
fusion and the TensorRT bridge.

TPU-native re-design: XLA already owns kernel fusion, so the extension point
here is at the SYMBOL DAG level — where the reference rewrites NNVM graphs,
we rewrite the immutable Symbol DAG before it is traced/jitted:

* ``register_pass(name)(fn)`` / ``apply_pass(sym, name, **kw)`` — the
  ApplyPass analog; a pass is ``fn(sym, **kw) -> sym``.
* ``SubgraphProperty`` — declarative node-set selection + replacement: a
  selector marks matching nodes, connected matches are grouped, and
  ``create_subgraph_node`` maps each group to a replacement op node.  The
  built-in quantization rewrite (contrib/quantization.py) and the AMP
  recolor (amp.py) run through this machinery.

A rewritten Symbol executes through the ordinary jit path, so a custom pass
composes with sharding/pjit exactly like built-in graphs.
"""
from __future__ import annotations

from typing import Callable, Dict

__all__ = ["register_pass", "apply_pass", "list_passes", "SubgraphProperty",
           "build_subgraph", "rewrite_nodes"]

_PASSES: Dict[str, Callable] = {}


def register_pass(name):
    """Decorator registering a graph pass ``fn(sym, **kw) -> sym``."""

    def deco(fn):
        _PASSES[name] = fn
        return fn

    return deco


_BUILTINS_LOADED = False


def _load_builtin_passes():
    """Import the modules that register the built-in passes (lazy to avoid
    an import cycle: amp/quantization themselves import mx.symbol)."""
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    _BUILTINS_LOADED = True
    from .. import amp  # noqa: F401  registers AMPLowPrecision
    from ..contrib import quantization  # noqa: F401  registers QuantizeGraph


def apply_pass(sym, name, **kwargs):
    """Run a registered pass on a Symbol (nnvm::ApplyPass analog)."""
    _load_builtin_passes()
    if name not in _PASSES:
        raise ValueError("no graph pass named %r (have: %s)"
                         % (name, sorted(_PASSES)))
    return _PASSES[name](sym, **kwargs)


def list_passes():
    _load_builtin_passes()
    return sorted(_PASSES)


def rewrite_nodes(sym, fn):
    """Bottom-up DAG rebuild: ``fn(node, new_inputs) -> Symbol | None``.

    ``fn`` returns a replacement node (with the given rebuilt inputs) or
    None to keep the node with its inputs swapped.  Shared subexpressions
    stay shared (memoized by node identity) — the common frame under every
    pass here and in amp/quantization.
    """
    from .symbol import Symbol, Group

    memo = {}

    def rebuild(node):
        if id(node) in memo:
            return memo[id(node)]
        if node.kind == "var":
            out = node
        else:
            new_inputs = [rebuild(x) if isinstance(x, Symbol) else x
                          for x in node.inputs]
            out = fn(node, new_inputs)
            if out is None:
                out = Symbol(node.kind, node.name, node.op,
                             dict(node.attrs), new_inputs, node.index)
                out._attr_map = dict(node._attr_map)
        memo[id(node)] = out
        return out

    heads = [rebuild(h) for h in sym._heads()]
    return heads[0] if len(heads) == 1 else Group(heads)


class SubgraphProperty:
    """Declarative select-and-replace (reference subgraph_property.h).

    Subclasses override:
      select(node) -> bool            does this op node start/join a match
      create_subgraph_node(nodes, inputs) -> Symbol
                                      replacement for one connected match
    ``build_subgraph`` walks the DAG, groups CONNECTED selected nodes
    (a node and its selected producer belong to one group, mirroring
    SubgraphSelector::SelectInput/SelectOutput), and substitutes each
    group's sink with the property's replacement node.
    """

    def select(self, node):
        raise NotImplementedError

    def create_subgraph_node(self, nodes, inputs):
        raise NotImplementedError


def build_subgraph(sym, prop):
    """Apply a SubgraphProperty over a Symbol (build_subgraph.cc analog).

    Groups are formed on the ORIGINAL graph along single-consumer def-use
    chains of selected nodes: a selected producer joins its selected
    consumer's group only when that consumer is its sole user, so a node
    whose output escapes the group is never absorbed (the reference's
    output-escape rule in SubgraphSelector).  Each group — nodes in
    producers-first order — is replaced at its sink by
    ``prop.create_subgraph_node(group_nodes, external_inputs)``, where
    external_inputs are the REBUILT inputs feeding the group from outside,
    in group-order of first use.
    """
    from .symbol import Symbol, Group, _topo

    # consumer counts on the original DAG (op-node uses only); graph heads
    # count as escapes too — a head's output is externally visible, so it
    # must never be absorbed into a consumer's group
    consumers = {}
    for n in _topo(sym):
        if n.kind == "op":
            for x in n.inputs:
                if isinstance(x, Symbol):
                    consumers[id(x)] = consumers.get(id(x), 0) + 1
    head_ids = {id(h) for h in sym._heads()}

    def absorb(node):
        """The group whose sink is `node`, producers first."""
        out = []
        for x in node.inputs:
            if isinstance(x, Symbol) and x.kind == "op" and \
                    prop.select(x) and consumers.get(id(x), 0) == 1 and \
                    id(x) not in head_ids:
                out.extend(absorb(x))
        out.append(node)
        return out

    memo = {}

    def rebuild(node):
        if id(node) in memo:
            return memo[id(node)]
        if node.kind == "var":
            out = node
        elif prop.select(node):
            group = absorb(node)
            inside = {id(g) for g in group}
            externals = []
            for g in group:
                for x in g.inputs:
                    if isinstance(x, Symbol) and id(x) in inside:
                        continue
                    externals.append(rebuild(x) if isinstance(x, Symbol)
                                     else x)
            out = prop.create_subgraph_node(group, externals)
        else:
            new_inputs = [rebuild(x) if isinstance(x, Symbol) else x
                          for x in node.inputs]
            out = Symbol(node.kind, node.name, node.op, dict(node.attrs),
                         new_inputs, node.index)
            out._attr_map = dict(node._attr_map)
        memo[id(node)] = out
        return out

    heads = [rebuild(h) for h in sym._heads()]
    return heads[0] if len(heads) == 1 else Group(heads)
