"""``mx.sym.contrib`` — lazy Symbol builders for contrib ops by short name
(reference: generated ``mxnet.symbol.contrib``)."""
from __future__ import annotations

from .symbol import _make_op_node
from ..ndarray.contrib import _resolve


def __getattr__(name):
    if name.startswith("_"):
        raise AttributeError(name)
    op = _resolve(name)  # raises AttributeError for unknown names

    def build(*args, **kwargs):
        return _make_op_node(op.name, list(args), kwargs)

    build.__name__ = name
    return build
