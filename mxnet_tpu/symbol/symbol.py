"""Symbol — lazy graph-composition API over the op registry.

Reference: python/mxnet/symbol/symbol.py (`Symbol`, compose without data,
bind/simple_bind at symbol.py:1500+ incl. the ``group2ctx`` model-parallel
arg) over the NNVM C++ graph (3rdparty/tvm/nnvm).  The reference keeps a
C++-side node graph and runs optimization passes (src/executor/
graph_executor.cc:388 Init pipeline) before creating engine ops.

TPU-native re-design: a Symbol is an immutable Python DAG node naming a
registered pure op.  "Binding" does not build an executor machine — it traces
the DAG once into a pure jax function and ``jit``s it; XLA then does
everything the reference's pass pipeline did (shape/type propagation at trace
time, memory planning, fusion, scheduling).  Gradient executors come from
``jax.vjp`` of the same traced function, replacing the MXGradient graph pass
(src/nnvm/gradient.cc:104).  Multi-device placement (``group2ctx``) becomes
sharding annotations, not device assignment.
"""
from __future__ import annotations

import json

import numpy as _np
import jax
import jax.numpy as jnp

from ..ops import registry as _registry
from .. import random as _random
from ..base import dtype_np
from ..context import current_context

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "Executor", "zeros", "ones"]


class Symbol:
    """Immutable graph node.

    kind: 'var' (named input), 'op' (registered op applied to inputs),
    'slice' (select one output of a multi-output node), 'group' (tuple of
    heads, reference: mx.sym.Group).
    ``inputs`` entries are Symbols or Python/numpy constants (scalars embed
    directly, matching ``sym + 1``).
    """

    __slots__ = ("kind", "name", "op", "attrs", "inputs", "index", "_attr_map")

    def __init__(self, kind, name, op=None, attrs=None, inputs=(), index=0):
        self.kind = kind
        self.name = name
        self.op = op
        self.attrs = attrs or {}
        self.inputs = list(inputs)
        self.index = index
        self._attr_map = {}

    # ------------------------------------------------------------- identity
    def __repr__(self):
        return "<Symbol %s>" % (self.name,)

    def attr(self, key):
        return self._attr_map.get(key)

    def attr_dict(self):
        out = {}
        for node in _topo(self):
            if node._attr_map:
                out[node.name] = dict(node._attr_map)
        return out

    def _set_attr(self, **kwargs):
        self._attr_map.update(kwargs)
        return self

    # ------------------------------------------------------------ listings
    def list_arguments(self):
        """Names of all variable leaves in topological order (reference:
        Symbol.list_arguments), aux states excluded."""
        return [n.name for n in _topo(self)
                if n.kind == "var" and not _is_aux_name(n.name)]

    def list_auxiliary_states(self):
        return [n.name for n in _topo(self)
                if n.kind == "var" and _is_aux_name(n.name)]

    def list_inputs(self):
        return [n.name for n in _topo(self) if n.kind == "var"]

    def list_outputs(self):
        """One name per actual output — multi-output heads expand to
        ``name_output0..N`` so output_dict/monitor callbacks stay aligned
        with forward()'s output list."""
        names = []
        for h in self._heads():
            n = _node_num_outputs(h)
            if n > 1 and h.kind == "op" and self.kind != "group":
                names.extend("%s_output%d" % (h.name, i) for i in range(n))
            elif h.kind == "var":
                names.append(h.name)
            else:
                names.append(h.name + "_output")
        return names

    @property
    def num_outputs(self):
        return len(self._heads())

    def _heads(self):
        if self.kind == "group":
            return list(self.inputs)
        return [self]

    def __iter__(self):
        heads = self._heads()
        if len(heads) == 1:
            # a single multi-output op iterates its outputs
            n = _node_num_outputs(heads[0])
            if n > 1:
                return iter([heads[0][i] for i in range(n)])
        return iter(heads)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            names = self.list_outputs()
            idx = names.index(idx)
        if self.kind == "group":
            return self.inputs[idx]
        if _node_num_outputs(self) > 1:
            return Symbol("slice", "%s%d" % (self.name, idx),
                          inputs=[self], index=idx)
        if idx != 0:
            raise IndexError("output index %d out of range" % idx)
        return self

    def get_internals(self):
        """Group of every node's outputs (reference: Symbol.get_internals,
        used to tap intermediate features e.g. for fine-tuning)."""
        return Group([n if n.kind == "var" else n
                      for n in _topo(self)])

    def get_children(self):
        ins = [i for i in self.inputs if isinstance(i, Symbol)]
        return Group(ins) if ins else None

    # ----------------------------------------------------------- operators
    def _binop(self, opname, other, reverse=False):
        a, b = (other, self) if reverse else (self, other)
        return _make_op_node(opname, [a, b], {})

    def __add__(self, o): return self._binop("broadcast_add", o)
    def __radd__(self, o): return self._binop("broadcast_add", o, True)
    def __sub__(self, o): return self._binop("broadcast_sub", o)
    def __rsub__(self, o): return self._binop("broadcast_sub", o, True)
    def __mul__(self, o): return self._binop("broadcast_mul", o)
    def __rmul__(self, o): return self._binop("broadcast_mul", o, True)
    def __truediv__(self, o): return self._binop("broadcast_div", o)
    def __rtruediv__(self, o): return self._binop("broadcast_div", o, True)
    def __pow__(self, o): return self._binop("broadcast_power", o)
    def __neg__(self): return _make_op_node("negative", [self], {})
    def __eq__(self, o): return self._binop("broadcast_equal", o)
    def __ne__(self, o): return self._binop("broadcast_not_equal", o)
    def __lt__(self, o): return self._binop("broadcast_lesser", o)
    def __le__(self, o): return self._binop("broadcast_lesser_equal", o)
    def __gt__(self, o): return self._binop("broadcast_greater", o)
    def __ge__(self, o): return self._binop("broadcast_greater_equal", o)
    __hash__ = object.__hash__

    def __getattr__(self, name):
        # method-style op application: sym.reshape(...), sym.mean(...) —
        # mirrors NDArray's generated methods
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            _registry.get(name)
        except AttributeError:
            raise AttributeError("Symbol has no attribute %r" % (name,)) \
                from None

        def method(*args, **kwargs):
            return _make_op_node(name, [self] + list(args), kwargs)
        method.__name__ = name
        return method

    # ----------------------------------------------------- shape/type infer
    def infer_shape(self, *args_shapes, **kwargs):
        """Returns (arg_shapes, out_shapes, aux_shapes) — reference
        Symbol.infer_shape.  Partial: parameter shapes are derived from data
        shapes via per-op reverse rules + jax.eval_shape forward propagation
        (replacing src/executor/infer_graph_attr_pass.cc).  Unknown shapes
        come back as None."""
        if args_shapes:
            kwargs.update(zip(self.list_arguments(), args_shapes))
        known = {n: tuple(v) for n, v in kwargs.items() if v is not None}
        var_shapes, out_shapes = _infer_shapes_partial(self, known)
        args = self.list_arguments()
        aux = self.list_auxiliary_states()
        arg_res = [var_shapes.get(n) for n in args]
        aux_res = [var_shapes.get(n) for n in aux]
        out_res = []
        for h in self._heads():
            n = _node_num_outputs(h)
            if n > 1 and h.kind == "op" and self.kind != "group":
                out_res.extend(out_shapes.get((id(h), i)) for i in range(n))
            else:
                base, idx = _unwrap_slice(h)
                out_res.append(out_shapes.get((id(base), idx)))
        return arg_res, out_res, aux_res

    def infer_type(self, **kwargs):
        """All-float32 default typing (the framework computes in f32/bf16 by
        policy — see mx.amp — rather than per-arg dtype solving)."""
        args = self.list_arguments()
        aux = self.list_auxiliary_states()
        f32 = _np.dtype(_np.float32)
        return ([_np.dtype(kwargs.get(n, f32)) for n in args],
                [f32] * len(self.list_outputs()), [f32] * len(aux))

    # -------------------------------------------------------------- binding
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, **kwargs):
        """Allocate arguments from shapes and bind (reference:
        MXExecutorSimpleBindEx, src/c_api/c_api_executor.cc:860)."""
        return self._simple_bind_shapes(kwargs, ctx=ctx, grad_req=grad_req,
                                        type_dict=type_dict,
                                        group2ctx=group2ctx)

    def _simple_bind_shapes(self, shape_map, ctx=None, grad_req="write",
                            type_dict=None, group2ctx=None):
        """Dict-based simple_bind: input names that collide with the
        kwargs API's own parameters (a Variable literally named "ctx")
        bind through here — the C ABI uses this path."""
        arg_shapes, _, aux_shapes = self.infer_shape(**dict(shape_map))
        from ..ndarray.ndarray import _wrap
        args = {}
        for name, shp in zip(self.list_arguments(), arg_shapes):
            if shp is None:
                raise ValueError(
                    "simple_bind could not infer a shape for %r — pass it "
                    "explicitly" % (name,))
            dt = (type_dict or {}).get(name, _np.float32)
            args[name] = _wrap(jnp.zeros(shp, dtype_np(dt)))
        aux = {}
        for name, shp in zip(self.list_auxiliary_states(), aux_shapes):
            if shp is None:
                raise ValueError(
                    "simple_bind could not infer a shape for aux %r" % (name,))
            aux[name] = _wrap(jnp.zeros(shp, _np.float32))
        placement = self._ctx_group_map(group2ctx)
        self._place_groups(args, placement)
        self._place_groups(aux, placement)
        args_grad = None
        if grad_req != "null":
            # grads live beside the params they update (reference: grad
            # arrays share the arg's assigned context)
            args_grad = {n: _wrap(jnp.zeros_like(v._data))
                         for n, v in args.items()}
            self._place_groups(args_grad, placement)
        return Executor(self, ctx or current_context(), args, args_grad,
                        grad_req, aux, placement=placement)

    def _ctx_group_map(self, group2ctx):
        """{var_name: Context} from each variable's ctx_group annotation
        (reference: AssignContext + group2ctx, graph_executor.cc:997)."""
        if not group2ctx:
            return {}
        out = {}
        for node in _topo(self):
            if node.kind != "var":
                continue
            grp = node._attr_map.get("ctx_group")
            if grp is not None and grp in group2ctx:
                out[node.name] = group2ctx[grp]
        return out

    @staticmethod
    def _place_groups(arrays, placement):
        """device_put each named array onto its ctx-group device: params
        RESIDE where the user assigned them (multi-chip memory
        distribution); the Executor inserts the cross-device copies at
        run time like the reference's AssignContext copy nodes."""
        for n, ctx in placement.items():
            if n in arrays:
                arrays[n]._data = jax.device_put(arrays[n]._data,
                                                 ctx.jax_device)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        """Bind with explicit arrays (reference: MXExecutorBindEX,
        src/c_api/c_api_executor.cc:135)."""
        from ..ndarray.ndarray import NDArray, _wrap
        names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(names, args))
        args = dict(args or {})
        aux_names = self.list_auxiliary_states()
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        aux_states = dict(aux_states or {})
        user_owned = {n for pool in (args, aux_states)
                      for n, v in pool.items() if isinstance(v, NDArray)}
        args = {n: (v if isinstance(v, NDArray) else _wrap(jnp.asarray(v)))
                for n, v in args.items()}
        aux_states = {n: (v if isinstance(v, NDArray)
                          else _wrap(jnp.asarray(v)))
                      for n, v in aux_states.items()}
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(names, args_grad))
        args_grad = dict(args_grad or {}) or None
        if args_grad:
            user_owned |= {n for n, v in args_grad.items()
                           if isinstance(v, NDArray)}
            args_grad = {n: (v if isinstance(v, NDArray)
                             else _wrap(jnp.asarray(v)))
                         for n, v in args_grad.items()}
        placement = self._ctx_group_map(group2ctx)
        # caller-owned NDArrays must already sit on their assigned device
        # (the reference ERRORS on a ctx mismatch rather than silently
        # relocating user data); arrays we wrapped fresh get placed
        for n, c in placement.items():
            for pool in (args, aux_states) + ((args_grad,) if args_grad
                                              else ()):
                v = pool.get(n)
                if v is None:
                    continue
                try:
                    want = c.jax_device
                    dev = next(iter(v._data.devices()))
                except Exception:  # noqa: BLE001 — uncommitted values
                    continue
                if dev == want:
                    continue
                if n in user_owned:
                    raise ValueError(
                        "bind: argument %r lives on %s but its ctx_group "
                        "assigns %s — create it on the assigned device "
                        "(reference AssignContext ctx-mismatch check)"
                        % (n, dev, want))
                v._data = jax.device_put(v._data, want)
        return Executor(self, ctx or current_context(), args, args_grad,
                        grad_req, aux_states, placement=placement)

    def eval(self, ctx=None, **kwargs):
        """One-shot forward (reference: Symbol.eval)."""
        ex = self.bind(ctx, args=kwargs)
        return ex.forward()

    # -------------------------------------------------------- serialization
    def tojson(self):
        """Graph JSON — same concept as the reference's symbol.json
        (MXSymbolSaveToJSON, src/c_api/c_api_symbolic.cc:500); own schema."""
        nodes = _topo(self)
        nid = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            ins = []
            for x in n.inputs:
                if isinstance(x, Symbol):
                    ins.append(["node", nid[id(x)]])
                else:
                    ins.append(["const", _np.asarray(x).tolist()])
            out_nodes.append({
                "kind": n.kind, "name": n.name, "op": n.op,
                "attrs": _json_attrs(n.attrs), "inputs": ins,
                "index": n.index, "attr_map": n._attr_map,
            })
        heads = [nid[id(h)] for h in self._heads()]
        return json.dumps({"nodes": out_nodes, "heads": heads,
                           "format": "mxnet_tpu-symbol-v1"}, indent=2)

    def save(self, fname):
        from .. import resilience as _resilience
        with _resilience.atomic_write(fname, "w") as f:
            f.write(self.tojson())


def _json_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, _np.dtype):
            v = v.name
        elif isinstance(v, type):
            v = _np.dtype(v).name
        elif isinstance(v, tuple):
            v = list(v)
        out[k] = v
    return out


def load_json(s):
    from ..compat import is_mxnet_symbol_json, load_mxnet_symbol
    if is_mxnet_symbol_json(s):
        # a REAL Apache-MXNet symbol.json (NNVM graph schema): replay it
        # through the native builders so existing models load as-is
        return load_mxnet_symbol(s)
    data = json.loads(s)
    nodes = []
    for spec in data["nodes"]:
        ins = []
        for kind, val in spec["inputs"]:
            ins.append(nodes[val] if kind == "node" else val)
        n = Symbol(spec["kind"], spec["name"], spec.get("op"),
                   spec.get("attrs") or {}, ins, spec.get("index", 0))
        n._attr_map = spec.get("attr_map") or {}
        nodes.append(n)
    heads = [nodes[i] for i in data["heads"]]
    return heads[0] if len(heads) == 1 else Group(heads)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# ------------------------------------------------------------ constructors

def Variable(name, shape=None, dtype=None, init=None, **attr_kwargs):
    s = Symbol("var", name)
    if shape is not None:
        s.attrs["shape"] = tuple(shape)
    if dtype is not None:
        s.attrs["dtype"] = _np.dtype(dtype).name
    # AttrScope annotations apply to Variables too (the scope's primary
    # consumers are parameter attrs: lr_mult/__init__/ctx_group), with
    # explicit per-variable attrs winning over the scope
    from ..attribute import AttrScope
    s._attr_map.update(AttrScope.current_attrs())
    if init is not None:
        # reference Variable(init=...) serializes the initializer into the
        # __init__ attr (python/mxnet/symbol/symbol.py Variable); InitDesc
        # routes it back through Initializer.__call__ at init_params time
        s._attr_map["__init__"] = init if isinstance(init, str) else \
            init.dumps()
    s._attr_map.update({k: str(v) for k, v in attr_kwargs.items()})
    return s


var = Variable


def Group(symbols):
    symbols = list(symbols)
    return Symbol("group", "group", inputs=symbols)


def zeros(shape, dtype="float32", **_):
    return _make_op_node("_zeros_shape", [],
                         {"shape": tuple(shape), "dtype": dtype})


def ones(shape, dtype="float32", **_):
    return _make_op_node("_ones_shape", [],
                         {"shape": tuple(shape), "dtype": dtype})


def _fill_shape(shape):
    # Reference shape semantics: a 0 dim means "unknown, solve at bind"
    # (mx.sym.zeros(shape=(0, H)) is how RNN cells spell batch-agnostic
    # begin_state, python/mxnet/rnn/rnn_cell.py:190-223).  The reference
    # runs bidirectional shape inference to fill it; here inference is
    # forward-only, so unknown dims lower to size 1 and XLA broadcasting
    # carries them — every consumer of a begin_state symbol is broadcast
    # math (broadcast_add/mul, FullyConnected over a batch of 1, the RNN
    # op's explicit state broadcast).
    return tuple(1 if s == 0 else s for s in shape)


_registry.register("_zeros_shape", differentiable=False)(
    lambda shape=(), dtype="float32", **_:
        jnp.zeros(_fill_shape(shape), dtype_np(dtype)))
_registry.register("_ones_shape", differentiable=False)(
    lambda shape=(), dtype="float32", **_:
        jnp.ones(_fill_shape(shape), dtype_np(dtype)))


_NAME_COUNTER = {}


def _auto_name(opname):
    base = opname.lower().lstrip("_")
    i = _NAME_COUNTER.get(base, 0)
    _NAME_COUNTER[base] = i + 1
    return "%s%d" % (base, i)


# Learnable-input slots per layer op.  Reference parity: the NNVM registry
# lists named inputs (FListInputNames) and the Python wrapper auto-creates
# missing weight/bias Variables named "{name}_{slot}"
# (python/mxnet/symbol/symbol.py generated ops).
_OP_INPUT_SLOTS = {
    "FullyConnected": ("data", "weight", "bias"),
    "Convolution": ("data", "weight", "bias"),
    "_contrib_quantized_fully_connected": ("data", "weight", "bias"),
    "_contrib_quantized_conv": ("data", "weight", "bias"),
    "Deconvolution": ("data", "weight", "bias"),
    "BatchNorm": ("data", "gamma", "beta", "moving_mean", "moving_var"),
    "LayerNorm": ("data", "gamma", "beta"),
    "GroupNorm": ("data", "gamma", "beta"),
    "InstanceNorm": ("data", "gamma", "beta"),
    "Embedding": ("data", "weight"),
    # output-loss ops auto-create their label input as "{name}_label"
    # (reference: mx.symbol.SoftmaxOutput(fc, name='sm') binds 'sm_label')
    "SoftmaxOutput": ("data", "label"),
    "LinearRegressionOutput": ("data", "label"),
    "LogisticRegressionOutput": ("data", "label"),
    "MAERegressionOutput": ("data", "label"),
    # fused RNN (reference src/operator/rnn.cc:652): parameters is the flat
    # cuDNN-layout blob; state_cell exists only in lstm mode
    "RNN": ("data", "parameters", "state", "state_cell"),
}


def _make_op_node(opname, inputs, attrs):
    op = _registry.get(opname)  # raises AttributeError for unknown ops
    name = attrs.pop("name", None) or _auto_name(opname)
    slots = _OP_INPUT_SLOTS.get(op.name)
    if slots:
        slot_vals = {}
        for i, x in enumerate(inputs):
            slot_vals[slots[i]] = x
        for s in slots:
            if s in attrs:
                slot_vals[s] = attrs.pop(s)
        no_bias = bool(attrs.get("no_bias", False))
        inputs = []
        for s in slots:
            v = slot_vals.get(s)
            if v is None:
                if s == "bias" and no_bias:
                    inputs.append(None)
                    continue
                if s == "state_cell" and attrs.get("mode", "lstm") != "lstm":
                    inputs.append(None)
                    continue
                if s == "data":
                    raise ValueError("%s: missing data input" % (op.name,))
                v = Variable("%s_%s" % (name, s))
            inputs.append(v)
    else:
        if "data" in attrs and not inputs:
            inputs = [attrs.pop("data")]
    norm_inputs = []
    for x in inputs:
        from ..ndarray.ndarray import NDArray
        if isinstance(x, NDArray):
            x = x._data  # constant capture
        norm_inputs.append(x)
    node = Symbol("op", name, op=op.name, attrs=attrs, inputs=norm_inputs)
    # annotation attrs from the enclosing AttrScope (ctx_group, lr_mult...)
    from ..attribute import AttrScope
    scope_attrs = AttrScope.current_attrs()
    if scope_attrs:
        node._attr_map.update(scope_attrs)
    return node


# Parameter-shape rules: given op attrs + the data-input shape, the shapes of
# learnable inputs.  This is the *reverse* half of the reference's per-op
# FInferShape (e.g. src/operator/nn/fully_connected.cc shape fn deriving
# weight=(num_hidden, in_dim)); the forward half is jax.eval_shape per node.
def _fc_param_shapes(attrs, dshape):
    nh = int(attrs["num_hidden"])
    flatten = attrs.get("flatten", True)
    in_dim = int(_np.prod(dshape[1:])) if flatten else dshape[-1]
    return {1: (nh, in_dim), 2: (nh,)}


def _conv_param_shapes(attrs, dshape):
    nf = int(attrs["num_filter"])
    kernel = tuple(attrs["kernel"])
    groups = int(attrs.get("num_group", 1))
    return {1: (nf, dshape[1] // groups) + kernel, 2: (nf,)}


def _deconv_param_shapes(attrs, dshape):
    nf = int(attrs["num_filter"])
    kernel = tuple(attrs["kernel"])
    return {1: (dshape[1], nf) + kernel, 2: (nf,)}


def _bn_param_shapes(attrs, dshape):
    axis = int(attrs.get("axis", 1))
    c = dshape[axis]
    return {1: (c,), 2: (c,), 3: (c,), 4: (c,)}


def _ln_param_shapes(attrs, dshape):
    axis = int(attrs.get("axis", -1))
    return {1: (dshape[axis],), 2: (dshape[axis],)}


def _in_param_shapes(attrs, dshape):
    return {1: (dshape[1],), 2: (dshape[1],)}


def _emb_param_shapes(attrs, dshape):
    return {1: (int(attrs["input_dim"]), int(attrs["output_dim"]))}


def _rnn_param_shapes(attrs, dshape):
    # data is TNC (T, B, I); parameters is the flat cuDNN-layout blob
    # (reference src/operator/rnn-inl.h GetRnnParamSize)
    from ..rnn._fused_layout import fused_rnn_param_size
    h = int(attrs["state_size"])
    layers = int(attrs.get("num_layers", 1))
    bi = str(attrs.get("bidirectional", False)) in ("True", "true", "1")
    mode = attrs.get("mode", "lstm")
    d = 2 if bi else 1
    total = fused_rnn_param_size(dshape[2], h, layers, mode, bi)
    state = (layers * d, dshape[1], h)
    shapes = {1: (total,), 2: state}
    if mode == "lstm":
        shapes[3] = state
    return shapes


_INT_DATA_OPS = {"Embedding", "one_hot", "take"}

# unary ops that preserve their input's shape — partial shape inference may
# propagate parameter shapes through them
_SHAPE_TRANSPARENT = {"cast", "_sim_quant", "identity", "BlockGrad",
                      "Dropout", "make_loss", "negative", "relu", "abs"}

def _softmax_output_label_shape(attrs, dshape):
    # reference SoftmaxOutput FInferShape: label is (N,) class indices
    return {1: (dshape[0],)}


def _regression_output_label_shape(attrs, dshape):
    # *RegressionOutput: label matches the prediction shape
    return {1: tuple(dshape)}


_PARAM_SHAPE_RULES = {
    "SoftmaxOutput": _softmax_output_label_shape,
    "LinearRegressionOutput": _regression_output_label_shape,
    "LogisticRegressionOutput": _regression_output_label_shape,
    "MAERegressionOutput": _regression_output_label_shape,
    "FullyConnected": _fc_param_shapes,
    "Convolution": _conv_param_shapes,
    "_contrib_quantized_fully_connected": _fc_param_shapes,
    "_contrib_quantized_conv": _conv_param_shapes,
    "Deconvolution": _deconv_param_shapes,
    "BatchNorm": _bn_param_shapes,
    "LayerNorm": _ln_param_shapes,
    "GroupNorm": _in_param_shapes,
    "InstanceNorm": _in_param_shapes,
    "Embedding": _emb_param_shapes,
    "RNN": _rnn_param_shapes,
}


def _infer_shapes_partial(sym, known, dtypes=None):
    """Forward shape propagation with reverse param rules — the TPU-native
    stand-in for the reference's iterative InferShape pass
    (src/executor/infer_graph_attr_pass.cc).  Returns
    {var_name: shape} ∪ known, {(node_id, out_idx): shape}."""
    var_shapes = dict(known)
    out_shapes = {}

    def in_shape(x):
        if not isinstance(x, Symbol):
            a = _np.asarray(x)
            return tuple(a.shape)
        if x.kind == "var":
            if x.name in var_shapes:
                return var_shapes[x.name]
            if "shape" in x.attrs:
                return tuple(x.attrs["shape"])
            return None
        base, idx = _unwrap_slice(x)
        return out_shapes.get((id(base), idx))

    for node in _topo(sym):
        if node.kind == "var":
            s = in_shape(node)
            if s is not None:
                out_shapes[(id(node), 0)] = s
            continue
        if node.kind == "slice":
            s = out_shapes.get((id(node.inputs[0]), node.index))
            if s is not None:
                out_shapes[(id(node), 0)] = s
            continue
        if node.kind != "op":
            continue
        shapes = [in_shape(x) if x is not None else None
                  for x in node.inputs]
        rule = _PARAM_SHAPE_RULES.get(node.op)
        if rule is not None and shapes and shapes[0] is not None:
            derived = rule(node.attrs, shapes[0])
            for i, shp in derived.items():
                if i >= len(node.inputs) or shapes[i] is not None or \
                        not isinstance(node.inputs[i], Symbol):
                    continue
                # follow shape-preserving unary wrappers (cast/_sim_quant/
                # BlockGrad...) down to the parameter variable they wrap —
                # AMP and quantization passes interpose these
                chain = [node.inputs[i]]
                while chain[-1].kind == "op" and \
                        chain[-1].op in _SHAPE_TRANSPARENT and \
                        isinstance(chain[-1].inputs[0], Symbol):
                    chain.append(chain[-1].inputs[0])
                leaf = chain[-1]
                if leaf.kind != "var":
                    continue
                shapes[i] = tuple(shp)
                var_shapes[leaf.name] = tuple(shp)
                for c in chain:
                    out_shapes[(id(c), 0)] = tuple(shp)
        if any(s is None and x is not None
               for s, x in zip(shapes, node.inputs)):
            continue  # unknown inputs: leave this node's outputs unknown
        op = _registry.get(node.op)
        specs = []
        for s, x in zip(shapes, node.inputs):
            if x is None:
                specs.append(None)
            elif isinstance(x, Symbol):
                specs.append(jax.ShapeDtypeStruct(s, _np.float32))
            else:
                specs.append(x)
        if node.op in _INT_DATA_OPS and isinstance(specs[0],
                                                   jax.ShapeDtypeStruct):
            specs[0] = jax.ShapeDtypeStruct(specs[0].shape, _np.int32)
        attrs = dict(node.attrs)
        if node.op in _AUX_UPDATE_RULES or node.op in _STOCHASTIC_OPS:
            attrs["training"] = False
        try:
            res = jax.eval_shape(lambda *a: op.fn(*a, **attrs), *specs)
        except Exception:
            continue
        outs = list(res) if isinstance(res, (tuple, list)) else [res]
        for i, o in enumerate(outs):
            out_shapes[(id(node), i)] = tuple(o.shape)
    return var_shapes, out_shapes


# ----------------------------------------------------------------- traversal

def _topo(sym):
    """Post-order unique traversal."""
    seen = set()
    order = []

    def visit(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        for x in n.inputs:
            if isinstance(x, Symbol):
                visit(x)
        order.append(n)

    visit(sym)
    if sym.kind == "group":
        # identity-based removal: Symbol.__eq__ builds graph nodes, so
        # list.remove's == comparison must never run on Symbols
        order = [n for n in order if n is not sym]
    return order


# Ops whose extra outputs are internal (reference: FNumVisibleOutputs — e.g.
# BatchNorm's (mean, var) outputs exist in the graph but are hidden from the
# user API, src/operator/nn/batch_norm.cc).
_VISIBLE_OUTPUTS = {"BatchNorm": 1}


def _unwrap_slice(x):
    """(base_node, output_index) for a symbol that may be a slice
    selector over a multi-output op."""
    if x.kind == "slice":
        return x.inputs[0], x.index
    return x, 0


def _node_num_outputs(node):
    if node.kind != "op":
        return 1
    if node.op in _VISIBLE_OUTPUTS:
        return _VISIBLE_OUTPUTS[node.op]
    op = _registry.get(node.op)
    n = op.num_outputs
    if n == -1:  # attr-dependent (split)
        return int(node.attrs.get("num_outputs", 1))
    return n


# Aux-state update rules: reference ops mutate their auxiliary inputs inside
# the kernel (e.g. BatchNorm moving stats, src/operator/nn/batch_norm.cc);
# our ops are pure, so the executor applies these write-backs explicitly.
def _bn_aux_update(node, env_in, outs):
    mom = float(node.attrs.get("momentum", 0.9))
    mm, mv = node.inputs[3], node.inputs[4]
    updates = {}
    if isinstance(mm, Symbol) and mm.kind == "var":
        updates[mm.name] = mom * env_in[3] + (1 - mom) * outs[1]
    if isinstance(mv, Symbol) and mv.kind == "var":
        updates[mv.name] = mom * env_in[4] + (1 - mom) * outs[2]
    return updates


_AUX_UPDATE_RULES = {"BatchNorm": _bn_aux_update}

_AUX_SUFFIXES = ("moving_mean", "moving_var", "running_mean", "running_var",
                 "moving_avg")


def _is_aux_name(name):
    return name.endswith(_AUX_SUFFIXES)


_STOCHASTIC_OPS = {"Dropout", "shuffle"}


def _eval_symbol(sym, env, training, aux_updates=None):
    """Interpret the DAG on jax values.  ``env`` maps var name -> array.
    Returns the list of head outputs.  Runs under jit when called from a
    bound Executor — pure apart from the explicit aux_updates dict."""
    from .. import numerics as _numerics
    taps = _numerics.collecting()
    cache = {}

    def value(node, index=0):
        key = (id(node), index)
        if key in cache:
            return cache[key]
        if node.kind == "var":
            if node.name not in env:
                raise ValueError("unbound variable %r" % (node.name,))
            out = env[node.name]
        elif node.kind == "slice":
            out = value(node.inputs[0], node.index)
        elif node.kind == "op":
            op = _registry.get(node.op)
            vals = [value(x) if isinstance(x, Symbol) else x
                    for x in node.inputs]
            attrs = dict(node.attrs)
            if node.op in _STOCHASTIC_OPS or node.op == "Dropout" \
                    or node.op in ("BatchNorm",):
                # the EXECUTOR's is_train decides train-vs-infer semantics;
                # a `training` attr baked into the node at trace/export
                # time (e.g. by a gluon layer's hybrid_forward) must not
                # win — Dropout's always-on behavior is the `mode` attr's
                # job, not `training`'s
                attrs["training"] = training
            res = op.fn(*vals, **attrs)
            multi = isinstance(res, (tuple, list))
            outs = list(res) if multi else [res]
            for i, o in enumerate(outs):
                cache[(id(node), i)] = o
            if taps:
                # per-op-output numerics tap sites (trace-time = the
                # graph's topological order); only instrumented program
                # variants ever evaluate with a collector open
                for i, o in enumerate(outs):
                    _numerics.tap(
                        node.name if not multi
                        else "%s[%d]" % (node.name, i), o)
            if training and aux_updates is not None \
                    and node.op in _AUX_UPDATE_RULES:
                aux_updates.update(
                    _AUX_UPDATE_RULES[node.op](node, vals, outs))
            out = outs[index]
        else:
            raise ValueError("cannot evaluate node kind %r" % (node.kind,))
        cache[key] = out
        return out

    heads = sym._heads()
    outs = []
    for h in heads:
        n = _node_num_outputs(h)
        if n > 1 and h.kind == "op" and sym.kind != "group":
            outs.extend(value(h, i) for i in range(n))
        else:
            outs.append(value(h, h.index if h.kind == "slice" else 0))
    return outs


# ------------------------------------------------------------------ Executor

class Executor:
    """Bound computation (reference: include/mxnet/executor.h over
    GraphExecutor).  forward/backward call into ONE jitted function per
    (training, shape-signature); XLA replaces the reference's memory planning
    + bulked engine ops (src/executor/graph_executor.cc:1016,1288)."""

    def __init__(self, sym, ctx, args, args_grad, grad_req, aux,
                 placement=None):
        self._symbol = sym
        self._ctx = ctx
        self.arg_dict = dict(args or {})
        self.grad_dict = dict(args_grad or {})
        self.aux_dict = dict(aux or {})
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self.arg_dict}
        self.grad_req = grad_req
        self.outputs = []
        self._fwd_cache = {}
        self._bwd_cache = {}
        self._fused_cache = {}
        self._monitor = None
        # ctx-group model parallelism: {name: jax.Device} where the user
        # pinned each param via group2ctx — the single source of truth the
        # forward/backward transfers, grad write-back, and
        # copy_params_from all honor
        self._placement = {}
        for n, c in (placement or {}).items():
            try:
                self._placement[n] = c.jax_device
            except Exception:  # noqa: BLE001 — backendless contexts
                pass

    # internals -----------------------------------------------------------
    def _to_exec_device(self, env):
        """Transfer any array pinned to ANOTHER device onto the executor's
        device before it feeds one jitted program — the reference's
        AssignContext cross-device copy nodes (graph_executor.cc:997).
        Same-device arrays pass through untouched."""
        if not self._placement:
            return env
        ctx = self._ctx if self._ctx is not None else current_context()
        try:
            exec_dev = ctx.jax_device
        except Exception:  # noqa: BLE001 — backendless contexts
            return env
        for n, v in env.items():
            try:
                if isinstance(v, jax.Array) and \
                        next(iter(v.devices())) != exec_dev:
                    env[n] = jax.device_put(v, exec_dev)
            except Exception:  # noqa: BLE001 — tracers/uncommitted values
                pass
        return env

    def _repin(self, name, arr):
        """Keep an array on its ctx-group device (grads and copied-in
        params stay beside the params they belong to)."""
        dev = self._placement.get(name)
        return jax.device_put(arr, dev) if dev is not None else arr

    def _env(self):
        env = {n: v._data for n, v in self.arg_dict.items()}
        env.update({n: v._data for n, v in self.aux_dict.items()})
        return self._to_exec_device(env)

    @property
    def arg_arrays(self):
        """Arg arrays in list_arguments order, None for unbound names —
        the positional correspondence the reference Executor guarantees."""
        return [self.arg_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict.get(n)
                for n in self._symbol.list_auxiliary_states()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    def _fwd_fn(self, training):
        from .. import autotune as _autotune
        from .. import config as _config
        # knob values AND mx.perf.autotune picks bake in at trace: the
        # epoch tracks config mutations, the generation tracks freshly
        # recorded tuning winners — either moving retraces
        cache_key = (training, (_config.epoch(), _autotune.generation()))
        if cache_key not in self._fwd_cache:
            # evict programs compiled under superseded knob epochs
            self._fwd_cache = {k: v for k, v in self._fwd_cache.items()
                               if k[1] == cache_key[1]}
            sym = self._symbol

            def run(env, key):
                with _random.trace_key_scope(key):
                    aux_updates = {}
                    outs = _eval_symbol(sym, env, training, aux_updates)
                    return outs, aux_updates

            self._fwd_cache[cache_key] = jax.jit(run)
        return self._fwd_cache[cache_key]

    # public --------------------------------------------------------------
    def _feed_inputs(self, input_map):
        """Assign forward inputs by name from a dict — the collision-safe
        entry point (names like "is_train" stay legal); forward()'s
        kwargs and the C ABI bridge both route through here."""
        from ..ndarray.ndarray import NDArray, _wrap
        for n, v in input_map.items():
            arr = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            if n in self.arg_dict:
                self.arg_dict[n]._data = arr
            else:
                self.arg_dict[n] = _wrap(arr)

    def forward(self, is_train=False, **kwargs):
        from .. import telemetry as _telemetry
        from .. import tracing as _tracing
        self._feed_inputs(kwargs)
        key = _random.new_eager_seed_key()
        with _telemetry.timer("executor.forward").time(), \
                _tracing.span("executor.forward", cat="executor"):
            outs, aux_updates = self._fwd_fn(bool(is_train))(
                self._env(), key)
        for n, v in aux_updates.items():
            if n in self.aux_dict:
                # pinned aux states (BN stats) stay on their ctx-group device
                self.aux_dict[n]._data = self._repin(n, v)
        from ..ndarray.ndarray import _wrap as _w2
        self.outputs = [_w2(o) for o in outs]
        if self._monitor:
            for name, arr in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor(name, arr)
        return self.outputs

    def _bwd_fn(self, wrt):
        """One jitted program computing outputs AND input gradients —
        forward + backward fuse into a single XLA executable (replacing the
        reference's separate backward graph executor,
        src/executor/graph_executor.cc:91)."""
        from .. import autotune as _autotune
        from .. import config as _config
        # knobs + autotune picks bake in at trace (see _fwd_fn)
        key_sig = (tuple(wrt), (_config.epoch(), _autotune.generation()))
        if key_sig not in self._bwd_cache:
            # evict programs compiled under superseded knob epochs (same
            # invalidation contract as _fwd_fn: a config.set between calls
            # must retrace the fused fwd+bwd program too)
            self._bwd_cache = {k: v for k, v in self._bwd_cache.items()
                               if k[1] == key_sig[1]}
            sym = self._symbol

            def run(wrt_vals, rest_env, cts, key):
                def fwd(wv):
                    env = dict(rest_env)
                    env.update(wv)
                    with _random.trace_key_scope(key):
                        return _eval_symbol(sym, env, True, None)

                outs, vjp = jax.vjp(fwd, wrt_vals)
                if cts is None:
                    cts_ = [jnp.ones_like(o) for o in outs]
                else:
                    cts_ = list(cts)
                (grads,) = vjp(cts_)
                return outs, grads

            self._bwd_cache[key_sig] = jax.jit(run,
                                               static_argnames=())
        return self._bwd_cache[key_sig]

    def fused_step_fn(self, wrt, optimizer, feed_sig, instrument=False):
        """ONE jitted program carrying forward + backward + optimizer
        update — the CachedOp ``static_alloc=True`` analog for the symbolic
        path (reference: src/imperative/cached_op.cc StaticForward/
        StaticBackward collapse per-op dispatch; here the whole train
        iteration is a single XLA executable and XLA owns the memory plan).

        ``wrt`` is the ordered tuple of trainable arg names; ``feed_sig``
        the per-batch input shape/dtype signature.  One program per
        (wrt, feed_sig, config-epoch) — parameters, optimizer state and the
        batch are traced pytree arguments, and params/state are DONATED on
        accelerator backends so the update happens in-place in HBM.

        Signature of the returned callable::

            new_params, new_state, aux_updates, outputs = fn(
                wrt_vals, opt_state, rest_env, feeds, key, t, lrs, wds)

        lr/wd arrive as device arrays evaluated eagerly per step (the
        ``_opt_hyper_arrays`` pattern from mxnet_tpu/parallel/trainer.py),
        so lr schedulers keep working instead of constant-folding; ``t`` is
        the traced update count for bias-corrected optimizers (Adam &c).

        ``instrument=True`` builds the numerics-instrumented VARIANT of
        the program (mx.numerics): per-op tap sites inside the forward
        plus grad./update. stats per param ride out as one extra stats
        dict appended to the return tuple.  The variant is a separate
        cache entry — the plain program stays byte-identical to a build
        without taps and toggling the capture knob never evicts it.
        """
        from .. import config as _config
        from .. import numerics as _numerics
        from .. import resilience as _resilience
        sym = self._symbol
        wrt_t = tuple(wrt)
        rescale = float(optimizer.rescale_grad)
        clip = optimizer.clip_gradient
        # nanguard bakes into the trace: when armed the program takes a
        # consecutive-bad-step streak carry and returns it (5-tuple); the
        # happy-path signature is untouched when the knob is off
        guard = _resilience.nanguard_mode()
        # the program closes over the optimizer, so its identity (and the
        # scalars baked in at trace time) is part of the key; cached entries
        # keep their optimizer alive, so id() stays unambiguous
        from .. import autotune as _autotune
        key_sig = (id(optimizer), rescale, clip, wrt_t, feed_sig, guard) \
            + _numerics.capture_token(instrument) \
            + ((_config.epoch(), _autotune.generation()),)
        fn = self._fused_cache.get(key_sig)
        if fn is not None:
            return fn
        # evict programs compiled under superseded knob epochs (same
        # invalidation contract as _fwd_cache/_bwd_cache)
        self._fused_cache = {k: v for k, v in self._fused_cache.items()
                             if k[-1] == key_sig[-1]}
        # fused Pallas optimizer epilogue (mx.kernels): trace-time
        # decision; a kernels-knob flip bumps the config epoch, so the
        # key above already forces the retrace
        from .. import kernels as _kernels
        fused_opt = _kernels.fused_step_enabled(optimizer)
        if fused_opt:
            _kernels.note_fused_step()

        def run(wrt_vals, opt_state, rest_env, feeds, key, t, lrs, wds,
                streak=None):
            env = dict(rest_env)
            env.update(feeds)

            def fwd(wv):
                e = dict(env)
                e.update(wv)
                aux_updates = {}
                with _random.trace_key_scope(key):
                    if instrument:
                        # tap values traced under vjp are vjp-internal —
                        # they escape through vjp's aux, never the outer
                        # return (a direct return would leak tracers)
                        with _numerics.collect() as fstats:
                            outs = _eval_symbol(sym, e, True, aux_updates)
                        return outs, (aux_updates, dict(fstats))
                    outs = _eval_symbol(sym, e, True, aux_updates)
                return outs, aux_updates

            outs, vjp, aux_updates = jax.vjp(fwd, wrt_vals, has_aux=True)
            stats = None
            if instrument:
                aux_updates, stats = aux_updates
            # out_grads=None semantics: ones cotangents, as in backward()
            (grads,) = vjp([jnp.ones_like(o) for o in outs])
            new_w = {}
            new_s = {}
            # stochastic optimizers (SGLD) draw from the step's traced key
            with _random.trace_key_scope(jax.random.fold_in(key, 1)):
                for i, n in enumerate(wrt_t):
                    g = grads[n] * rescale
                    if clip is not None:
                        g = jnp.clip(g, -clip, clip)
                    if stats is not None:
                        _numerics.record(stats, "grad." + n, g)
                    if fused_opt and wrt_vals[n].dtype == jnp.float32:
                        w, _m, s = optimizer.step_fused(
                            wrt_vals[n], g, opt_state[n], lrs[i], wds[i],
                            t, out_dtype=wrt_vals[n].dtype)
                        new_w[n] = w
                        new_s[n] = s
                        continue
                    w, s = optimizer.step(wrt_vals[n], g, opt_state[n],
                                          lrs[i], wds[i], t)
                    new_w[n] = w.astype(wrt_vals[n].dtype)
                    new_s[n] = s
            if stats is not None:
                # pre-guard candidate updates: on a bad step these SHOW
                # the non-finite values forensics is after
                for n in wrt_t:
                    _numerics.record(stats, "update." + n, new_w[n])
            if not guard:
                if stats is not None:
                    return new_w, new_s, aux_updates, outs, stats
                return new_w, new_s, aux_updates, outs
            # non-finite step guard: keep old params/state/aux on a bad
            # step; the check stays on-device (no host sync unless the
            # bad branch actually fires)
            finite = _resilience.all_finite(outs, grads)
            new_streak = _resilience.guarded_streak(finite, streak,
                                                    "module")
            new_w = _resilience.select_tree(finite, new_w, wrt_vals)
            new_s = _resilience.select_tree(finite, new_s, opt_state)
            aux_updates = _resilience.select_tree(
                finite, aux_updates,
                {n: rest_env[n] for n in aux_updates})
            if stats is not None:
                return new_w, new_s, aux_updates, outs, new_streak, stats
            return new_w, new_s, aux_updates, outs, new_streak

        # donation needs a real accelerator: the CPU backend can't alias
        # donated buffers (it would only warn and copy anyway)
        donate = (0, 1) if jax.default_backend() != "cpu" else ()
        from .. import perf as _perf
        fn = _perf.wrap(jax.jit(run, donate_argnums=donate),
                        "module", key_sig, source="module")
        self._fused_cache[key_sig] = fn
        from .. import profiler as _profiler
        _profiler.counter_increment("fused_compiles")
        return fn

    def backward(self, out_grads=None):
        from ..ndarray.ndarray import NDArray, _wrap
        wrt = tuple(sorted(n for n in self.arg_dict
                           if self.grad_req.get(n, "null") != "null"))
        if not wrt:
            return
        rest_env = {n: v._data for n, v in self.aux_dict.items()}
        rest_env.update({n: v._data for n, v in self.arg_dict.items()
                         if n not in wrt})
        rest_env = self._to_exec_device(rest_env)
        wrt_vals = self._to_exec_device(
            {n: self.arg_dict[n]._data for n in wrt})
        if out_grads is not None:
            if isinstance(out_grads, (NDArray, jnp.ndarray, _np.ndarray)):
                out_grads = [out_grads]
            out_grads = [g._data if isinstance(g, NDArray)
                         else jnp.asarray(g) for g in out_grads]
        key = _random.new_eager_seed_key()
        from .. import telemetry as _telemetry
        from .. import tracing as _tracing
        with _telemetry.timer("executor.backward").time(), \
                _tracing.span("executor.backward", cat="executor"):
            _, grads = self._bwd_fn(wrt)(wrt_vals, rest_env, out_grads, key)
        for n in wrt:
            g = grads[n]
            if g.dtype == jax.dtypes.float0:
                continue
            req = self.grad_req.get(n, "write")
            g = self._repin(n, g)  # grads live beside their params
            tgt = self.grad_dict.get(n)
            if tgt is None:
                self.grad_dict[n] = _wrap(g)
            elif req == "add":
                tgt._data = self._repin(n, tgt._data + g)
            else:
                tgt._data = g

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        from ..ndarray.ndarray import NDArray
        for n, v in (arg_params or {}).items():
            if n in self.arg_dict:
                self.arg_dict[n]._data = self._repin(
                    n, v._data if isinstance(v, NDArray) else jnp.asarray(v))
            elif not allow_extra_params:
                raise ValueError("unknown argument %r" % (n,))
        for n, v in (aux_params or {}).items():
            if n in self.aux_dict:
                self.aux_dict[n]._data = self._repin(
                    n, v._data if isinstance(v, NDArray) else jnp.asarray(v))
            elif not allow_extra_params:
                raise ValueError("unknown aux state %r" % (n,))

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (jit re-specializes per signature)."""
        from ..ndarray.ndarray import _wrap
        new_args = {}
        for n, v in self.arg_dict.items():
            if n in kwargs:
                # fresh arrays inherit the name's ctx-group placement
                new_args[n] = _wrap(self._repin(
                    n, jnp.zeros(tuple(kwargs[n]), v._data.dtype)))
            else:
                new_args[n] = v
        ex = Executor(self._symbol, self._ctx, new_args,
                      dict(self.grad_dict), self.grad_req,
                      dict(self.aux_dict))
        ex._placement = dict(self._placement)
        return ex

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor = callback
