"""``mx.operator`` — Python-defined custom operators.

Reference: python/mxnet/operator.py — `CustomOp` (forward/backward with
assign), `CustomOpProp` (shape/type inference + registration), `register`;
native side runs these on dedicated worker threads outside the engine to
dodge GIL deadlocks (src/operator/custom/custom-inl.h:52-166).

TPU-native re-design: a custom op is host Python called through
``jax.pure_callback``, so it composes with jit/vmap of the surrounding
program (the engine-thread machinery is unnecessary — XLA treats the
callback as an opaque host node with declared output shapes, which is what
CustomOpProp.infer_shape provides).  ``backward`` is wired in with
``jax.custom_vjp``, keeping autograd exact.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from .ops.registry import register as _register_op, Operator
from .ndarray.ndarray import NDArray, _wrap

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators"]

_CUSTOM = {}


class CustomOp:
    """Base class for the imperative kernel (reference: operator.py:428)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """reference semantics: honor the write/add/null request."""
        if req == "null":
            return
        if isinstance(src, NDArray):
            src = src._data
        if req == "add":
            dst._data = dst._data + jnp.asarray(src)
        else:
            dst._data = jnp.asarray(src)


class CustomOpProp:
    """Shape/type metadata + kernel factory (reference: operator.py:474)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Decorator registering a CustomOpProp subclass under `reg_name`; the
    op becomes reachable as mx.nd.Custom(..., op_type=reg_name) and by name
    (reference: mx.operator.register)."""

    def deco(prop_cls):
        _CUSTOM[reg_name] = prop_cls

        def op_fn(*arrays, **attrs):
            attrs.pop("op_type", None)
            prop = prop_cls(**attrs)
            in_shapes = [tuple(a.shape) for a in arrays]
            in_dtypes = [a.dtype for a in arrays]
            _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
            _, out_dtypes, _ = prop.infer_type(in_dtypes)
            out_specs = tuple(jax.ShapeDtypeStruct(tuple(s), d)
                              for s, d in zip(out_shapes, out_dtypes))
            kernel = prop.create_operator(None, in_shapes, in_dtypes)

            def host_forward(*host_arrays):
                ins = [_wrap(jnp.asarray(a)) for a in host_arrays]
                outs = [_wrap(jnp.zeros(s.shape, s.dtype))
                        for s in out_specs]
                kernel.forward(True, ["write"] * len(outs), ins, outs, [])
                res = tuple(_np.asarray(o._data) for o in outs)
                return res if len(res) > 1 else res[0]

            def host_backward(host_in, host_out, host_ograds):
                ins = [_wrap(jnp.asarray(a)) for a in host_in]
                outs = [_wrap(jnp.asarray(a)) for a in host_out]
                ogs = [_wrap(jnp.asarray(a)) for a in host_ograds]
                igs = [_wrap(jnp.zeros_like(jnp.asarray(a)))
                       for a in host_in]
                kernel.backward(["write"] * len(igs), ogs, ins, outs, igs,
                                [])
                res = tuple(_np.asarray(g._data) for g in igs)
                return res if len(res) > 1 else res[0]

            single_out = len(out_specs) == 1

            @jax.custom_vjp
            def call(*xs):
                out = jax.pure_callback(
                    host_forward,
                    out_specs[0] if single_out else out_specs, *xs)
                return out

            def call_fwd(*xs):
                out = call(*xs)
                return out, (xs, out)

            def call_bwd(res, ct):
                xs, out = res
                outs = (out,) if single_out else tuple(out)
                cts = (ct,) if single_out else tuple(ct)
                in_specs = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype)
                                 for x in xs)
                grads = jax.pure_callback(
                    host_backward,
                    in_specs[0] if len(in_specs) == 1 else in_specs,
                    xs, outs, cts)
                return (grads,) if len(in_specs) == 1 else tuple(grads)

            call.defvjp(call_fwd, call_bwd)
            return call(*arrays)

        _CUSTOM_FNS[reg_name] = op_fn
        _register_op(reg_name)(op_fn)
        return prop_cls

    return deco


_CUSTOM_FNS = {}


def get_all_registered_operators():
    return list(_CUSTOM)


@_register_op("Custom")
def _custom(*arrays, op_type=None, **attrs):
    """mx.nd.Custom(data..., op_type='name') / sym.Custom parity entry."""
    if op_type not in _CUSTOM_FNS:
        raise ValueError("custom op %r is not registered" % (op_type,))
    return _CUSTOM_FNS[op_type](*arrays, **attrs)
