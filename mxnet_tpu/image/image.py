"""``mx.image`` — imperative image loading/augmentation + ImageIter.

Reference: python/mxnet/image/image.py (imdecode/imresize/augmenters/
`ImageIter` over .rec or .lst files) and the native augmenter chain
(src/io/image_aug_default.cc).

TPU-native re-design: decode/augment run on the host in NumPy/PIL (the chip
never decodes JPEGs); per-image randomness uses numpy RNG; batches leave the
host already in final layout so the device sees one contiguous H2D transfer.
Heavy batch math (normalize/crop of a whole batch) can run as jax ops via the
regular nd namespace.
"""
from __future__ import annotations

import os
import random as _pyrandom

import numpy as _np

from ..io import DataIter, DataBatch, DataDesc
from ..ndarray.ndarray import NDArray, _wrap
import jax.numpy as jnp

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize",
           "random_size_crop", "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "ResizeAug", "ForceResizeAug", "CenterCropAug", "RandomCropAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "LightingAug", "ColorJitterAug", "RandomOrderAug", "Augmenter",
           "HueJitterAug", "RandomGrayAug", "RandomSizedCropAug",
           "SequentialAug", "CreateAugmenter", "ImageIter", "scale_down"]


def _to_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return _np.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decode jpeg/png bytes to an HWC uint8 NDArray (reference:
    mx.image.imdecode over cv2; PIL here)."""
    from ..recordio import _decode_img
    arr = _decode_img(bytes(buf), 1 if flag else 0)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return _wrap(jnp.asarray(arr))


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    from PIL import Image
    arr = _to_np(src).astype(_np.uint8)
    mode = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC}.get(
        interp, Image.BILINEAR)
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    pil = Image.fromarray(arr[:, :, 0] if squeeze else arr)
    out = _np.asarray(pil.resize((w, h), mode))
    if out.ndim == 2:
        out = out[:, :, None]
    return _wrap(jnp.asarray(out))


def scale_down(src_size, size):
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, size * h // w
    else:
        new_w, new_h = size * w // h, size
    return imresize(arr, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _to_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(arr, size[0], size[1], interp)
    return _wrap(jnp.asarray(arr))


def center_crop(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (float, int)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * aspect)))
        new_h = int(round(_np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(arr, size, interp)


def color_normalize(src, mean, std=None):
    arr = _to_np(src).astype(_np.float32)
    arr -= _np.asarray(mean, _np.float32)
    if std is not None:
        arr /= _np.asarray(std, _np.float32)
    return _wrap(jnp.asarray(arr))


# ----------------------------------------------------------------- augmenters

class Augmenter:
    """Base augmenter (reference: mx.image.Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _wrap(jnp.asarray(_to_np(src)[:, ::-1].copy()))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return _wrap(jnp.asarray(_to_np(src).astype(self.typ)))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return _wrap(jnp.asarray(_to_np(src).astype(_np.float32) * alpha))


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        arr = _to_np(src).astype(_np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (arr * self._coef).sum(axis=2, keepdims=True)
        mean = gray.mean() * (1.0 - alpha)
        return _wrap(jnp.asarray(arr * alpha + mean))


class SaturationJitterAug(Augmenter):
    _coef = ContrastJitterAug._coef

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        arr = _to_np(src).astype(_np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (arr * self._coef).sum(axis=2, keepdims=True) * (1.0 - alpha)
        return _wrap(jnp.asarray(arr * alpha + gray))


class LightingAug(Augmenter):
    """PCA lighting noise (AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return _wrap(jnp.asarray(_to_np(src).astype(_np.float32) + rgb))


class ColorJitterAug(Augmenter):
    def __init__(self, brightness, contrast, saturation):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.augs = [a for a in (
            BrightnessJitterAug(brightness) if brightness else None,
            ContrastJitterAug(contrast) if contrast else None,
            SaturationJitterAug(saturation) if saturation else None)
            if a is not None]

    def __call__(self, src):
        augs = list(self.augs)
        _pyrandom.shuffle(augs)
        for a in augs:
            src = a(src)
        return src


class HueJitterAug(Augmenter):
    """Random hue rotation in YIQ space (reference: image.py
    HueJitterAug — same Gray-world rotation matrix construction)."""

    _yiq = _np.array([[0.299, 0.587, 0.114],
                      [0.596, -0.274, -0.321],
                      [0.211, -0.523, 0.311]], _np.float32)
    _yiq_inv = _np.array([[1.0, 0.956, 0.621],
                          [1.0, -0.272, -0.647],
                          [1.0, -1.107, 1.705]], _np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        arr = _to_np(src).astype(_np.float32)
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        rot = _np.array([[1.0, 0.0, 0.0],
                         [0.0, u, -w],
                         [0.0, w, u]], _np.float32)
        t = self._yiq_inv @ rot @ self._yiq
        return _wrap(jnp.asarray(arr @ t.T))


class RandomGrayAug(Augmenter):
    """Randomly convert to 3-channel grayscale (reference: image.py
    RandomGrayAug)."""

    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = _to_np(src).astype(_np.float32)
            gray = (arr * self._coef).sum(axis=2, keepdims=True)
            return _wrap(jnp.asarray(_np.broadcast_to(
                gray, arr.shape).copy()))
        return src


class RandomSizedCropAug(Augmenter):
    """Random area+aspect crop resized to `size` (reference: image.py
    RandomSizedCropAug — the Inception-style crop)."""

    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class SequentialAug(Augmenter):
    """Apply a fixed sequence of augmenters (reference: image.py
    SequentialAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter chain factory (reference: mx.image.CreateAugmenter
    / image_aug_default.cc defaults)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4., 4 / 3.), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if pca_noise > 0:
        eigval = [55.46, 4.794, 1.148]
        eigvec = [[-0.5675, 0.7192, 0.4009],
                  [-0.5808, -0.0045, -0.8140],
                  [-0.5836, -0.6948, 0.4203]]
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and len(_np.atleast_1d(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class _NativeRecAdapter:
    """Duck-types MXIndexedRecordIO over the C++ mmap reader."""

    def __init__(self, native_file):
        self._f = native_file
        self.keys = list(range(len(native_file)))

    def read_idx(self, i):
        return self._f.read_index(i)


class ImageIter(DataIter):
    """Image iterator over .rec (RecordIO) or .lst + image dir (reference:
    mx.image.ImageIter / src/io/iter_image_recordio_2.cc ImageRecordIter).

    Output layout NCHW float32, label float32.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imgrec=None, data_name="data", label_name="softmax_label",
                 last_batch_handle="pad", seed=None, **kwargs):
        super().__init__(batch_size)
        # seed controls shuffle determinism (reference ImageRecordIter's
        # `seed` param); a private Random keeps it isolated from the global
        # stream so two seeded iterators are independently reproducible.
        self._shuffle_rng = _pyrandom.Random(seed) if seed is not None \
            else _pyrandom
        self._last_batch_handle = last_batch_handle
        assert path_imgrec or path_imglist or imgrec
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        if aug_list is not None:
            self.auglist = aug_list
        else:
            import inspect
            aug_params = set(
                inspect.signature(CreateAugmenter).parameters) - {
                    "data_shape"}
            unknown = set(kwargs) - aug_params
            if unknown:
                raise TypeError("ImageIter: unknown arguments %s"
                                % (sorted(unknown),))
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        self.data_name = data_name
        self.label_name = label_name
        self.shuffle = shuffle
        self._records = None
        self._imglist = None
        if path_imgrec or imgrec is not None:
            self._rec = None
            if imgrec is not None:
                self._rec = imgrec
            else:
                try:  # native mmap reader (src/native/recordio.cc)
                    from ..native import NativeRecordFile, available
                    if available():
                        self._rec = _NativeRecAdapter(
                            NativeRecordFile(path_imgrec))
                except Exception:
                    self._rec = None
                if self._rec is None:
                    from .recordio_compat import open_indexed
                    self._rec = open_indexed(path_imgrec)
            self._keys = list(self._rec.keys)
        else:
            self._imglist = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    labels = _np.asarray(parts[1:-1], _np.float32)
                    self._imglist.append((parts[-1], labels))
            self._keys = list(range(len(self._imglist)))
        # multi-host sharding: each part reads a disjoint key range
        # (reference: ImageRecordIter part_index/num_parts)
        n = len(self._keys)
        lo = n * part_index // num_parts
        hi = n * (part_index + 1) // num_parts
        self._keys = self._keys[lo:hi]
        self.path_root = path_root
        self._order = list(range(len(self._keys)))
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shp)]

    def reset(self):
        if self.shuffle:
            self._shuffle_rng.shuffle(self._order)
        self.cur = 0

    def _read_sample(self, i):
        from .recordio_compat import record_to_image
        key = self._keys[self._order[i]]
        if self._imglist is not None:
            fname, label = self._imglist[key]
            img = imread(os.path.join(self.path_root, fname))
        else:
            label, img = record_to_image(self._rec.read_idx(key))
        for aug in self.auglist:
            img = aug(img)
        arr = _to_np(img).astype(_np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.transpose(2, 0, 1)  # HWC -> CHW
        return arr, _np.atleast_1d(_np.asarray(label, _np.float32))

    def _decode_pool(self, workers):
        pool = getattr(self, "_pool", None)
        if pool is None or getattr(self, "_pool_size", 0) != workers:
            if pool is not None:
                # drain in-flight decode jobs before replacing the pool so
                # a mid-flight knob change can't abandon submitted work
                pool.shutdown(wait=True)
            from concurrent.futures import ThreadPoolExecutor
            pool = ThreadPoolExecutor(max_workers=workers,
                                      thread_name_prefix="mx-decode")
            self._pool = pool
            self._pool_size = workers
        return pool

    def close(self):
        """Release the decode thread pool (idempotent; also runs on GC)."""
        pool = getattr(self, "_pool", None)
        if pool is not None:
            self._pool = None
            self._pool_size = 0
            pool.shutdown(wait=False)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _decode_positions(self, positions):
        """Decode + augment the samples at the given epoch positions.

        ``io.decode_workers`` > 1 maps them over a shared thread pool (PIL
        decode releases the GIL — the reference's preprocess_threads
        analog); otherwise decodes serially on the calling thread.  Either
        way each read retries transient I/O errors with backoff and draws
        injected 'io' faults (docs/RESILIENCE.md), and pool workers carry
        the caller's tracing context so decode spans keep their parentage.
        """
        from .. import config as _config
        from .. import resilience as _resilience
        from .. import tracing as _tracing

        def read(pos):
            return _resilience.call_with_retry(
                self._read_sample, pos, kind="io", inject_faults=True)

        workers = int(_config.get("io.decode_workers") or 0)
        if workers <= 1 or len(positions) <= 1:
            return [read(p) for p in positions]
        pool = self._decode_pool(workers)
        with _tracing.span("io.decode", cat="io", workers=workers):
            # wrap_context per submit: each job gets its OWN context copy
            # (a shared copy cannot be entered by two threads at once)
            jobs = [pool.submit(_tracing.wrap_context(read), p)
                    for p in positions]
            return [j.result() for j in jobs]

    def _batch_samples(self):
        """One batch of decoded samples: ``([(slot, data, label), ...],
        pad)`` with the wrap-pad of short final batches applied.  The
        assembly hook shared with iterators composing over this one
        (io.ImageDetRecordIter)."""
        n = len(self._keys)
        if self.cur >= n:
            raise StopIteration
        if self._last_batch_handle == "discard" and n - self.cur < \
                self.batch_size:
            raise StopIteration
        slots = []  # (batch slot, epoch position)
        pad = 0
        i = 0
        while i < self.batch_size and self.cur < n:
            slots.append((i, self.cur))
            self.cur += 1
            i += 1
        if i < self.batch_size:
            pad = self.batch_size - i
            for j in range(i, self.batch_size):  # wrap-pad from epoch start
                slots.append((j, j % max(i, 1)))
        decoded = self._decode_positions([pos for _, pos in slots])
        return [(slot, d, l)
                for (slot, _), (d, l) in zip(slots, decoded)], pad

    def next(self):
        C, H, W = self.data_shape
        samples, pad = self._batch_samples()
        batch_data = _np.zeros((self.batch_size, C, H, W), _np.float32)
        batch_label = _np.zeros((self.batch_size, self.label_width),
                                _np.float32)
        for slot, d, l in samples:
            batch_data[slot] = d
            batch_label[slot] = l[:self.label_width]
        label = batch_label[:, 0] if self.label_width == 1 else batch_label
        return DataBatch([_wrap(jnp.asarray(batch_data))],
                         [_wrap(jnp.asarray(label))], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
