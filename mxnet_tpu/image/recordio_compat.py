"""Bridge between mx.image iterators and the RecordIO container.

Reference: the native ImageRecordIOParser2 (src/io/iter_image_recordio_2.cc)
parses records and decodes images inside the C++ pipeline; here the split is
recordio.py (framing) + this module (record -> labeled image).
"""
from __future__ import annotations

import numpy as _np

from ..recordio import MXIndexedRecordIO, unpack, _decode_img  # noqa: F401


def open_indexed(path_imgrec):
    idx_path = path_imgrec[:-4] + ".idx" if path_imgrec.endswith(".rec") \
        else path_imgrec + ".idx"
    return MXIndexedRecordIO(idx_path, path_imgrec, "r")


def record_to_image(buf):
    """record bytes -> (label array, HWC uint8 image array)."""
    header, payload = unpack(buf)
    label = header.label
    img = _decode_img(payload)
    if img.ndim == 2:
        img = img[:, :, None]
    return _np.atleast_1d(_np.asarray(label, _np.float32)), img
