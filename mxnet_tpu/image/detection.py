"""Detection augmenters: joint image + bounding-box transforms.

Reference: python/mxnet/image/detection.py (DetBorrowAug,
DetRandomSelectAug, DetHorizontalFlipAug, DetRandomCropAug,
DetRandomPadAug, CreateDetAugmenter) over
src/io/image_det_aug_default.cc.

Labels are (N, 5+) float arrays, rows ``[cls, x1, y1, x2, y2, ...]`` with
corner coordinates NORMALIZED to [0, 1] — the reference's det-label
layout.  Every augmenter maps ``(src, label) -> (src, label)``; images are
host numpy/NDArray HWC like the classification augmenters (host-side data
pipeline, device sees only the batched output).
"""
from __future__ import annotations

import random as _pyrandom

import jax.numpy as jnp
import numpy as _np

from .image import Augmenter, _to_np, _wrap, fixed_crop

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter"]


class DetAugmenter:
    """Base detection augmenter (reference: detection.py DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only augmenter into the detection chain (labels pass
    through untouched) — reference detection.py:70."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly apply one of `aug_list` (or none, with skip_prob) —
    reference detection.py:84."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return _pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and mirror the box x-coordinates — reference
    detection.py:103."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            arr = _to_np(src)[:, ::-1, :]
            label = _np.array(label, _np.float32, copy=True)
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            x2 = label[valid, 3].copy()
            label[valid, 1] = 1.0 - x2
            label[valid, 3] = 1.0 - x1
            return _wrap(jnp.asarray(arr.copy())), label
        return src, label


def _box_coverage(crop, boxes):
    """Object coverage of one crop vs (N,4) boxes: intersection over BOX
    area (the reference's min_object_covered semantics,
    image_det_aug_default.cc — NOT IoU, which would starve small
    objects)."""
    tl = _np.maximum(crop[:2], boxes[:, :2])
    br = _np.minimum(crop[2:], boxes[:, 2:4])
    wh = _np.clip(br - tl, 0, None)
    inter = wh[:, 0] * wh[:, 1]
    area_b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    return inter / _np.maximum(area_b, 1e-12)


class DetRandomCropAug(DetAugmenter):
    """SSD-style constrained random crop (reference detection.py:161 /
    image_det_aug_default.cc): sample crops until one has IoU with some
    object >= min_object_covered; boxes are clipped/renormalized and
    fully-outside objects are dropped (marked cls=-1 to keep row count
    static for batching)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _sample_crop(self, label):
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(*self.area_range)
            ar = _pyrandom.uniform(*self.aspect_ratio_range)
            w = min(_np.sqrt(area * ar), 1.0)
            h = min(_np.sqrt(area / ar), 1.0)
            x0 = _pyrandom.uniform(0, 1 - w)
            y0 = _pyrandom.uniform(0, 1 - h)
            crop = _np.array([x0, y0, x0 + w, y0 + h], _np.float32)
            valid = label[:, 0] >= 0
            if not valid.any():
                return crop
            cov = _box_coverage(crop, label[valid, 1:5])
            if cov.max() >= self.min_object_covered:
                return crop
        return None

    def __call__(self, src, label):
        label = _np.array(label, _np.float32, copy=True)
        crop = self._sample_crop(label)
        if crop is None:
            return src, label
        arr = _to_np(src)
        h, w = arr.shape[:2]
        x0, y0, x1, y1 = crop
        px0, py0 = int(x0 * w), int(y0 * h)
        pw = max(1, int((x1 - x0) * w))
        ph = max(1, int((y1 - y0) * h))
        out = fixed_crop(arr, px0, py0, pw, ph, None, 2)
        cw, ch = x1 - x0, y1 - y0
        valid = label[:, 0] >= 0
        b = label[valid, 1:5]
        b[:, [0, 2]] = (b[:, [0, 2]] - x0) / cw
        b[:, [1, 3]] = (b[:, [1, 3]] - y0) / ch
        clipped = _np.clip(b, 0.0, 1.0)
        # drop objects whose center left the crop (reference center rule)
        cx = (b[:, 0] + b[:, 2]) / 2
        cy = (b[:, 1] + b[:, 3]) / 2
        keep = (cx > 0) & (cx < 1) & (cy > 0) & (cy < 1)
        label[valid, 1:5] = clipped
        cls = label[valid, 0]
        cls[~keep] = -1.0
        label[valid, 0] = cls
        return out, label


class DetRandomPadAug(DetAugmenter):
    """Zoom-out pad (reference detection.py:280): place the image on a
    larger canvas filled with `fill`, shrinking the boxes accordingly."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = _to_np(src)
        h, w = arr.shape[:2]
        label = _np.array(label, _np.float32, copy=True)
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(*self.area_range)
            ar = _pyrandom.uniform(*self.aspect_ratio_range)
            nw = int(w * _np.sqrt(scale * ar))
            nh = int(h * _np.sqrt(scale / ar))
            if nw >= w and nh >= h:
                break
        else:
            return src, label
        x0 = _pyrandom.randint(0, nw - w)
        y0 = _pyrandom.randint(0, nh - h)
        canvas = _np.empty((nh, nw, arr.shape[2]), arr.dtype)
        canvas[...] = _np.asarray(self.pad_val, arr.dtype)
        canvas[y0:y0 + h, x0:x0 + w, :] = arr
        valid = label[:, 0] >= 0
        b = label[valid, 1:5]
        b[:, [0, 2]] = (b[:, [0, 2]] * w + x0) / nw
        b[:, [1, 3]] = (b[:, [1, 3]] * h + y0) / nh
        label[valid, 1:5] = b
        return _wrap(jnp.asarray(canvas)), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Detection augmenter chain factory (reference: detection.py:342
    CreateDetAugmenter — same knob set and ordering)."""
    from .image import (ResizeAug, ForceResizeAug, CastAug,
                        ColorJitterAug, HueJitterAug, RandomGrayAug,
                        LightingAug, ColorNormalizeAug)
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(1.0, area_range[0]), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # force final shape AFTER the geometric augs (reference ordering)
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if pca_noise > 0:
        eigval = [55.46, 4.794, 1.148]
        eigvec = [[-0.5675, 0.7192, 0.4009],
                  [-0.5808, -0.0045, -0.8140],
                  [-0.5836, -0.6948, 0.4203]]
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval,
                                                eigvec)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and len(_np.atleast_1d(mean)):
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist
