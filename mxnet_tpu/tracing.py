"""``mx.tracing`` — causal spans, Chrome-trace sink, and the hang watchdog.

Reference: the engine profiler's per-thread event buffers dumped as Chrome
tracing JSON (src/profiler/profiler.h:251 DumpProfile) gave the reference
*attribution* — every engine op, IO thread and KVStore transfer on one
timeline.  mx.telemetry (PR 2) answers "how long do steps take" in
aggregate; this module answers "where inside THIS step did the time go,
and across which threads":

  * SPANS — ``with tracing.span("module.step"): ...`` opens a timed span
    whose parent/child links are carried by a ``contextvars.ContextVar``,
    so causality survives thread hops: the io.py prefetch worker runs
    under the context captured when the prefetcher started (see
    ``wrap_context``), and its spans carry the parent's ``trace_id``.
    Every span also enters a ``jax.profiler.TraceAnnotation`` while a
    device trace is active, so framework phases (fwd/bwd/opt-update/
    prefetch/push/pull/allreduce) show up nested inside XLA's own profile.
  * CHROME SINK — ``MXNET_TPU_TRACE=chrome:<path>`` (the ``tracing.sink``
    knob, same pattern as ``telemetry.sink``) streams finished spans as
    Chrome trace-event JSON ("array format": one event per line, so a
    killed job still leaves a loadable file — ``load_trace`` parses both
    complete and truncated traces).  ``tools/trace_merge.py`` aligns this
    host plane with the device-op plane from a jax.profiler capture into
    one two-plane trace.
  * FLIGHT RECORDER + WATCHDOG — a bounded ring of the last K span/step
    events, plus ``MXNET_TPU_WATCHDOG=<secs>``: a daemon thread that,
    when no train step completes within the deadline, dumps all Python
    thread stacks, every OPEN span with its age, the event ring, device
    memory, and telemetry gauge/counter snapshots to a timestamped JSON
    report — then lets the job keep running.  A silent multi-host hang
    becomes a diagnosable artifact instead of a killed process.

Near-zero overhead when off: ``span()`` returns a shared no-op object
unless a sink, the watchdog, or a device trace is active — one function
call and three reads on the hot path.
"""
from __future__ import annotations

import contextvars
import json
import os
import sys
import threading
import time
import traceback
from collections import deque

from . import profiler as _profiler

__all__ = ["span", "current_span", "wrap_context", "configure_sink",
           "configure_watchdog", "configure_ring", "enabled", "sink_path",
           "open_spans", "ring_events", "record_event", "notify_step",
           "dump_watchdog_report", "load_trace", "validate_trace_events",
           "validate_watchdog_report", "register_stall_probe",
           "unregister_stall_probe", "check_stall_probes",
           "last_step_age_s", "Span"]

# ------------------------------------------------------------- span context
#: the active span for the calling context.  contextvars (not thread-local)
#: so explicit context capture (wrap_context / contextvars.copy_context)
#: carries parentage across the prefetch-thread and server-thread hops.
_CURRENT = contextvars.ContextVar("mxtpu_trace_span", default=None)

_ID_LOCK = threading.Lock()
_NEXT_ID = [1]


def _new_id():
    with _ID_LOCK:
        i = _NEXT_ID[0]
        _NEXT_ID[0] += 1
    return i


# perf_counter gives durations; this pair anchors them to the unix epoch so
# Chrome-trace timestamps are comparable across processes on one host.
_TS_BASE_UNIX = time.time()
_TS_BASE_PERF = time.perf_counter()


def _unix_from_perf(t_perf):
    return _TS_BASE_UNIX + (t_perf - _TS_BASE_PERF)


# open-span registry: span_id -> Span, for the watchdog's "where is every
# thread stuck" report.  Guarded by its own lock; entries exist only while
# tracing is active, so the hot path pays nothing when off.
_OPEN_LOCK = threading.Lock()
_OPEN = {}  # guarded-by: _OPEN_LOCK

# ------------------------------------------------------------ chrome sink
# Sink state is rebound only under _SINK_LOCK; the `_SINK is None` fast
# checks on the emit path read lock-free on purpose (a stale None just
# drops one event during reconfigure), hence [writes] mode.
_SINK_LOCK = threading.Lock()
_SINK = None          # guarded-by[writes]: _SINK_LOCK
_SINK_PATH = None     # guarded-by[writes]: _SINK_LOCK
# guarded-by[writes]: _SINK_LOCK — idents that already emitted thread_name
_SINK_THREADS = None


def configure_sink(spec):
    """(Re)configure the Chrome-trace span sink from ``chrome:<path>`` (a
    bare path is accepted as shorthand); empty/None disables.  Called by the
    ``tracing.sink`` knob's set() hook and at import from
    ``MXNET_TPU_TRACE``."""
    global _SINK, _SINK_PATH, _SINK_THREADS
    spec = (spec or "").strip()
    path = None
    if spec:
        path = spec[len("chrome:"):] if spec.startswith("chrome:") else spec
        if not path:
            raise ValueError("tracing sink %r names no path" % (spec,))
    with _SINK_LOCK:
        if path == _SINK_PATH and (_SINK is None) == (path is None):
            return
        if _SINK is not None:
            try:
                _SINK.write("%s\n]\n" % json.dumps(
                    {"ph": "M", "pid": os.getpid(), "tid": 0,
                     "name": "trace_end", "args": {}}))
                _SINK.close()
            except Exception:  # noqa: BLE001 — best-effort close
                pass
            _SINK = None
        _SINK_PATH = path
        _SINK_THREADS = set()
        if path is not None:
            _SINK = open(path, "w", buffering=1)
            _SINK.write("[\n")
            _write_event_locked({
                "ph": "M", "pid": os.getpid(), "tid": 0,
                "name": "process_name",
                "args": {"name": "mxnet_tpu host (pid %d)" % os.getpid()}})


def _write_event_locked(event):
    _SINK.write(json.dumps(event) + ",\n")


def _emit(event):
    """Append one Chrome trace event (no-op when the sink is off); lazily
    emits a thread_name metadata record the first time a thread appears."""
    if _SINK is None:
        return
    tid = event.get("tid")
    with _SINK_LOCK:
        if _SINK is None:
            return
        if tid is not None and tid not in _SINK_THREADS:
            _SINK_THREADS.add(tid)
            _write_event_locked({
                "ph": "M", "pid": os.getpid(), "tid": tid,
                "name": "thread_name",
                "args": {"name": threading.current_thread().name}})
        _write_event_locked(event)


def enabled():
    return _SINK is not None


def sink_path():
    return _SINK_PATH


def flush():
    """Force buffered span events to disk (fsync) — the sink's streaming
    line format is truncation-tolerant (load_trace), so a flushed partial
    trace from a preempted run is fully loadable."""
    with _SINK_LOCK:
        if _SINK is None:
            return
        _SINK.flush()
        try:
            os.fsync(_SINK.fileno())
        except OSError:  # pragma: no cover — non-fsyncable sink
            pass


# --------------------------------------------------------- flight recorder
_RING_LOCK = threading.Lock()
_RING = deque(maxlen=256)  # guarded-by: _RING_LOCK


def configure_ring(size):
    """Resize the flight-recorder ring (the ``tracing.ring_size`` knob);
    existing events are carried over up to the new bound."""
    global _RING
    size = max(1, int(size))
    with _RING_LOCK:
        if _RING.maxlen != size:
            _RING = deque(_RING, maxlen=size)


def record_event(kind, name, **fields):
    """Append one event to the flight-recorder ring (always cheap: one
    dict build and a lock-guarded deque append; callers gate on activity)."""
    rec = {"ts": round(time.time(), 6), "kind": kind, "name": name,
           "thread": threading.current_thread().name}
    rec.update(fields)
    with _RING_LOCK:
        _RING.append(rec)
    return rec


def ring_events():
    with _RING_LOCK:
        return list(_RING)


# ----------------------------------------------------------------- spans
class _NoopSpan:
    """Shared do-nothing span: the off-path cost of ``span()``."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class Span:
    """One timed causal span.  Use via ``tracing.span(name)``."""

    __slots__ = ("name", "cat", "args", "trace_id", "span_id", "parent_id",
                 "thread", "_t0", "_token", "_ann")

    def __init__(self, name, cat, args):
        self.name = name
        self.cat = cat
        self.args = args
        self.trace_id = None
        self.span_id = None
        self.parent_id = None
        self.thread = None
        self._token = None
        self._ann = None

    def __enter__(self):
        parent = _CURRENT.get()
        if parent is not None and parent.trace_id is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = _new_id()
            self.parent_id = None
        self.span_id = _new_id()
        self.thread = threading.current_thread().name
        self._token = _CURRENT.set(self)
        with _OPEN_LOCK:
            _OPEN[self.span_id] = self
        if _profiler._STATE["running"]:
            # nest the framework phase inside XLA's own device profile
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # noqa: BLE001 — device tracing unavailable
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def age_s(self):
        """Seconds since the span opened (watchdog report column)."""
        return time.perf_counter() - self._t0

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
            self._ann = None
        with _OPEN_LOCK:
            _OPEN.pop(self.span_id, None)
        _CURRENT.reset(self._token)
        args = {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id}
        if self.args:
            args.update(self.args)
        if exc_type is not None:
            args["error"] = "%s: %s" % (exc_type.__name__, exc)
        _emit({"name": self.name, "cat": self.cat, "ph": "X",
               "ts": round(_unix_from_perf(self._t0) * 1e6, 3),
               "dur": round(dur * 1e6, 3), "pid": os.getpid(),
               "tid": threading.get_ident(), "args": args})
        if _WD_DEADLINE is not None:
            record_event("span", self.name, dur_ms=round(dur * 1e3, 4),
                         trace_id=self.trace_id, span_id=self.span_id,
                         parent_id=self.parent_id,
                         **({"error": args["error"]}
                            if exc_type is not None else {}))
        return False


def span(name, cat="host", **args):
    """Open a causal span.  Returns a shared no-op unless the Chrome sink,
    the watchdog, or a device trace is active — the near-zero-overhead
    contract for instrumented hot paths."""
    if _SINK is None and _WD_DEADLINE is None \
            and not _profiler._STATE["running"]:
        return _NOOP
    return Span(name, cat, args)


def current_span():
    """The innermost active span for this context, or None."""
    return _CURRENT.get()


def open_spans():
    """Live spans as [{name, age_s, trace_id, span_id, parent_id, thread}],
    oldest first — the watchdog report's open-span table."""
    with _OPEN_LOCK:
        spans = sorted(_OPEN.values(), key=lambda s: -s.age_s())
    return [{"name": s.name, "age_s": round(s.age_s(), 4),
             "trace_id": s.trace_id, "span_id": s.span_id,
             "parent_id": s.parent_id, "thread": s.thread} for s in spans]


def wrap_context(fn):
    """Bind ``fn`` to the CALLER's context so spans it opens in another
    thread keep this trace's parentage — the dmlc::ThreadedIter hop fix.
    ``PrefetchingIter`` wraps its worker with this."""
    ctx = contextvars.copy_context()
    def bound(*a, **kw):
        return ctx.run(fn, *a, **kw)
    return bound


# -------------------------------------------------------------- watchdog
# Watchdog state is (re)armed only under _WD_LOCK; the hot-path
# `_WD_DEADLINE is not None` checks and the report writer read lock-free
# (worst case: one poll against a stale deadline), hence [writes] mode.
_WD_LOCK = threading.Lock()
_WD_DEADLINE = None     # guarded-by[writes]: _WD_LOCK — seconds, None=off
_WD_THREAD = None       # guarded-by[writes]: _WD_LOCK
_WD_STOP = None         # guarded-by[writes]: _WD_LOCK
_WD_REPORT_DIR = ""     # guarded-by[writes]: _WD_LOCK
# perf_counter of the last completed train step (any source); the watchdog
# measures hang age against this
_LAST_PROGRESS = [time.perf_counter()]

# stall probes: name -> fn(interval_s) -> dict|None.  Subsystems with their
# own liveness signal (e.g. the mx.serving batcher, whose queue can stall
# while train steps keep completing) register here; the watchdog polls them
# alongside the step-age check and flight-records whatever dict a probe
# returns.  Probes must be fast, thread-safe, and never raise (exceptions
# are swallowed — the watchdog must not die).
_PROBE_LOCK = threading.Lock()
_STALL_PROBES = {}  # guarded-by: _PROBE_LOCK


def register_stall_probe(name, fn):
    """Register a watchdog stall probe.  ``fn(interval_s)`` is called from
    the watchdog thread each poll; it returns None while healthy, or a
    JSON-serializable dict describing the stall (the dict lands in the
    flight-recorder ring and the watchdog report's ``stalls`` section).
    Re-registering a name replaces the probe."""
    with _PROBE_LOCK:
        _STALL_PROBES[name] = fn


def unregister_stall_probe(name):
    with _PROBE_LOCK:
        _STALL_PROBES.pop(name, None)


def check_stall_probes(interval_s):
    """Run every registered stall probe against ``interval_s`` and return
    ``{name: info}`` for those reporting a stall.  Probe exceptions are
    swallowed (a broken probe must not take the watchdog down).  Public so
    tests and on-demand dumps can evaluate probes without a live
    watchdog."""
    with _PROBE_LOCK:
        probes = list(_STALL_PROBES.items())
    stalls = {}
    for name, fn in probes:
        try:
            info = fn(interval_s)
        except Exception:  # noqa: BLE001 — the watchdog must not die
            continue
        if info:
            stalls[name] = info
    return stalls


def last_step_age_s():
    """Seconds since the last completed train step (any source) — the
    watchdog's hang-age signal, exposed for the mx.obs ``/healthz``
    endpoint.  Measured from process start until the first step."""
    return time.perf_counter() - _LAST_PROGRESS[0]


def notify_step(source, step, wall_s, error=None):
    """Called by ``telemetry.step_scope`` on every completed train step —
    the watchdog's liveness signal.  A FAILING step still counts as
    progress (an exception loop is not a hang) but lands in the flight
    recorder with its error."""
    _LAST_PROGRESS[0] = time.perf_counter()
    if _WD_DEADLINE is not None or _SINK is not None:
        fields = {"source": source, "step": step,
                  "wall_ms": round(wall_s * 1e3, 4)}
        if error is not None:
            fields["error"] = error
        record_event("step_error" if error is not None else "step",
                     "%s.step" % source, **fields)


def configure_watchdog(seconds, report_dir=None):
    """(Re)arm the hang watchdog from the ``tracing.watchdog`` knob
    (``MXNET_TPU_WATCHDOG``): ``seconds`` > 0 starts a daemon thread that
    dumps a flight-recorder report whenever no train step completes for
    that long, then re-arms; 0/None stops it."""
    global _WD_DEADLINE, _WD_THREAD, _WD_STOP, _WD_REPORT_DIR
    seconds = float(seconds or 0)
    with _WD_LOCK:
        if report_dir is not None:
            _WD_REPORT_DIR = report_dir
        if _WD_STOP is not None:
            _WD_STOP.set()
            _WD_THREAD = None
            _WD_STOP = None
        if seconds <= 0:
            _WD_DEADLINE = None
            return
        _WD_DEADLINE = seconds
        _LAST_PROGRESS[0] = time.perf_counter()
        _WD_STOP = threading.Event()
        _WD_THREAD = threading.Thread(
            target=_watchdog_loop, args=(seconds, _WD_STOP),
            name="mxtpu-watchdog", daemon=True)
        _WD_THREAD.start()


def _watchdog_loop(deadline, stop):
    poll = max(0.02, min(deadline / 4.0, 1.0))
    last_seen = _LAST_PROGRESS[0]
    fires = 0               # consecutive reports with no progress between
    next_fire_age = deadline
    probe_next = {}         # per-probe refire backoff (perf_counter floor)
    while not stop.wait(poll):
        # subsystem stall probes run on their own liveness signal: a
        # serving-queue stall is a stall even while train steps complete
        now = time.perf_counter()
        stalls = {name: info
                  for name, info in check_stall_probes(deadline).items()
                  if probe_next.get(name, 0.0) <= now}
        for name, info in stalls.items():
            probe_next[name] = now + deadline * 4  # refire backoff
            record_event("stall", name, **info)
            from . import telemetry as _telemetry
            _telemetry.counter("tracing.stall_probe_fires").inc()
            try:
                path = dump_watchdog_report(stalls={name: info})
                print("mxnet_tpu watchdog: stall probe %r fired — "
                      "flight-recorder report: %s" % (name, path),
                      file=sys.stderr)
            except Exception as exc:  # noqa: BLE001 — must not die
                print("mxnet_tpu watchdog: stall report dump failed: %s"
                      % (exc,), file=sys.stderr)
        progress = _LAST_PROGRESS[0]
        if progress != last_seen:
            last_seen = progress
            fires = 0
            next_fire_age = deadline
        age = time.perf_counter() - progress
        if age < next_fire_age:
            continue
        try:
            path = dump_watchdog_report(stalled_s=age)
            print("mxnet_tpu watchdog: no step completed in %.3fs "
                  "(deadline %.3fs) — flight-recorder report: %s"
                  % (age, deadline, path), file=sys.stderr)
        except Exception as exc:  # noqa: BLE001 — the watchdog must not die
            print("mxnet_tpu watchdog: report dump failed: %s" % (exc,),
                  file=sys.stderr)
        from . import telemetry as _telemetry
        _telemetry.counter("tracing.watchdog_fires").inc()
        # exponential backoff while ONE stall persists (reports at 1x, 3x,
        # 7x, 15x... the deadline, capped at 8x spacing): a multi-hour hang
        # yields a handful of reports, not hundreds — and the job runs on
        fires += 1
        next_fire_age = age + deadline * min(2 ** fires, 8)


def _thread_stacks():
    """Every live Python thread with its current stack — the py-spy view
    the watchdog freezes into the report."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        out.append({
            "thread_id": ident,
            "name": t.name if t is not None else "<unknown>",
            "daemon": bool(t.daemon) if t is not None else None,
            "stack": [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)],
        })
    out.sort(key=lambda rec: rec["name"])
    return out


def dump_watchdog_report(stalled_s=None, path=None, stalls=None):
    """Write the flight-recorder report: thread stacks, open spans with
    ages, the event ring, device memory, and telemetry gauge/counter
    snapshots.  ``stalls`` ({probe_name: info}) attaches subsystem
    stall-probe findings — e.g. the mx.serving probe's open requests and
    breaker states.  Public so a debugger (or a SIGQUIT handler) can dump
    the same artifact on demand; returns the report path."""
    from . import telemetry as _telemetry
    snap = _telemetry.snapshot()
    if stalled_s is None:
        stalled_s = time.perf_counter() - _LAST_PROGRESS[0]
    report = {
        "event": "watchdog_report",
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "deadline_s": _WD_DEADLINE,
        "last_step_age_s": round(stalled_s, 4),
        "threads": _thread_stacks(),
        "open_spans": open_spans(),
        "ring": ring_events(),
        "device_mem_bytes": _safe_device_memory(),
        "gauges": snap["gauges"],
        "counters": snap["counters"],
    }
    if stalls:
        report["stalls"] = stalls
    if path is None:
        stamp = time.strftime("%Y%m%d_%H%M%S") \
            + "_%03d" % int((time.time() % 1) * 1000)
        path = os.path.join(_WD_REPORT_DIR or ".",
                            "watchdog_report_%s.json" % stamp)
    with open(path, "w") as f:
        json.dump(report, f, indent=1, default=str)
    return path


def _safe_device_memory():
    """Device memory from the watchdog thread: the runtime may be mid-hang,
    so any backend error degrades to null rather than killing the dump."""
    from . import telemetry as _telemetry
    try:
        return _telemetry.device_memory_bytes()
    except Exception:  # noqa: BLE001
        return None


# ------------------------------------------------------- trace (re)loading
def load_trace(path):
    """Parse a Chrome trace file into a list of event dicts.  Accepts the
    object form ({"traceEvents": [...]}), a complete JSON array, and this
    module's line-oriented array format EVEN WHEN TRUNCATED by a kill —
    half-written trailing lines are dropped."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            return list(doc.get("traceEvents", []))
        if isinstance(doc, list):
            return [e for e in doc if isinstance(e, dict)]
    except ValueError:
        pass
    events = []
    for line in text.splitlines():
        line = line.strip().rstrip(",")
        if line in ("", "[", "]"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue  # the killed job's half-written final line
        if isinstance(obj, dict):
            events.append(obj)
    return events


def validate_trace_events(events):
    """Validate span events from a chrome-sink trace: every complete ("X")
    event carries timing and span identity, and every parent_id resolves to
    a span_id present in the trace.  Returns the X events; raises
    ValueError naming the offence."""
    xs = [e for e in events if e.get("ph") == "X"]
    if not xs:
        raise ValueError("trace contains no span (ph=X) events")
    ids = set()
    for e in xs:
        for key in ("name", "ts", "dur", "pid", "tid"):
            if key not in e:
                raise ValueError("span event missing %r: %r" % (key, e))
        args = e.get("args", {})
        for key in ("trace_id", "span_id"):
            if not isinstance(args.get(key), int):
                raise ValueError("span %r missing %s" % (e.get("name"), key))
        ids.add(args["span_id"])
    for e in xs:
        parent = e.get("args", {}).get("parent_id")
        if parent is not None and parent not in ids:
            raise ValueError("span %r parent_id %s matches no span in the "
                             "trace" % (e.get("name"), parent))
    return xs


_REPORT_REQUIRED = {"event": str, "ts": (int, float),
                    "last_step_age_s": (int, float), "threads": list,
                    "open_spans": list, "ring": list, "gauges": dict,
                    "counters": dict}


def validate_watchdog_report(rec):
    """Validate one parsed watchdog report against the documented schema
    (docs/OBSERVABILITY.md); raises ValueError naming the offending
    field."""
    if not isinstance(rec, dict):
        raise ValueError("report must be an object, got %r" % (rec,))
    for key, typ in _REPORT_REQUIRED.items():
        if key not in rec:
            raise ValueError("report missing required field %r" % (key,))
        if not isinstance(rec[key], typ):
            raise ValueError("field %r: expected %s, got %r"
                             % (key, typ, rec[key]))
    if rec["event"] != "watchdog_report":
        raise ValueError("not a watchdog report: event=%r" % (rec["event"],))
    if not rec["threads"]:
        raise ValueError("report carries no thread stacks")
    for t in rec["threads"]:
        if not isinstance(t, dict) or not t.get("stack"):
            raise ValueError("thread entry without a stack: %r" % (t,))
    for s in rec["open_spans"]:
        for key in ("name", "age_s", "trace_id", "span_id"):
            if key not in s:
                raise ValueError("open span missing %r: %r" % (key, s))
    return rec


# honor MXNET_TPU_TRACE / MXNET_TPU_WATCHDOG at import (the knobs' set()
# hooks handle runtime flips); telemetry imports this module at its own
# bottom, so any training-path import activates the env vars.
from . import telemetry as _telemetry_mod  # noqa: E402

_telemetry_mod._TRACING_STEP_HOOK = notify_step

from . import config as _config  # noqa: E402

try:
    configure_ring(_config.get("tracing.ring_size"))
    configure_sink(_config.get("tracing.sink"))
    configure_watchdog(_config.get("tracing.watchdog"),
                       report_dir=_config.get("tracing.watchdog_dir"))
except KeyError:  # pragma: no cover — config stripped of the knobs
    pass
