"""``mx.runtime`` — runtime feature introspection.

Reference: python/mxnet/runtime.py `Features`/`feature_list` over the libinfo
build flags (include/mxnet/libinfo.h:141-193 — CUDA, CUDNN, MKLDNN,
DIST_KVSTORE...).  TPU-native: features reflect what this build can actually
do (platform backends, pallas availability, distributed init), discovered at
query time instead of baked at compile time.
"""
from __future__ import annotations

from collections import namedtuple

__all__ = ["Feature", "Features", "feature_list", "is_enabled"]

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    import jax
    feats = {}

    def have(mod):
        try:
            __import__(mod)
            return True
        except Exception:
            return False

    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:
        platforms = set()
    feats["TPU"] = "tpu" in platforms
    feats["CPU"] = True
    feats["GPU"] = "gpu" in platforms or "cuda" in platforms
    feats["PALLAS"] = have("jax.experimental.pallas")
    feats["DIST_KVSTORE"] = True          # jax.distributed-backed
    feats["INT64_TENSOR_SIZE"] = True
    feats["SIGNAL_HANDLER"] = True
    feats["OPENCV"] = False               # PIL-based image path
    feats["PIL"] = have("PIL")
    feats["BLAS_OPEN"] = False            # XLA supplies all kernels
    feats["MKLDNN"] = False
    feats["CUDA"] = False
    feats["CUDNN"] = False
    feats["NATIVE_IO"] = _native_io_available()
    return feats


def _native_io_available():
    try:
        from .native import lib as _native  # noqa: F401
        return _native.available()
    except Exception:
        return False


class Features(dict):
    """Mapping name -> Feature (reference Features mapping API)."""

    instance = None

    def __init__(self):
        super().__init__([(k, Feature(k, v)) for k, v in _detect().items()])

    def __repr__(self):
        return "[%s]" % ", ".join(
            "✔ %s" % k if v.enabled else "✖ %s" % k
            for k, v in sorted(self.items()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("Feature %r does not exist" % (feature_name,))
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())


def is_enabled(feature_name):
    return Features().is_enabled(feature_name)
