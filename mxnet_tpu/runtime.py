"""``mx.runtime`` — runtime feature introspection + program tuning.

Reference: python/mxnet/runtime.py `Features`/`feature_list` over the libinfo
build flags (include/mxnet/libinfo.h:141-193 — CUDA, CUDNN, MKLDNN,
DIST_KVSTORE...).  TPU-native: features reflect what this build can actually
do (platform backends, pallas availability, distributed init), discovered at
query time instead of baked at compile time.

Program tuning (``scan_stack``): the knob-driven scan/unroll + remat
policy applied to repeated-layer stacks — the TPU analog of the
reference graph optimizer's memory-vs-recompute planning.  Scanning the
layer stack keeps trace and compile time O(1) in depth; a
``jax.checkpoint`` policy trades activation memory for recompute in the
backward pass.
"""
from __future__ import annotations

from collections import namedtuple

__all__ = ["Feature", "Features", "feature_list", "is_enabled",
           "scan_stack", "stack_tuning", "stack_candidates",
           "checkpoint_policy"]

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    import jax
    feats = {}

    def have(mod):
        try:
            __import__(mod)
            return True
        except Exception:
            return False

    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:
        platforms = set()
    feats["TPU"] = "tpu" in platforms
    feats["CPU"] = True
    feats["GPU"] = "gpu" in platforms or "cuda" in platforms
    feats["PALLAS"] = have("jax.experimental.pallas")
    feats["DIST_KVSTORE"] = True          # jax.distributed-backed
    feats["INT64_TENSOR_SIZE"] = True
    feats["SIGNAL_HANDLER"] = True
    feats["OPENCV"] = False               # PIL-based image path
    feats["PIL"] = have("PIL")
    feats["BLAS_OPEN"] = False            # XLA supplies all kernels
    feats["MKLDNN"] = False
    feats["CUDA"] = False
    feats["CUDNN"] = False
    feats["NATIVE_IO"] = _native_io_available()
    return feats


def _native_io_available():
    try:
        from .native import lib as _native  # noqa: F401
        return _native.available()
    except Exception:
        return False


class Features(dict):
    """Mapping name -> Feature (reference Features mapping API)."""

    instance = None

    def __init__(self):
        super().__init__([(k, Feature(k, v)) for k, v in _detect().items()])

    def __repr__(self):
        return "[%s]" % ", ".join(
            "✔ %s" % k if v.enabled else "✖ %s" % k
            for k, v in sorted(self.items()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("Feature %r does not exist" % (feature_name,))
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())


def is_enabled(feature_name):
    return Features().is_enabled(feature_name)


# --------------------------------------------------------- program tuning
def stack_candidates():
    """The discrete (mode, remat) grid mx.perf.autotune measures over:
    every legal combination of the two validated knobs.  'unroll' pairs
    with remat-off only — rematerializing an inlined stack re-traces
    every layer body, which the scan path exists to avoid."""
    return (("scan", ""), ("scan", "dots"), ("scan", "full"),
            ("unroll", ""))


def stack_tuning():
    """The active (mode, remat) pair: the validated knobs
    ``runtime.stack_mode`` (scan|unroll) and ``runtime.remat``
    (''|dots|full) — or, while BOTH knobs sit at their defaults, a
    persisted mx.perf.autotune winner for the layer stack (measured by
    ``autotune.search_stack``; an explicit knob always wins)."""
    from . import autotune as _autotune
    from . import config as _config
    tuned = _autotune.stack_pick()
    if tuned is not None:
        return tuned
    return _config.get("runtime.stack_mode"), _config.get("runtime.remat")


def checkpoint_policy(name):
    """Resolve a remat policy name to a ``jax.checkpoint`` policy:
    '' -> None (no remat), 'dots' -> save matmul results and recompute
    the elementwise rest (the MFU-friendly default — recomputing
    elementwise ops is cheap, recomputing matmuls is not), 'full' ->
    save only the layer inputs (maximum memory saving)."""
    import jax
    if name == "dots":
        pols = jax.checkpoint_policies
        return (getattr(pols, "dots_saveable", None)
                or pols.checkpoint_dots)
    if name == "full":
        return "full"
    return None


def scan_stack(body, carry, xs):
    """Run ``body(carry, x)`` over the leading axis of ``xs`` with the
    knob-selected stacking strategy.

    ``runtime.stack_mode='scan'`` (default) lowers one ``lax.scan`` —
    the program traces and compiles the layer ONCE regardless of depth,
    which is where the trace/compile-time win over an unrolled stack
    comes from.  ``'unroll'`` inlines every layer (larger programs,
    but XLA can specialize per layer).  ``runtime.remat`` wraps the body
    in ``jax.checkpoint`` with the matching policy; '' applies no wrapper
    at all so default-knob programs stay byte-identical to the
    pre-tuning lowering.
    """
    import jax
    from jax import lax
    mode, remat = stack_tuning()
    if remat:
        policy = checkpoint_policy(remat)
        if policy == "full":
            body = jax.checkpoint(body)
        else:
            body = jax.checkpoint(body, policy=policy)
    if mode == "unroll":
        import jax.numpy as jnp
        leaves = jax.tree_util.tree_leaves(xs)
        n = leaves[0].shape[0]
        ys = []
        for i in range(n):
            x = jax.tree_util.tree_map(lambda a: a[i], xs)
            carry, y = body(carry, x)
            ys.append(y)
        if ys and ys[0] is None:
            return carry, None
        # stack per-layer outputs like lax.scan does (the paged KV-cache
        # writes of models/transformer.py ride the layer scan as ys)
        return carry, jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *ys)
    return lax.scan(body, carry, xs)
