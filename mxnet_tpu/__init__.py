"""mxnet_tpu — a TPU-native deep-learning framework with the capability
surface of Apache MXNet (reference: ZhennanQin/incubator-mxnet ~1.6-dev).

Compute path: JAX/XLA (+Pallas kernels); scaling path: jax.sharding Mesh +
shard_map collectives over ICI/DCN.  See SURVEY.md at the repo root for the
reference→TPU design mapping.

Import as ``import mxnet_tpu as mx`` — the namespace mirrors ``mxnet``:
mx.nd, mx.sym, mx.gluon, mx.autograd, mx.cpu()/mx.gpu()/mx.tpu(), mx.io,
mx.metric, mx.optimizer, mx.init, mx.random, mx.kv.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .base import MXNetError
# legacy-launcher compatibility: a DMLC_ROLE=server/scheduler process exits
# cleanly at import (the roles are obsolete — dist_sync is peer allreduce)
from .kvstore_server import _init_kvstore_server_module
_init_kvstore_server_module()
del _init_kvstore_server_module
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import ndarray
from . import ndarray as nd
from .ndarray.ndarray import NDArray
from . import random
from . import autograd

# Subsystems are imported lazily to keep `import mxnet_tpu` light.
_LAZY = {
    "gluon": ".gluon",
    "sym": ".symbol",
    "symbol": ".symbol",
    "optimizer": ".optimizer",
    "metric": ".metric",
    "init": ".initializer",
    "initializer": ".initializer",
    "lr_scheduler": ".lr_scheduler",
    "io": ".io",
    "image": ".image",
    "kvstore": ".kvstore",
    "kv": ".kvstore",
    "mod": ".module",
    "module": ".module",
    "rnn": ".rnn",
    "callback": ".callback",
    "model": ".model",
    "profiler": ".profiler",
    "telemetry": ".telemetry",
    "tracing": ".tracing",
    "obs": ".obs",
    "resilience": ".resilience",
    "elastic": ".elastic",
    "perf": ".perf",
    "kernels": ".kernels",
    "runtime": ".runtime",
    "test_utils": ".test_utils",
    "parallel": ".parallel",
    "amp": ".amp",
    "np": ".numpy",
    "npx": ".numpy_extension",
    "visualization": ".visualization",
    "viz": ".visualization",
    "recordio": ".recordio",
    "engine": ".engine",
    "monitor": ".monitor",
    "operator": ".operator",
    "native": ".native",
    "contrib": ".contrib",
    "deploy": ".deploy",
    "serving": ".serving",
    "quantization": ".quantization",
    "config": ".config",
    "compat": ".compat",
    "dlpack": ".dlpack",
    "library": ".library",
    "rtc": ".rtc",
    "attribute": ".attribute",
    "AttrScope": ".attribute",
    "executor": ".executor",
    "executor_manager": ".executor_manager",
    "kvstore_server": ".kvstore_server",
    "log": ".log",
    "util": ".util",
    "registry": ".registry",
    "libinfo": ".libinfo",
}


def __getattr__(name):
    import importlib
    if name in _LAZY:
        mod = importlib.import_module(_LAZY[name], __name__)
        # CamelCase entries are classes re-exported from their module
        # (e.g. mx.AttrScope from mx.attribute)
        val = getattr(mod, name) if name[:1].isupper() else mod
        globals()[name] = val
        return val
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
