"""Contrib operators: bounding-box / detection ops.

Reference: src/operator/contrib/bounding_box-inl.h (box_nms with the
index-trick for XLA-hostile dynamic output counts), multibox_* (SSD anchors,
src/operator/contrib/multibox_prior.cc), ROI pooling
(src/operator/roi_pooling.cc).

TPU-native design: everything is fixed-shape.  NMS keeps `topk` boxes and
marks suppressed entries with -1 score instead of shrinking the output
(exactly the trick the reference uses to keep shapes static); the O(k^2)
suppression matrix runs as dense math on the MXU via lax.scan over a
fixed-size loop, which XLA fuses — no serialized host loop.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


def _corner_iou(a, b):
    """IoU of [..., 4] corner boxes (xmin,ymin,xmax,ymax)."""
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:4], b[..., 2:4])
    wh = jnp.clip(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[..., 2] - a[..., 0], 0) * \
        jnp.clip(a[..., 3] - a[..., 1], 0)
    area_b = jnp.clip(b[..., 2] - b[..., 0], 0) * \
        jnp.clip(b[..., 3] - b[..., 1], 0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


def _center_to_corner(x):
    cx, cy, w, h = x[..., 0], x[..., 1], x[..., 2], x[..., 3]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


@register("box_iou", aliases=("_contrib_box_iou",))
def _box_iou(lhs, rhs, format="corner", **_):
    a = jnp.asarray(lhs)
    b = jnp.asarray(rhs)
    if format == "center":
        a = _center_to_corner(a)
        b = _center_to_corner(b)
    return _corner_iou(a[..., :, None, :], b[..., None, :, :])


def _nms_one(boxes, valid_thresh, overlap_thresh, topk, score_index,
             coord_start, id_index, force_suppress):
    """NMS for one [N, K] element array.  Returns same-shape output with
    suppressed/invalid rows' score set to -1, sorted by score desc —
    matching the reference's in-place semantics
    (src/operator/contrib/bounding_box-inl.h)."""
    n = boxes.shape[0]
    scores = boxes[:, score_index]
    valid = scores > valid_thresh
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    sorted_boxes = boxes[order]
    sorted_valid = valid[order]
    coords = lax.dynamic_slice_in_dim(sorted_boxes, coord_start, 4, axis=1)
    iou = _corner_iou(coords[:, None, :], coords[None, :, :])
    if id_index >= 0 and not force_suppress:
        same_class = sorted_boxes[:, id_index][:, None] == \
            sorted_boxes[:, id_index][None, :]
        iou = jnp.where(same_class, iou, 0.0)
    suppress_matrix = (iou > overlap_thresh) & sorted_valid[None, :]
    if topk > 0:
        in_topk = jnp.arange(n) < topk
        sorted_valid = sorted_valid & in_topk

    def body(keep, i):
        # suppressed if any earlier kept box overlaps it
        earlier = (jnp.arange(n) < i) & keep
        sup = jnp.any(earlier & suppress_matrix[:, i])
        keep = keep.at[i].set(keep[i] & ~sup)
        return keep, None

    keep0 = sorted_valid
    keep, _ = lax.scan(body, keep0, jnp.arange(n))
    return sorted_boxes.at[:, score_index].set(
        jnp.where(keep, sorted_boxes[:, score_index], -1.0))


@register("box_nms", aliases=("_contrib_box_nms", "box_non_maximum_suppression"))
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1,
             force_suppress=False, in_format="corner",
             out_format="corner", **_):
    x = jnp.asarray(data)
    shape = x.shape
    flat = x.reshape((-1,) + shape[-2:])
    fn = lambda b: _nms_one(b, valid_thresh, overlap_thresh, int(topk),
                            int(score_index), int(coord_start),
                            int(id_index), bool(force_suppress))
    out = jax.vmap(fn)(flat)
    return out.reshape(shape)


@register("bipartite_matching", aliases=("_contrib_bipartite_matching",),
          differentiable=False, num_outputs=2)
def _bipartite_matching(dist, is_ascend=False, threshold=0.5, topk=-1, **_):
    """Greedy bipartite matching (reference:
    src/operator/contrib/bipartite_matching.cc).  dist: [..., N, M]."""
    x = jnp.asarray(dist)
    shape = x.shape
    flat = x.reshape((-1,) + shape[-2:])

    def match_one(d):
        n, m = d.shape
        big = jnp.inf if is_ascend else -jnp.inf

        def body(carry, _):
            dd, row_match, col_used = carry
            flat_idx = jnp.argmin(dd) if is_ascend else jnp.argmax(dd)
            i, j = flat_idx // m, flat_idx % m
            val = dd[i, j]
            ok = (val <= threshold) if is_ascend else (val >= threshold)
            row_match = jnp.where(ok, row_match.at[i].set(j), row_match)
            col_used = jnp.where(ok, col_used.at[j].set(1), col_used)
            dd = dd.at[i, :].set(big)
            dd = dd.at[:, j].set(big)
            return (dd, row_match, col_used), None

        iters = min(n, m) if topk <= 0 else min(topk, min(n, m))
        (d_, row_match, col_used), _ = lax.scan(
            body, (d, jnp.full((n,), -1, jnp.int32),
                   jnp.zeros((m,), jnp.int32)), None, length=iters)
        return row_match.astype(jnp.float32), col_used.astype(jnp.float32)

    rows, cols = jax.vmap(match_one)(flat)
    return (rows.reshape(shape[:-1]), cols.reshape(shape[:-2] + shape[-1:]))


@register("ROIPooling", aliases=("roi_pooling",))
def _roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0, **_):
    """ROI max pooling (reference: src/operator/roi_pooling.cc).
    data [B,C,H,W]; rois [R,5] (batch_idx, x1, y1, x2, y2)."""
    x = jnp.asarray(data)
    r = jnp.asarray(rois)
    B, C, H, W = x.shape
    ph, pw = pooled_size

    def pool_one(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = jnp.round(roi[1:5] * spatial_scale)
        h = jnp.maximum(y2 - y1 + 1, 1.0)
        w = jnp.maximum(x2 - x1 + 1, 1.0)
        y_lo = jnp.clip(jnp.floor(y1 + jnp.arange(ph) / ph * h), 0, H - 1)
        y_hi = jnp.clip(jnp.ceil(y1 + (jnp.arange(ph) + 1) / ph * h), 1, H)
        x_lo = jnp.clip(jnp.floor(x1 + jnp.arange(pw) / pw * w), 0, W - 1)
        x_hi = jnp.clip(jnp.ceil(x1 + (jnp.arange(pw) + 1) / pw * w), 1, W)
        img = x[b]  # [C, H, W]
        # dense mask-based max per cell keeps shapes static
        yy = jnp.arange(H)[None, :]
        xx = jnp.arange(W)[None, :]
        ymask = (yy >= y_lo[:, None]) & (yy < y_hi[:, None])   # [ph, H]
        xmask = (xx >= x_lo[:, None]) & (xx < x_hi[:, None])   # [pw, W]
        cell = ymask[:, None, None, :, None] & \
            xmask[None, :, None, None, :]                       # [ph,pw,1,H,W]
        vals = jnp.where(cell, img[None, None, :, :, :], -jnp.inf)
        return jnp.max(vals, axis=(3, 4)).transpose(2, 0, 1)    # [C,ph,pw]

    return jax.vmap(pool_one)(r)


@register("MultiBoxPrior", aliases=("_contrib_MultiBoxPrior",),
          differentiable=False)
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5), **_):
    """SSD anchor generation (reference:
    src/operator/contrib/multibox_prior.cc).  data [B,C,H,W] ->
    [1, H*W*(S+R-1), 4] corner anchors."""
    x = jnp.asarray(data)
    H, W = x.shape[-2], x.shape[-1]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[1] if steps[1] > 0 else 1.0 / H
    step_x = steps[0] if steps[0] > 0 else 1.0 / W
    cy = (jnp.arange(H) + offsets[1]) * step_y
    cx = (jnp.arange(W) + offsets[0]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # [H,W,2]
    whs = []
    for s in sizes:
        whs.append((s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0])))
    for r in ratios[1:]:
        whs.append((sizes[0] * jnp.sqrt(r), sizes[0] / jnp.sqrt(r)))
    whs = jnp.asarray(whs)  # [A, 2] (w, h)
    cyx = cyx[:, :, None, :]
    w = whs[None, None, :, 0] / 2
    h = whs[None, None, :, 1] / 2
    xmin = cyx[..., 1] - w
    ymin = cyx[..., 0] - h
    xmax = cyx[..., 1] + w
    ymax = cyx[..., 0] + h
    anchors = jnp.stack([xmin, ymin, xmax, ymax], axis=-1)
    anchors = anchors.reshape(1, -1, 4)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors


@register("box_encode", aliases=("_contrib_box_encode",), num_outputs=2)
def _box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
                stds=(0.1, 0.1, 0.2, 0.2), **_):
    """Encode matched boxes as regression targets (reference:
    src/operator/contrib/bounding_box.cc box_encode)."""
    a = jnp.asarray(anchors)
    g = jnp.take_along_axis(jnp.asarray(refs),
                            jnp.asarray(matches)[..., None].astype(jnp.int32),
                            axis=-2)
    aw = a[..., 2] - a[..., 0]
    ah = a[..., 3] - a[..., 1]
    ax = (a[..., 0] + a[..., 2]) / 2
    ay = (a[..., 1] + a[..., 3]) / 2
    gw = g[..., 2] - g[..., 0]
    gh = g[..., 3] - g[..., 1]
    gx = (g[..., 0] + g[..., 2]) / 2
    gy = (g[..., 1] + g[..., 3]) / 2
    tx = ((gx - ax) / jnp.maximum(aw, 1e-12) - means[0]) / stds[0]
    ty = ((gy - ay) / jnp.maximum(ah, 1e-12) - means[1]) / stds[1]
    tw = (jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-12), 1e-12))
          - means[2]) / stds[2]
    th = (jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-12), 1e-12))
          - means[3]) / stds[3]
    targets = jnp.stack([tx, ty, tw, th], axis=-1)
    mask = (jnp.asarray(samples) > 0.5)[..., None]
    return jnp.where(mask, targets, 0.0), mask.astype(targets.dtype)


@register("box_decode", aliases=("_contrib_box_decode",))
def _box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
                clip=-1.0, format="corner", **_):
    d = jnp.asarray(data)
    a = jnp.asarray(anchors)
    if format == "corner":
        aw = a[..., 2] - a[..., 0]
        ah = a[..., 3] - a[..., 1]
        ax = (a[..., 0] + a[..., 2]) / 2
        ay = (a[..., 1] + a[..., 3]) / 2
    else:
        ax, ay, aw, ah = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    ox = d[..., 0] * std0 * aw + ax
    oy = d[..., 1] * std1 * ah + ay
    dw = d[..., 2] * std2
    dh = d[..., 3] * std3
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    ow = jnp.exp(dw) * aw / 2
    oh = jnp.exp(dh) * ah / 2
    return jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)


# ----------------------------------------------------- quantization primitives
# (reference: src/operator/quantization/quantize_v2.cc, dequantize.cc; the
# contrib.quantization driver builds on these)

@register("_contrib_quantize_v2", aliases=("quantize_v2",),
          differentiable=False, num_outputs=3)
def _quantize_v2(data, min_calib_range=None, max_calib_range=None,
                 out_type="int8", **_):
    x = jnp.asarray(data)
    lo = jnp.asarray(min_calib_range if min_calib_range is not None
                     else x.min())
    hi = jnp.asarray(max_calib_range if max_calib_range is not None
                     else x.max())
    amax = jnp.maximum(jnp.abs(lo), jnp.abs(hi))
    scale = 127.0 / jnp.maximum(amax, 1e-12)
    q = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
    return q, -amax, amax


@register("_contrib_dequantize", aliases=("dequantize",),
          differentiable=False)
def _dequantize(data, min_range, max_range, out_type="float32", **_):
    q = jnp.asarray(data).astype(jnp.float32)
    amax = jnp.maximum(jnp.abs(jnp.asarray(min_range)),
                       jnp.abs(jnp.asarray(max_range)))
    return q * (amax / 127.0)


@register("_sim_quant", differentiable=False)
def _sim_quant(data, amax=1.0, **_):
    """Simulated-affine int8: round onto the int8 grid, stay f32 (AQT
    pattern — keeps every op a pure jax function on MXU-friendly dtypes)."""
    s = 127.0 / max(float(amax), 1e-12)
    return jnp.clip(jnp.round(jnp.asarray(data) * s), -127, 127) / s


def _to_int8(x, amax):
    """Symmetric per-tensor int8.  amax <= 0 means DYNAMIC range: compute
    |max| from the tensor at runtime (the calib_mode='none' path — reference
    quantize_v2's min_calib_range-less mode)."""
    x = jnp.asarray(x)
    amax = jnp.asarray(amax, jnp.float32)
    amax = jnp.where(amax > 0, amax,
                     jnp.max(jnp.abs(x)).astype(jnp.float32))
    s = 127.0 / jnp.maximum(amax, 1e-12)
    q = jnp.clip(jnp.round(x * s), -127, 127)
    return q.astype(jnp.int8), s


@register("_contrib_quantized_fully_connected",
          aliases=("quantized_fully_connected",), differentiable=False)
def _quantized_fully_connected(data, weight, bias=None, amax_data=1.0,
                               amax_weight=1.0, num_hidden=None,
                               no_bias=False, flatten=True, **_):
    """REAL int8 dense: both operands quantized to int8, contracted on the
    MXU with s32 accumulation, rescaled back to f32 (reference:
    src/operator/quantization/quantized_fully_connected.cc; the quantize ->
    int8 GEMM -> dequantize chain is fused into one op here so XLA keeps the
    int8 tensors internal)."""
    x = jnp.asarray(data)
    if flatten:
        x = x.reshape(x.shape[0], -1)
    xq, sx = _to_int8(x, amax_data)
    wq, sw = _to_int8(weight, amax_weight)
    # contract x's LAST axis with w's input axis — same semantics as the
    # dense FC (ops/nn.py jnp.dot(x, w.T)) for flatten=False ndim>2 inputs
    acc = lax.dot_general(xq, wq, (((xq.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) / (sx * sw)
    if bias is not None and not no_bias:
        out = out + jnp.asarray(bias)
    return out


@register("_contrib_quantized_conv", aliases=("quantized_conv",),
          differentiable=False)
def _quantized_conv(data, weight, bias=None, amax_data=1.0, amax_weight=1.0,
                    kernel=None, stride=None, dilate=None, pad=None,
                    num_filter=None, num_group=1, no_bias=False, layout=None,
                    **_):
    """REAL int8 convolution with s32 accumulation (reference:
    src/operator/quantization/quantized_conv.cu)."""
    x = jnp.asarray(data)
    w = jnp.asarray(weight)
    ndim = x.ndim - 2
    from .nn import _tup, _conv_dims
    stride = _tup(stride, ndim)
    dilate = _tup(dilate, ndim)
    pad = _tup(pad if pad is not None else 0, ndim)
    pad = pad if isinstance(pad[0], tuple) else tuple((p, p) for p in pad)
    xq, sx = _to_int8(x, amax_data)
    wq, sw = _to_int8(w, amax_weight)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _conv_dims(ndim))
    acc = lax.conv_general_dilated(
        xq, wq, window_strides=stride, padding=pad, rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) / (sx * sw)
    if bias is not None and not no_bias:
        out = out + jnp.asarray(bias).reshape((1, -1) + (1,) * ndim)
    return out


@register("MultiBoxTarget", aliases=("_contrib_MultiBoxTarget",),
          differentiable=False, num_outputs=3)
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5,
                     variances=(0.1, 0.1, 0.2, 0.2), **_):
    """SSD training targets (reference:
    src/operator/contrib/multibox_target.cc).

    anchor (1, N, 4) corner boxes; label (B, M, 5) rows [cls, x1, y1, x2,
    y2] padded with -1; cls_pred (B, C+1, N).  Returns (box_target (B,N*4),
    box_mask (B,N*4), cls_target (B,N)) — matched anchors regress their gt
    with variance scaling, background anchors are hard-negative-mined to
    ``negative_mining_ratio`` x positives by max non-background score, the
    rest get ignore_label.  All static shapes (sorting replaces the
    reference's dynamic queues).
    """
    a = jnp.asarray(anchor)[0]                       # (N, 4)
    lab = jnp.asarray(label)
    cp = jnp.asarray(cls_pred)
    B, M, _ = lab.shape
    N = a.shape[0]

    def one(lab_b, cp_b):
        valid = lab_b[:, 0] >= 0                     # (M,)
        gt = lab_b[:, 1:5]
        iou = _corner_iou(a[:, None, :], gt[None, :, :])   # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)            # per-anchor best gt
        best_iou = jnp.max(iou, axis=1)
        # Forced matching is greedy bipartite, like the reference: each
        # valid gt with POSITIVE overlap claims the globally-best remaining
        # anchor (a per-gt argmax scatter would drop a gt when two gts
        # share a best anchor).  Reuses the bipartite_matching op's claim-
        # and-retire scan; the threshold keeps zero-IoU gts from force-
        # claiming an arbitrary anchor.
        forced_gt_f, _ = _bipartite_matching(iou, is_ascend=False,
                                             threshold=1e-12)
        forced_gt = forced_gt_f.astype(jnp.int32)
        forced = forced_gt >= 0
        matched = forced | (best_iou >= overlap_threshold)
        gt_idx = jnp.where(forced, jnp.maximum(forced_gt, 0), best_gt)
        # regression targets: shared center-offset encoder (box_encode op)
        t, m = _box_encode(matched.astype(jnp.float32), gt_idx, a, gt,
                           stds=tuple(float(v) for v in variances))
        box_t = t.reshape(-1)
        box_m = jnp.broadcast_to(m, t.shape).reshape(-1)
        # hard negative mining: unmatched anchors BELOW the mining-iou
        # threshold are negative candidates; keep ratio * num_pos of them
        # (ranked by max foreground score) as background, ignore the rest.
        # ratio <= 0 disables mining: every candidate is background
        # (reference default -1, multibox_target.cc).
        neg_cand = ~matched & (best_iou < negative_mining_thresh)
        if negative_mining_ratio > 0:
            fg_score = jnp.max(cp_b[1:], axis=0)     # (N,)
            order = jnp.argsort(jnp.where(neg_cand, -fg_score, jnp.inf))
            rank = jnp.zeros((N,), jnp.int32).at[order].set(
                jnp.arange(N, dtype=jnp.int32))
            n_pos = jnp.sum(matched.astype(jnp.int32))
            keep_neg = neg_cand & (rank < (negative_mining_ratio
                                           * jnp.maximum(n_pos, 1)))
        else:
            keep_neg = neg_cand
        cls_t = jnp.where(matched, lab_b[gt_idx, 0] + 1.0,
                          jnp.where(keep_neg, 0.0, ignore_label))
        return box_t, box_m, cls_t

    box_t, box_m, cls_t = jax.vmap(one)(lab, cp)
    return box_t, box_m, cls_t


@register("MultiBoxDetection", aliases=("_contrib_MultiBoxDetection",),
          differentiable=False)
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, nms_threshold=0.5,
                        force_suppress=False, nms_topk=-1,
                        variances=(0.1, 0.1, 0.2, 0.2), **_):
    """SSD decode + per-class NMS (reference:
    src/operator/contrib/multibox_detection.cc).

    cls_prob (B, C+1, N) softmax scores (class 0 = background); loc_pred
    (B, N*4); anchor (1, N, 4).  Output (B, N, 6) rows
    [class_id, score, x1, y1, x2, y2], suppressed rows class_id = -1 —
    the static-shape convention shared with box_nms.
    """
    cp = jnp.asarray(cls_prob)
    lp = jnp.asarray(loc_pred)
    a = jnp.asarray(anchor)[0]
    B, C1, N = cp.shape
    v0, v1, v2, v3 = (float(v) for v in variances)

    def one(cp_b, lp_b):
        # shared variance-scaled decoder (box_decode op); MultiBoxDetection
        # additionally clips the OUTPUT corners to the unit image
        boxes = _box_decode(lp_b.reshape(N, 4), a, v0, v1, v2, v3)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        cls_id = jnp.argmax(cp_b[1:], axis=0).astype(jnp.float32)  # (N,)
        score = jnp.max(cp_b[1:], axis=0)
        keep = score > threshold
        rows = jnp.concatenate(
            [jnp.where(keep, cls_id, -1.0)[:, None],
             jnp.where(keep, score, -1.0)[:, None], boxes], axis=1)
        out = _nms_one(rows, valid_thresh=0.0,
                       overlap_thresh=nms_threshold, topk=int(nms_topk),
                       score_index=1, coord_start=2, id_index=0,
                       force_suppress=bool(force_suppress))
        # reference convention: suppressed rows carry class_id -1
        return out.at[:, 0].set(jnp.where(out[:, 1] > 0, out[:, 0], -1.0))

    return jax.vmap(one)(cp, lp)
