"""Tensor ops: elementwise, broadcast, reductions, indexing, linalg.

Reference: src/operator/tensor/ (31.2 kLoC of mshadow/CUDA kernels,
elemwise_binary_broadcast_op-inl.h, matrix_op-inl.h, ordering_op-inl.h ...).
TPU-native: every op is one pure jax.numpy/lax lowering; XLA fuses elementwise
chains into single kernels, so there is no hand-written kernel layer at all.
Names follow the reference's NNVM registry where a counterpart exists.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# Active sparse-embedding routing context (parallel/embedding.py
# SparseLookupContext), installed for the duration of ONE fused-step trace
# via set_embed_context().  Thread-local: trainer traces on one thread never
# see a context installed by another (no shared mutable state, no lock).
import threading as _threading  # noqa: E402
_EMBED_ROUTE = _threading.local()


def set_embed_context(ctx):
    """Install ``ctx`` as this thread's Embedding routing context; returns
    the previous one so callers can restore it in a ``finally``."""
    prev = getattr(_EMBED_ROUTE, "ctx", None)
    _EMBED_ROUTE.ctx = ctx
    return prev

# ---------------------------------------------------------------- arithmetic

def _bin(name, fn, aliases=()):
    register(name, aliases=aliases)(lambda a, b, **_: fn(jnp.asarray(a), jnp.asarray(b)))


_bin("broadcast_add", jnp.add, aliases=("elemwise_add", "_plus", "add"))
_bin("broadcast_sub", jnp.subtract, aliases=("elemwise_sub", "_minus", "subtract"))
_bin("broadcast_mul", jnp.multiply, aliases=("elemwise_mul", "multiply"))
_bin("broadcast_div", jnp.divide, aliases=("elemwise_div", "divide"))
_bin("broadcast_mod", jnp.mod, aliases=("mod",))
_bin("broadcast_power", jnp.power, aliases=("power", "_power"))
_bin("broadcast_maximum", jnp.maximum, aliases=("maximum",))
_bin("broadcast_minimum", jnp.minimum, aliases=("minimum",))
_bin("broadcast_hypot", jnp.hypot, aliases=("hypot",))
_bin("arctan2", jnp.arctan2, aliases=("broadcast_arctan2",))


def _cmp(name, fn, aliases=()):
    @register(name, differentiable=False, aliases=aliases)
    def _op(a, b, _fn=fn, **_):
        return _fn(jnp.asarray(a), jnp.asarray(b)).astype(jnp.float32)


_cmp("broadcast_equal", jnp.equal, aliases=("_equal",))
_cmp("broadcast_not_equal", jnp.not_equal, aliases=("_not_equal",))
_cmp("broadcast_greater", jnp.greater, aliases=("_greater",))
_cmp("broadcast_greater_equal", jnp.greater_equal, aliases=("_greater_equal",))
_cmp("broadcast_lesser", jnp.less, aliases=("_lesser",))
_cmp("broadcast_lesser_equal", jnp.less_equal, aliases=("_lesser_equal",))
_cmp("broadcast_logical_and", jnp.logical_and, aliases=("logical_and",))
_cmp("broadcast_logical_or", jnp.logical_or, aliases=("logical_or",))
_cmp("broadcast_logical_xor", jnp.logical_xor, aliases=("logical_xor",))


# ---------------------------------------------------------------- unary math

def _un(name, fn, aliases=(), differentiable=True):
    register(name, aliases=aliases, differentiable=differentiable)(
        lambda a, _fn=fn, **_: _fn(jnp.asarray(a)))


_un("negative", jnp.negative)
_un("abs", jnp.abs)
_un("sign", jnp.sign)
_un("rint", jnp.rint, differentiable=False)
_un("ceil", jnp.ceil, differentiable=False)
_un("floor", jnp.floor, differentiable=False)
_un("trunc", jnp.trunc, differentiable=False)
_un("round", jnp.round, differentiable=False)
_un("exp", jnp.exp)
_un("expm1", jnp.expm1)
_un("log", jnp.log)
_un("log10", jnp.log10)
_un("log2", jnp.log2)
_un("log1p", jnp.log1p)
_un("sqrt", jnp.sqrt)
_un("rsqrt", lambda a: lax.rsqrt(a))
_un("cbrt", jnp.cbrt)
_un("rcbrt", lambda a: 1.0 / jnp.cbrt(a))
_un("square", jnp.square)
_un("reciprocal", jnp.reciprocal)
_un("sin", jnp.sin)
_un("cos", jnp.cos)
_un("tan", jnp.tan)
_un("arcsin", jnp.arcsin)
_un("arccos", jnp.arccos)
_un("arctan", jnp.arctan)
_un("sinh", jnp.sinh)
_un("cosh", jnp.cosh)
_un("tanh", jnp.tanh)
_un("arcsinh", jnp.arcsinh)
_un("arccosh", jnp.arccosh)
_un("arctanh", jnp.arctanh)
_un("degrees", jnp.degrees)
_un("radians", jnp.radians)
_un("sigmoid", jax.nn.sigmoid)
_un("softsign", jax.nn.soft_sign)
_un("relu", jax.nn.relu)
_un("erf", jax.scipy.special.erf)
_un("erfinv", jax.scipy.special.erfinv)
_un("gamma", lambda a: jnp.exp(jax.scipy.special.gammaln(a)))
_un("gammaln", jax.scipy.special.gammaln)
_un("logical_not", lambda a: jnp.logical_not(a).astype(jnp.float32),
    differentiable=False)
_un("isnan", lambda a: jnp.isnan(a).astype(jnp.float32), differentiable=False)
_un("isinf", lambda a: jnp.isinf(a).astype(jnp.float32), differentiable=False)
_un("isfinite", lambda a: jnp.isfinite(a).astype(jnp.float32), differentiable=False)


@register("clip")
def _clip(a, a_min=None, a_max=None, **_):
    return jnp.clip(a, a_min, a_max)


@register("cast", aliases=("Cast",))
def _cast(a, dtype="float32", **_):
    from ..base import dtype_np
    return jnp.asarray(a, dtype=dtype_np(dtype))


@register("smooth_l1")
def _smooth_l1(a, scalar=1.0, **_):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(a) < 1.0 / s2, 0.5 * s2 * a * a,
                     jnp.abs(a) - 0.5 / s2)


# ---------------------------------------------------------------- reductions

def _norm_red_axis(a, axis, exclude):
    """MXNet reduce semantics: axis may be int/tuple/None; exclude=True means
    reduce over all axes NOT listed (reference: broadcast_reduce_op.h)."""
    if exclude:
        listed = (axis,) if isinstance(axis, int) else tuple(axis or ())
        listed = tuple(ax % a.ndim for ax in listed)
        return tuple(i for i in range(a.ndim) if i not in listed)
    return axis


def _red(name, fn, aliases=(), differentiable=True):
    @register(name, aliases=aliases, differentiable=differentiable)
    def _op(a, axis=None, keepdims=False, exclude=False, _fn=fn, **kw):
        a = jnp.asarray(a)
        return _fn(a, axis=_norm_red_axis(a, axis, exclude), keepdims=keepdims)


_red("sum", jnp.sum, aliases=("sum_axis",))
_red("mean", jnp.mean)
_red("prod", jnp.prod)
_red("nansum", jnp.nansum)
_red("nanprod", jnp.nanprod)
_red("max", jnp.max, aliases=("max_axis",))
_red("min", jnp.min, aliases=("min_axis",))


@register("norm")
def _norm(a, axis=None, keepdims=False, ord=2, **_):
    if ord == 1:
        return jnp.sum(jnp.abs(a), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=keepdims))


@register("argmax", differentiable=False)
def _argmax(a, axis=None, keepdims=False, **_):
    out = jnp.argmax(a, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)


@register("argmin", differentiable=False)
def _argmin(a, axis=None, keepdims=False, **_):
    out = jnp.argmin(a, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)


@register("logsumexp")
def _logsumexp(a, axis=None, keepdims=False, **_):
    return jax.scipy.special.logsumexp(a, axis=axis, keepdims=keepdims)


# ---------------------------------------------------------------- shape ops

@register("reshape", aliases=("Reshape",))
def _reshape(a, shape=None, **_):
    return jnp.reshape(a, shape)


@register("transpose")
def _transpose(a, axes=None, **_):
    return jnp.transpose(a, axes if axes else None)


@register("swapaxes", aliases=("SwapAxis",))
def _swapaxes(a, dim1=0, dim2=0, **_):
    return jnp.swapaxes(a, dim1, dim2)


@register("flatten", aliases=("Flatten",))
def _flatten(a, **_):
    return jnp.reshape(a, (a.shape[0], -1))


@register("expand_dims")
def _expand_dims(a, axis=0, **_):
    return jnp.expand_dims(a, axis)


@register("squeeze")
def _squeeze(a, axis=None, **_):
    return jnp.squeeze(a, axis)


@register("broadcast_to")
def _broadcast_to(a, shape=None, **_):
    # MXNet semantics: 0 in target shape keeps the source dim
    tgt = tuple(s if s != 0 else a.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(a, tgt)


@register("broadcast_axis")
def _broadcast_axis(a, axis=(), size=(), **_):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(a.shape)
    for ax, s in zip(axes, sizes):
        tgt[ax] = s
    return jnp.broadcast_to(a, tuple(tgt))


@register("tile")
def _tile(a, reps=(), **_):
    return jnp.tile(a, reps)


@register("repeat")
def _repeat(a, repeats=1, axis=None, **_):
    return jnp.repeat(a, repeats, axis=axis)


@register("pad", aliases=("Pad",))
def _pad(a, pad_width=None, mode="constant", constant_value=0.0, **_):
    pw = list(pad_width)
    # reference uses flat 2N tuple (mshadow style); accept both
    if pw and not isinstance(pw[0], (tuple, list)):
        pw = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(a, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(a, pw, mode=jmode)


@register("concat", aliases=("Concat",))
def _concat(*args, dim=1, **_):
    return jnp.concatenate([jnp.asarray(a) for a in args], axis=dim)


@register("stack")
def _stack(*args, axis=0, **_):
    return jnp.stack([jnp.asarray(a) for a in args], axis=axis)


@register("split", aliases=("SliceChannel",), num_outputs=-1)
def _split(a, num_outputs=1, axis=1, squeeze_axis=False, **_):
    parts = jnp.split(a, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("slice_axis")
def _slice_axis(a, axis=0, begin=0, end=None, **_):
    n = a.shape[axis]
    if end is None:
        end = n
    if begin < 0:
        begin += n
    if end < 0:
        end += n
    idx = [slice(None)] * a.ndim
    idx[axis] = slice(begin, end)
    return a[tuple(idx)]


@register("slice", aliases=("crop",))
def _slice(a, begin=(), end=(), step=None, **_):
    idx = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(slice(b, e, s))
    return a[tuple(idx)]


@register("slice_like")
def _slice_like(a, b, axes=(), **_):
    idx = [slice(None)] * a.ndim
    axes = axes or range(a.ndim)
    for ax in axes:
        idx[ax] = slice(0, b.shape[ax])
    return a[tuple(idx)]


@register("_slice_index")
def _slice_index(a, key=None, **_):
    return a[key]


@register("reverse", aliases=("flip",))
def _reverse(a, axis=0, **_):
    return jnp.flip(a, axis)


@register("where")
def _where(cond, x, y, **_):
    return jnp.where(jnp.asarray(cond).astype(bool), x, y)


@register("diag")
def _diag(a, k=0, **_):
    return jnp.diag(a, k) if a.ndim <= 2 else jnp.diagonal(a, k, -2, -1)


@register("zeros_like")
def _zeros_like(a, **_):
    return jnp.zeros_like(a)


@register("ones_like")
def _ones_like(a, **_):
    return jnp.ones_like(a)


@register("full_like")
def _full_like(a, fill_value=0.0, **_):
    return jnp.full_like(a, fill_value)


@register("shape_array", differentiable=False)
def _shape_array(a, **_):
    return _as_index(a.shape)


@register("size_array", differentiable=False)
def _size_array(a, **_):
    return _as_index([a.size])


# ---------------------------------------------------------------- indexing

def _as_index(x):
    """Canonical index dtype: int32 by default (covers every single-core
    array), int64 when x64 is opted in so >2^31 offsets survive
    (docs/MIGRATION.md int64 posture)."""
    import jax as _jax
    dt = jnp.int64 if _jax.config.jax_enable_x64 else jnp.int32
    return jnp.asarray(x).astype(dt)


@register("take")
def _take(a, indices, axis=0, mode="clip", **_):
    idx = _as_index(indices)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("Embedding", aliases=("embedding",))
def _embedding(data, weight, input_dim=None, output_dim=None,
               sparse_grad=False, **_):
    ctx = getattr(_EMBED_ROUTE, "ctx", None)
    if ctx is not None and sparse_grad:
        # mesh-sharded deduplicated lookup (parallel/embedding.py): active
        # only inside an SPMDTrainer fused-step trace; returns None for
        # weights the context does not route (dense gather below)
        out = ctx.lookup(data, weight)
        if out is not None:
            return out
    idx = _as_index(data)
    return jnp.take(weight, idx, axis=0)


def _embedding_sparse_vjp(in_arrays, attrs, cotangents):
    """Row-sparse weight gradient for Embedding(sparse_grad=True): the
    cotangent rows keyed by the looked-up ids, no dense scatter image
    (reference: src/operator/tensor/indexing_op.cc EmbeddingOpBackward
    row_sparse output).  Returns (d_data, d_weight) for the two NDArray
    inputs; ids are integers so d_data is None."""
    from ..ndarray.sparse import RowSparseTangent
    data, weight = in_arrays[0], in_arrays[1]
    (ct,) = cotangents if len(cotangents) == 1 else (cotangents[0],)
    ids = _as_index(data).ravel()
    vals = jnp.reshape(ct, (ids.shape[0], -1))
    return (None, RowSparseTangent(ids, vals, weight.shape))


from .registry import get as _get_op  # noqa: E402
_get_op("Embedding").sparse_vjp = _embedding_sparse_vjp


@register("one_hot", differentiable=False)
def _one_hot(a, depth=1, on_value=1.0, off_value=0.0, dtype="float32", **_):
    from ..base import dtype_np
    oh = jax.nn.one_hot(_as_index(a), depth)
    return (oh * (on_value - off_value) + off_value).astype(dtype_np(dtype))


@register("pick")
def _pick(data, index, axis=-1, keepdims=False, mode="clip", **_):
    idx = jnp.clip(_as_index(index), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis)
    return out


@register("gather_nd")
def _gather_nd(data, indices, **_):
    idx = _as_index(indices)
    # indices shape (M, ...) indexes the first M dims of data
    return data[tuple(idx[i] for i in range(idx.shape[0]))]


@register("scatter_nd")
def _scatter_nd(data, indices, shape=None, **_):
    idx = _as_index(indices)
    out = jnp.zeros(shape, data.dtype)
    return out.at[tuple(idx[i] for i in range(idx.shape[0]))].add(data)


@register("take_along_axis")
def _take_along_axis(a, indices, axis=0, **_):
    return jnp.take_along_axis(a, _as_index(indices), axis)


@register("boolean_mask", differentiable=False)
def _boolean_mask(data, index, axis=0, **_):
    # dynamic-shape op: eager-only (reference src/operator/contrib/boolean_mask.cc)
    import numpy as onp
    mask = onp.asarray(index).astype(bool)
    return jnp.compress(mask, data, axis=axis)


# ---------------------------------------------------------------- ordering

@register("sort")
def _sort(a, axis=-1, is_ascend=True, **_):
    out = jnp.sort(a, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", differentiable=False)
def _argsort(a, axis=-1, is_ascend=True, dtype="float32", **_):
    out = jnp.argsort(a, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.float32)


@register("topk", differentiable=False)
def _topk(a, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32", **_):
    neg = not is_ascend
    mv = jnp.moveaxis(a, axis, -1)
    vals, idxs = lax.top_k(mv if neg else -mv, k)
    if not neg:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis).astype(jnp.float32)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idxs
    return idxs


@register("shuffle", differentiable=False)
def _shuffle(a, **_):
    from ..random import next_key
    return jax.random.permutation(next_key(), a, axis=0)


# ---------------------------------------------------------------- linalg

@register("dot")
def _dot(a, b, transpose_a=False, transpose_b=False, **_):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    return jnp.dot(a, b)


@register("batch_dot")
def _batch_dot(a, b, transpose_a=False, transpose_b=False, **_):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("batch_dot_auto")
def _batch_dot_auto(a, b, **_):
    return jnp.matmul(a, b)


@register("linalg_gemm2")
def _linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0, **_):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def _linalg_potrf(a, **_):
    return jnp.linalg.cholesky(a)


@register("linalg_syrk")
def _linalg_syrk(a, transpose=False, alpha=1.0, **_):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("linalg_trsm")
def _linalg_trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0, **_):
    if transpose:
        a = jnp.swapaxes(a, -1, -2)
        lower = not lower
    if rightside:
        xt = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(b, -1, -2), lower=not lower)
        return alpha * jnp.swapaxes(xt, -1, -2)
    return alpha * jax.scipy.linalg.solve_triangular(a, b, lower=lower)


@register("L2Normalization")
def _l2norm(a, eps=1e-10, mode="instance", **_):
    if mode == "instance":
        axes = tuple(range(1, a.ndim))
    elif mode == "channel":
        axes = (1,)
    else:
        axes = tuple(range(1, a.ndim))
    denom = jnp.sqrt(jnp.sum(jnp.square(a), axis=axes, keepdims=True) + eps)
    return a / denom


# ---------------------------------------------------------------- sequences

@register("SequenceMask", aliases=("sequence_mask",))
def _sequence_mask(data, sequence_length=None, use_sequence_length=False,
                   value=0.0, axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        return jnp.asarray(data)
    length = data.shape[axis]
    steps = jnp.arange(length)
    shape = [1] * data.ndim
    shape[axis] = length
    steps = steps.reshape(shape)
    seq = jnp.asarray(sequence_length)
    bshape = [1] * data.ndim
    bshape[1 - axis] = seq.shape[0]
    mask = steps < seq.reshape(bshape)
    return jnp.where(mask, data, value)


@register("SequenceLast", aliases=("sequence_last",))
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return jnp.asarray(data)[tuple(idx)]
    seq = jnp.asarray(sequence_length).astype(jnp.int32) - 1
    mv = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return jnp.take_along_axis(
        mv, seq.reshape((1, -1) + (1,) * (mv.ndim - 2)), axis=0)[0]


@register("SequenceReverse", aliases=("sequence_reverse",))
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                      axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis)
    T = data.shape[axis]
    mv = jnp.moveaxis(data, axis, 0)
    seq = jnp.asarray(sequence_length).astype(jnp.int32)
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < seq[None, :], seq[None, :] - 1 - t, t)  # (T,B)
    out = jnp.take_along_axis(
        mv, src.reshape(src.shape + (1,) * (mv.ndim - 2)), axis=0)
    return jnp.moveaxis(out, 0, axis)


# ------------------------------------------------- round-3 coverage widening
# Reference: src/operator/tensor/matrix_op.cc (depth/space reshuffles,
# cumulative ops), broadcast_reduce_op_value.cc, init_op.cc (creation ops),
# ravel.cc, loss_binary_op.cc.

@register("cumsum", aliases=("_np_cumsum",))
def _cumsum(a, axis=None, dtype=None, **_):
    return jnp.cumsum(jnp.asarray(a), axis=axis, dtype=dtype)


@register("cumprod")
def _cumprod(a, axis=None, dtype=None, **_):
    return jnp.cumprod(jnp.asarray(a), axis=axis, dtype=dtype)


@register("depth_to_space")
def _depth_to_space(data, block_size=1, **_):
    """(N, C*b*b, H, W) -> (N, C, H*b, W*b) (reference matrix_op.cc)."""
    x = jnp.asarray(data)
    n, c, h, w = x.shape
    b = block_size
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def _space_to_depth(data, block_size=1, **_):
    x = jnp.asarray(data)
    n, c, h, w = x.shape
    b = block_size
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(n, c * b * b, h // b, w // b)


@register("batch_take")
def _batch_take(a, indices, **_):
    """out[i] = a[i, indices[i]] (reference indexing_op.cc batch_take)."""
    x = jnp.asarray(a)
    idx = _as_index(indices)
    return jnp.take_along_axis(x, idx.reshape(-1, 1), axis=1)[:, 0]


@register("broadcast_like")
def _broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None, **_):
    x = jnp.asarray(lhs)
    like = jnp.asarray(rhs)
    if lhs_axes is None:
        return jnp.broadcast_to(x, like.shape)
    shape = list(x.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        shape[la] = like.shape[ra]
    return jnp.broadcast_to(x, tuple(shape))


@register("reshape_like")
def _reshape_like(lhs, rhs, **_):
    return jnp.asarray(lhs).reshape(jnp.asarray(rhs).shape)


@register("digamma")
def _digamma(a, **_):
    return jax.scipy.special.digamma(jnp.asarray(a))


@register("moments", num_outputs=2)
def _moments(data, axes=None, keepdims=False, **_):
    """(mean, variance) over `axes` (reference nn/moments.cc)."""
    x = jnp.asarray(data)
    ax = tuple(axes) if axes is not None else None
    return (jnp.mean(x, axis=ax, keepdims=keepdims),
            jnp.var(x, axis=ax, keepdims=keepdims))


@register("argmax_channel", differentiable=False)
def _argmax_channel(data, **_):
    return jnp.argmax(jnp.asarray(data), axis=1).astype(jnp.float32)


@register("unravel_index", differentiable=False)
def _unravel_index(data, shape=None, **_):
    idx = _as_index(data)
    coords = jnp.unravel_index(idx, tuple(shape))
    return jnp.stack(coords, axis=0)


@register("ravel_multi_index", differentiable=False)
def _ravel_multi_index(data, shape=None, **_):
    coords = _as_index(data)
    mult = []
    acc = 1
    for s in reversed(tuple(shape)):
        mult.append(acc)
        acc *= s
    mult = _as_index(list(reversed(mult)))
    return jnp.sum(coords * mult.reshape(-1, *([1] * (coords.ndim - 1))),
                   axis=0).astype(jnp.float32)


# creation ops (reference: src/operator/tensor/init_op.cc registry names)

@register("_zeros", differentiable=False, aliases=("zeros",))
def _zeros_op(shape=None, dtype="float32", **_):
    return jnp.zeros(shape if shape is not None else (), jnp.dtype(dtype))


@register("_ones", differentiable=False, aliases=("ones",))
def _ones_op(shape=None, dtype="float32", **_):
    return jnp.ones(shape if shape is not None else (), jnp.dtype(dtype))


@register("_full", differentiable=False, aliases=("full",))
def _full_op(shape=None, value=0.0, dtype="float32", **_):
    return jnp.full(shape if shape is not None else (), value,
                    jnp.dtype(dtype))


@register("_arange", differentiable=False, aliases=("arange",))
def _arange_op(start=0, stop=None, step=1.0, repeat=1, dtype="float32", **_):
    out = jnp.arange(start, stop, step, jnp.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", differentiable=False, aliases=("linspace",))
def _linspace_op(start=0, stop=None, num=50, endpoint=True, dtype="float32",
                 **_):
    return jnp.linspace(start, stop, num, endpoint=endpoint,
                        dtype=jnp.dtype(dtype))


@register("_eye", differentiable=False, aliases=("eye",))
def _eye_op(N=0, M=0, k=0, dtype="float32", **_):
    return jnp.eye(int(N), int(M) if M else None, k=int(k),
                   dtype=jnp.dtype(dtype))


@register("_copy_to_device")
def _copy_to_device(a, _device=None, **_):
    """Differentiable cross-device copy (reference: the CopyTo op
    AssignContext inserts between ctx groups): jax.device_put is a
    primitive whose transpose returns the cotangent to the source device,
    so NDArray.copyto(ctx) stays on the tape during record()."""
    return jax.device_put(jnp.asarray(a), _device)
