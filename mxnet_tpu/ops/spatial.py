"""Spatial sampling and warping ops.

Reference: src/operator/bilinear_sampler.cc, grid_generator.cc,
spatial_transformer.cc, contrib/deformable_convolution.cc,
contrib/roi_align.cc, contrib/bilinear_resize.cc, correlation.cc.

TPU-native: all of these reduce to ONE shared differentiable gather —
``_sample_bilinear`` — expressed with static-shape advanced indexing that XLA
lowers to vectorized dynamic-gathers; gradients (including w.r.t. the
sampling coordinates) come from jax's autodiff of the interpolation weights
instead of the reference's hand-written backward kernels
(bilinear_sampler-inl.h BilinearSamplerBackward etc.).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _sample_bilinear(data, y, x):
    """Sample NCHW `data` at float pixel coords y/x of shape (N, *S);
    returns (N, C, *S).  Points outside the image contribute zero (the
    reference's zero-padding boundary)."""
    N, C, H, W = data.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = (y - y0)[:, None]
    wx = (x - x0)[:, None]

    def corner(yy, xx):
        ok = ((yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1))
        yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        vals = jax.vmap(lambda img, a, b: img[:, a, b])(data, yc, xc)
        return vals * ok[:, None].astype(data.dtype)

    v00 = corner(y0, x0)
    v01 = corner(y0, x0 + 1)
    v10 = corner(y0 + 1, x0)
    v11 = corner(y0 + 1, x0 + 1)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def _denorm(coord, size):
    """[-1, 1] normalized -> pixel coordinate."""
    return (coord + 1.0) * (size - 1) / 2.0


@register("BilinearSampler", aliases=("bilinear_sampler",))
def _bilinear_sampler(data, grid, cudnn_off=None, **_):
    """data (N,C,H,W), grid (N,2,Ho,Wo) normalized (x, y) in [-1,1]
    (reference bilinear_sampler.cc)."""
    d = jnp.asarray(data)
    g = jnp.asarray(grid)
    x = _denorm(g[:, 0], d.shape[3])
    y = _denorm(g[:, 1], d.shape[2])
    return _sample_bilinear(d, y, x)


@register("GridGenerator", aliases=("grid_generator",))
def _grid_generator(data, transform_type="affine", target_shape=(0, 0), **_):
    """affine: (N,6) params -> sampling grid (N,2,H,W); warp: (N,2,H,W)
    pixel flow -> normalized grid (reference grid_generator.cc)."""
    d = jnp.asarray(data)
    if transform_type == "affine":
        H, W = int(target_shape[0]), int(target_shape[1])
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        src = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        theta = d.reshape(-1, 2, 3)
        out = jnp.einsum("nij,jk->nik", theta, src)              # (N,2,HW)
        return out.reshape(-1, 2, H, W)
    if transform_type == "warp":
        N, _, H, W = d.shape
        base_y, base_x = jnp.meshgrid(jnp.arange(H, dtype=d.dtype),
                                      jnp.arange(W, dtype=d.dtype),
                                      indexing="ij")
        px = base_x + d[:, 0]
        py = base_y + d[:, 1]
        nx = 2.0 * px / (W - 1) - 1.0
        ny = 2.0 * py / (H - 1) - 1.0
        return jnp.stack([nx, ny], axis=1)
    raise ValueError("unknown transform_type %r" % transform_type)


@register("SpatialTransformer", aliases=("spatial_transformer",))
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine", sampler_type="bilinear",
                         cudnn_off=None, **_):
    """Affine grid from loc (N,6) + bilinear sampling
    (reference spatial_transformer.cc)."""
    grid = _grid_generator(loc, "affine", target_shape)
    return _bilinear_sampler(data, grid)


@register("_contrib_BilinearResize2D", aliases=("BilinearResize2D",
                                                "bilinear_resize_2d"))
def _bilinear_resize(data, like=None, height=0, width=0, scale_height=None,
                     scale_width=None, mode="size", **_):
    """Bilinear resize with align-corners coordinate mapping
    (reference contrib/bilinear_resize.cc)."""
    d = jnp.asarray(data)
    N, C, H, W = d.shape
    if like is not None and mode == "like":
        height, width = jnp.asarray(like).shape[2:4]
    if scale_height is not None:
        height = int(H * scale_height)
    if scale_width is not None:
        width = int(W * scale_width)
    height, width = int(height), int(width)
    ys = jnp.linspace(0.0, H - 1.0, height)
    xs = jnp.linspace(0.0, W - 1.0, width)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    y = jnp.broadcast_to(gy, (N,) + gy.shape)
    x = jnp.broadcast_to(gx, (N,) + gx.shape)
    return _sample_bilinear(d, y, x)


@register("_contrib_ROIAlign", aliases=("ROIAlign", "roi_align"))
def _roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
               sample_ratio=-1, position_sensitive=False, aligned=False, **_):
    """ROI Align (reference contrib/roi_align.cc): average of bilinear
    samples on a regular sub-grid inside each pooled cell."""
    d = jnp.asarray(data)
    r = jnp.asarray(rois)
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    ns = sample_ratio if sample_ratio > 0 else 2
    off = 0.5 if aligned else 0.0
    batch_idx = r[:, 0].astype(jnp.int32)
    x1 = r[:, 1] * spatial_scale - off
    y1 = r[:, 2] * spatial_scale - off
    x2 = r[:, 3] * spatial_scale - off
    y2 = r[:, 4] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
    # sub-sample grid: (ph*ns, pw*ns) points per roi
    sy = (jnp.arange(ph * ns) + 0.5) / ns    # in pooled-cell units
    sx = (jnp.arange(pw * ns) + 0.5) / ns
    gy, gx = jnp.meshgrid(sy, sx, indexing="ij")
    y = y1[:, None, None] + gy[None] * (rh / ph)[:, None, None]
    x = x1[:, None, None] + gx[None] * (rw / pw)[:, None, None]
    per_roi = d[batch_idx]                   # (R, C, H, W)
    samp = _sample_bilinear(per_roi, y, x)   # (R, C, ph*ns, pw*ns)
    R, C = samp.shape[:2]
    samp = samp.reshape(R, C, ph, ns, pw, ns)
    return samp.mean(axis=(3, 5))


@register("_contrib_DeformableConvolution", aliases=("DeformableConvolution",
                                                     "deformable_convolution"))
def _deformable_convolution(data, offset, weight, bias=None, kernel=None,
                            stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                            num_filter=None, num_group=1,
                            num_deformable_group=1, no_bias=False, **_):
    """Deformable convolution v1 (reference
    contrib/deformable_convolution.cc): per-output-location learned offsets
    shift each kernel tap's sampling point; taps are gathered with the
    shared bilinear sampler and contracted with the weights in one einsum
    (the deformable_im2col + GEMM of the reference, fused)."""
    d = jnp.asarray(data)
    w = jnp.asarray(weight)
    off = jnp.asarray(offset)
    N, C, H, W = d.shape
    O, Cg, kh, kw = w.shape
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = num_deformable_group
    base_y = jnp.arange(Ho) * sh - ph
    base_x = jnp.arange(Wo) * sw - pw
    gy, gx = jnp.meshgrid(base_y.astype(d.dtype), base_x.astype(d.dtype),
                          indexing="ij")
    cols = []
    cpg = C // dg
    for g in range(dg):
        dslice = d[:, g * cpg:(g + 1) * cpg]
        taps = []
        for i in range(kh):
            for j in range(kw):
                k = i * kw + j
                oy = off[:, 2 * (g * kh * kw + k)]
                ox = off[:, 2 * (g * kh * kw + k) + 1]
                y = gy[None] + i * dh + oy
                x = gx[None] + j * dw + ox
                taps.append(_sample_bilinear(dslice, y, x))
        # (N, cpg, kh*kw, Ho, Wo)
        cols.append(jnp.stack(taps, axis=2))
    col = jnp.concatenate(cols, axis=1)      # (N, C, K, Ho, Wo)
    col = col.reshape(N, C * kh * kw, Ho, Wo)
    wg = w.reshape(num_group, O // num_group, Cg * kh * kw)
    colg = col.reshape(N, num_group, (C // num_group) * kh * kw, Ho, Wo)
    out = jnp.einsum("gok,ngkhw->ngohw", wg, colg)
    out = out.reshape(N, O, Ho, Wo)
    if bias is not None and not no_bias:
        out = out + jnp.asarray(bias).reshape(1, -1, 1, 1)
    return out


@register("Correlation", aliases=("correlation",))
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True, **_):
    """Correlation layer (reference correlation.cc, FlowNet-style):
    out[:, k, y, x] = mean_c data1[:, c, y, x] · data2[:, c, y+dy, x+dx]
    over the displacement grid k=(dy, dx)."""
    a = jnp.asarray(data1)
    b = jnp.asarray(data2)
    if kernel_size != 1:
        raise NotImplementedError("Correlation: kernel_size>1 not supported")
    ps = pad_size
    ap = jnp.pad(a, ((0, 0), (0, 0), (ps, ps), (ps, ps)))
    bp = jnp.pad(b, ((0, 0), (0, 0), (ps, ps), (ps, ps)))
    N, C, Hp, Wp = ap.shape
    disp = max_displacement
    steps = 2 * (disp // stride2) + 1
    Ho = (Hp - 2 * disp) // stride1
    Wo = (Wp - 2 * disp) // stride1
    ys = disp + jnp.arange(Ho) * stride1
    xs = disp + jnp.arange(Wo) * stride1
    out = []
    for dy in range(-disp, disp + 1, stride2):
        for dx in range(-disp, disp + 1, stride2):
            a_c = ap[:, :, disp:disp + Ho * stride1:stride1,
                     disp:disp + Wo * stride1:stride1]
            b_c = bp[:, :, disp + dy:disp + dy + Ho * stride1:stride1,
                     disp + dx:disp + dx + Wo * stride1:stride1]
            if is_multiply:
                out.append((a_c * b_c).mean(axis=1))
            else:
                out.append(jnp.abs(a_c - b_c).mean(axis=1))
    del ys, xs
    return jnp.stack(out, axis=1).reshape(N, steps * steps, Ho, Wo)
