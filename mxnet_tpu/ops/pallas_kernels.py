"""Built-in Pallas kernels — the custom-kernel escape hatch in use.

Reference role: the hand-written CUDA kernels MXNet reaches for when
library kernels fall short (RTC, src/common/rtc.cc; fused contrib kernels).
On TPU the escape hatch is Mosaic via Pallas (pallas_guide.md); these
kernels double as the worked examples for ``mx.rtc``.

Each kernel follows the VMEM-block pattern: the grid walks row blocks, a
block lives in VMEM, and the body is VPU elementwise math with on-chip
reductions — no HBM roundtrips between the fused stages.  On CPU they run
through the Pallas interpreter (same numerics), so tests validate the
kernels without a TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["pallas_row_softmax", "pallas_scale_bias_relu",
           "pallas_flash_attention"]


def _row_softmax_kernel(x_ref, o_ref):
    """Numerically-stable softmax over the last axis of one row block.
    max/sum reductions stay in VMEM — one HBM read, one HBM write."""
    x = x_ref[:]
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[:] = e / jnp.sum(e, axis=-1, keepdims=True)


def _scale_bias_relu_kernel(x_ref, scale_ref, bias_ref, o_ref):
    """Fused y = relu(x * scale + bias) — the classic post-GEMM epilogue."""
    o_ref[:] = jnp.maximum(x_ref[:] * scale_ref[:] + bias_ref[:], 0.0)


def _row_block(n_rows, row_bytes, budget=2 << 20):
    """Largest divisor of n_rows whose block stays under the VMEM budget
    (a block must tile the array exactly).  O(sqrt(n)) divisor walk — this
    runs on the host per eager call, so no linear scans."""
    cap = max(1, budget // max(row_bytes, 1))
    best = 1
    i = 1
    while i * i <= n_rows:
        if n_rows % i == 0:
            if i <= cap and i > best:
                best = i
            j = n_rows // i
            if j <= cap and j > best:
                best = j
        i += 1
    return best


@register("pallas_softmax", differentiable=False)
def pallas_row_softmax(data, **_):
    """Row softmax via the Pallas kernel (mx.nd.pallas_softmax).

    The grid walks row blocks sized to fit VMEM, so arbitrarily tall
    logits tensors stream through the kernel; one row must fit on chip
    (true for any real vocab at fp32: 32k cols = 128KB)."""
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    x = jnp.asarray(data)
    flat = x.reshape(-1, x.shape[-1])
    n, d = flat.shape
    rows = _row_block(n, d * flat.dtype.itemsize)
    out = pl.pallas_call(
        _row_softmax_kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        grid=(n // rows,),
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        interpret=interpret_mode())(flat)
    return out.reshape(x.shape)


def _flash_attention_kernel(scale, causal, block_q, q_ref, k_ref, v_ref,
                            o_ref):
    """One q block vs the full K/V of its (batch, head) slice.

    The score matrix [block_q, S] lives only in VMEM — it is never
    materialized in HBM, which is the whole point of flash attention: HBM
    traffic is O(S*D) instead of O(S^2).  Softmax accumulates in f32 on
    chip; the MXU does both matmuls.
    """
    from jax.experimental import pallas as pl
    q = q_ref[0].astype(jnp.float32)                # [bq, D]
    k = k_ref[0].astype(jnp.float32)                # [S, D]
    v = v_ref[0]                                    # [S, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        i = pl.program_id(1)
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(k_pos <= q_pos, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    acc = jax.lax.dot_general(e.astype(v.dtype), v,
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[0] = (acc / jnp.sum(e, axis=-1, keepdims=True)).astype(
        o_ref.dtype)


@register("pallas_flash_attention", differentiable=False)
def pallas_flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                           **_):
    """Flash attention via Pallas (mx.nd.pallas_flash_attention).

    q/k/v: [B, H, S, D].  The grid walks (batch*heads, q blocks); each
    step holds one q block plus its head's full K/V in VMEM (S*D per
    operand — S=8k at D=128 bf16 is 2MB, comfortably on chip), so the
    S x S score matrix never touches HBM.  Sequences larger than VMEM
    shard S over the 'sp' mesh axis first (parallel.ring_attention) and
    run this kernel per shard.  Forward-only by design — training uses
    the XLA attention whose backward XLA fuses well; this is the
    inference escape hatch (reference analog: hand-written fused CUDA
    attention via RTC, src/common/rtc.cc).
    """
    import math
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    import functools

    q = jnp.asarray(q)
    k = jnp.asarray(k)
    v = jnp.asarray(v)
    B, H, S, D = q.shape
    Skv = k.shape[2]
    if causal and Skv != S:
        raise ValueError("causal flash attention needs matching q/kv "
                         "lengths, got Sq=%d Skv=%d" % (S, Skv))
    if v.shape != k.shape:
        raise ValueError("k and v shapes differ: %s vs %s"
                         % (k.shape, v.shape))
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    # largest divisor of S <= block_q, so an awkward block_q degrades to
    # the best legal tiling instead of cliff-diving to 1-row blocks
    bq = _row_block(S, 1, budget=min(block_q, S))
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, Skv, D)
    vf = v.reshape(B * H, Skv, D)
    kernel = functools.partial(_flash_attention_kernel, scale, bool(causal),
                               bq)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(B * H, S // bq),
        in_specs=[pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0)),
                  pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0))],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        interpret=interpret_mode())(qf, kf, vf)
    return out.reshape(B, H, S, D)


@register("pallas_scale_bias_relu", differentiable=False)
def pallas_scale_bias_relu(data, scale, bias, **_):
    """Fused per-feature epilogue y = relu(x*scale + bias)
    (mx.nd.pallas_scale_bias_relu); scale/bias broadcast over the last
    axis INSIDE the kernel, so HBM reads stay B*D + 2*D."""
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    x = jnp.asarray(data)
    d = x.shape[-1]
    flat = x.reshape(-1, d)
    n = flat.shape[0]
    s = jnp.asarray(scale).reshape(1, d).astype(x.dtype)
    b = jnp.asarray(bias).reshape(1, d).astype(x.dtype)
    rows = _row_block(n, d * flat.dtype.itemsize)
    out = pl.pallas_call(
        _scale_bias_relu_kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        grid=(n // rows,),
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0)),
                  pl.BlockSpec((1, d), lambda i: (0, 0)),
                  pl.BlockSpec((1, d), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        interpret=interpret_mode())(flat, s, b)
    return out.reshape(x.shape)
