"""Built-in Pallas kernels — the custom-kernel escape hatch in use.

Reference role: the hand-written CUDA kernels MXNet reaches for when
library kernels fall short (RTC, src/common/rtc.cc; fused contrib kernels).
On TPU the escape hatch is Mosaic via Pallas (pallas_guide.md); these
kernels double as the worked examples for ``mx.rtc``.

Each kernel follows the VMEM-block pattern: the grid walks row blocks, a
block lives in VMEM, and the body is VPU elementwise math with on-chip
reductions — no HBM roundtrips between the fused stages.  On CPU they run
through the Pallas interpreter (same numerics), so tests validate the
kernels without a TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["pallas_row_softmax", "pallas_scale_bias_relu"]


def _row_softmax_kernel(x_ref, o_ref):
    """Numerically-stable softmax over the last axis of one row block.
    max/sum reductions stay in VMEM — one HBM read, one HBM write."""
    x = x_ref[:]
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[:] = e / jnp.sum(e, axis=-1, keepdims=True)


def _scale_bias_relu_kernel(x_ref, scale_ref, bias_ref, o_ref):
    """Fused y = relu(x * scale + bias) — the classic post-GEMM epilogue."""
    o_ref[:] = jnp.maximum(x_ref[:] * scale_ref[:] + bias_ref[:], 0.0)


def _row_block(n_rows, row_bytes, budget=2 << 20):
    """Largest divisor of n_rows whose block stays under the VMEM budget
    (a block must tile the array exactly).  O(sqrt(n)) divisor walk — this
    runs on the host per eager call, so no linear scans."""
    cap = max(1, budget // max(row_bytes, 1))
    best = 1
    i = 1
    while i * i <= n_rows:
        if n_rows % i == 0:
            if i <= cap and i > best:
                best = i
            j = n_rows // i
            if j <= cap and j > best:
                best = j
        i += 1
    return best


@register("pallas_softmax", differentiable=False)
def pallas_row_softmax(data, **_):
    """Row softmax via the Pallas kernel (mx.nd.pallas_softmax).

    The grid walks row blocks sized to fit VMEM, so arbitrarily tall
    logits tensors stream through the kernel; one row must fit on chip
    (true for any real vocab at fp32: 32k cols = 128KB)."""
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    x = jnp.asarray(data)
    flat = x.reshape(-1, x.shape[-1])
    n, d = flat.shape
    rows = _row_block(n, d * flat.dtype.itemsize)
    out = pl.pallas_call(
        _row_softmax_kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        grid=(n // rows,),
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        interpret=interpret_mode())(flat)
    return out.reshape(x.shape)


@register("pallas_scale_bias_relu", differentiable=False)
def pallas_scale_bias_relu(data, scale, bias, **_):
    """Fused per-feature epilogue y = relu(x*scale + bias)
    (mx.nd.pallas_scale_bias_relu); scale/bias broadcast over the last
    axis INSIDE the kernel, so HBM reads stay B*D + 2*D."""
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    x = jnp.asarray(data)
    d = x.shape[-1]
    flat = x.reshape(-1, d)
    n = flat.shape[0]
    s = jnp.asarray(scale).reshape(1, d).astype(x.dtype)
    b = jnp.asarray(bias).reshape(1, d).astype(x.dtype)
    rows = _row_block(n, d * flat.dtype.itemsize)
    out = pl.pallas_call(
        _scale_bias_relu_kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        grid=(n // rows,),
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0)),
                  pl.BlockSpec((1, d), lambda i: (0, 0)),
                  pl.BlockSpec((1, d), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        interpret=interpret_mode())(flat, s, b)
    return out.reshape(x.shape)
