"""Built-in Pallas kernels — the custom-kernel escape hatch in use.

Reference role: the hand-written CUDA kernels MXNet reaches for when
library kernels fall short (RTC, src/common/rtc.cc; fused contrib kernels).
On TPU the escape hatch is Mosaic via Pallas (pallas_guide.md); these
kernels double as the worked examples for ``mx.rtc``.

Each kernel follows the VMEM-block pattern: the grid walks row blocks, a
block lives in VMEM, and the body is VPU elementwise math with on-chip
reductions — no HBM roundtrips between the fused stages.  On CPU they run
through the Pallas interpreter (same numerics), so tests validate the
kernels without a TPU.

Kernel tier (docs/PERF_NOTES.md "Kernel tier"): flash attention is a
full training kernel — the tiled online-softmax forward saves per-row
logsumexp residuals and a Pallas backward (recompute-style, two kernels:
dq over q blocks, dk/dv over kv blocks) rides ``jax.custom_vjp``.  The
fused optimizer epilogues (``fused_sgd_step``/``fused_adam_step``) fold
the whole elementwise update chain plus the low-precision cast into ONE
kernel so bf16 params never round-trip through a separate f32 master
copy program.  Routing and fallback live in ``mx.kernels``; the raw
kernels here stay policy-free.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["pallas_row_softmax", "pallas_scale_bias_relu",
           "pallas_flash_attention", "flash_attention",
           "pallas_paged_attention",
           "fused_sgd_step", "fused_adam_step"]

_NEG = -1e30


def _row_block(n_rows, row_bytes, budget=None):
    """Largest divisor of n_rows whose block stays under the VMEM budget
    (a block must tile the array exactly).  O(sqrt(n)) divisor walk — this
    runs on the host per eager call, so no linear scans.  ``budget``
    defaults to the validated ``kernels.vmem_budget`` knob
    (MXNET_TPU_KERNELS_VMEM_BUDGET)."""
    if budget is None:
        from .. import config as _config
        budget = _config.get("kernels.vmem_budget")
    cap = max(1, budget // max(row_bytes, 1))
    best = 1
    i = 1
    while i * i <= n_rows:
        if n_rows % i == 0:
            if i <= cap and i > best:
                best = i
            j = n_rows // i
            if j <= cap and j > best:
                best = j
        i += 1
    return best


# ------------------------------------------------------------ row softmax
def _row_softmax_kernel(x_ref, o_ref, m_ref, l_ref):
    """Numerically-stable softmax over the last axis of one row block.
    max/sum reductions stay in VMEM — one HBM read, one HBM write for the
    rows plus two [rows, 1] residual columns (the saved row max/sum the
    custom-vjp backward reuses)."""
    x = x_ref[:]
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    o_ref[:] = e / s
    m_ref[:] = m
    l_ref[:] = s


def _row_softmax_bwd_kernel(x_ref, m_ref, l_ref, dy_ref, dx_ref):
    """softmax VJP from the saved row max/sum: y rebuilds as
    exp(x - m)/l on chip (no second max/sum pass), then
    dx = y * (dy - sum(dy * y))."""
    y = jnp.exp(x_ref[:] - m_ref[:]) / l_ref[:]
    dy = dy_ref[:]
    dx_ref[:] = y * (dy - jnp.sum(dy * y, axis=-1, keepdims=True))


def _softmax_fwd_call(flat):
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    n, d = flat.shape
    rows = _row_block(n, d * flat.dtype.itemsize)
    return pl.pallas_call(
        _row_softmax_kernel,
        out_shape=[jax.ShapeDtypeStruct(flat.shape, flat.dtype),
                   jax.ShapeDtypeStruct((n, 1), flat.dtype),
                   jax.ShapeDtypeStruct((n, 1), flat.dtype)],
        grid=(n // rows,),
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0)),
                   pl.BlockSpec((rows, 1), lambda i: (i, 0)),
                   pl.BlockSpec((rows, 1), lambda i: (i, 0))],
        interpret=interpret_mode())(flat)


def _softmax_bwd_call(x, m, l, dy):
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    n, d = x.shape
    rows = _row_block(n, d * x.dtype.itemsize)
    return pl.pallas_call(
        _row_softmax_bwd_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(n // rows,),
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0)),
                  pl.BlockSpec((rows, 1), lambda i: (i, 0)),
                  pl.BlockSpec((rows, 1), lambda i: (i, 0)),
                  pl.BlockSpec((rows, d), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        interpret=interpret_mode())(x, m, l, dy)


@jax.custom_vjp
def _row_softmax(flat):
    return _softmax_fwd_call(flat)[0]


def _row_softmax_fwd(flat):
    y, m, l = _softmax_fwd_call(flat)
    return y, (flat, m, l)


def _row_softmax_bwd(res, dy):
    x, m, l = res
    return (_softmax_bwd_call(x, m, l, dy),)


_row_softmax.defvjp(_row_softmax_fwd, _row_softmax_bwd)


@register("pallas_softmax")
def pallas_row_softmax(data, **_):
    """Row softmax via the Pallas kernel (mx.nd.pallas_softmax).

    The grid walks row blocks sized to fit VMEM, so arbitrarily tall
    logits tensors stream through the kernel; one row must fit on chip
    (true for any real vocab at fp32: 32k cols = 128KB).  Differentiable:
    the forward saves the per-row max and sum and the custom-vjp backward
    kernel reuses them (no recomputed reductions)."""
    x = jnp.asarray(data)
    flat = x.reshape(-1, x.shape[-1])
    return _row_softmax(flat).reshape(x.shape)


# ------------------------------------------------------- flash attention
def _flash_fwd_kernel(scale, causal, block_q, q_ref, k_ref, v_ref,
                      o_ref, lse_ref):
    """One q block vs the full K/V of its (batch, head) slice.

    The score matrix [block_q, S] lives only in VMEM — it is never
    materialized in HBM, which is the whole point of flash attention: HBM
    traffic is O(S*D) instead of O(S^2).  Softmax accumulates in f32 on
    chip; the MXU does both matmuls.  The per-row logsumexp lands in a
    [block_q] residual strip so the backward can rebuild the
    probabilities without a second max/sum pass.
    """
    from jax.experimental import pallas as pl
    q = q_ref[0].astype(jnp.float32)                # [bq, D]
    k = k_ref[0].astype(jnp.float32)                # [S, D]
    v = v_ref[0]                                    # [S, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        i = pl.program_id(1)
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=-1, keepdims=True)
    acc = jax.lax.dot_general(e.astype(v.dtype), v,
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m[:, 0] + jnp.log(l[:, 0])


def _flash_bwd_dq_kernel(scale, causal, block_q, q_ref, k_ref, v_ref,
                         do_ref, lse_ref, delta_ref, dq_ref):
    """dq for one q block: recompute the probabilities from the saved
    logsumexp (p = exp(s - lse)), then
    ds = p * (dO @ V^T - delta) * scale and dq = ds @ K — the score and
    ds matrices stay in VMEM."""
    from jax.experimental import pallas as pl
    q = q_ref[0].astype(jnp.float32)                # [bq, D]
    k = k_ref[0].astype(jnp.float32)                # [S, D]
    v = v_ref[0].astype(jnp.float32)                # [S, D]
    do = do_ref[0].astype(jnp.float32)              # [bq, D]
    lse = lse_ref[0]                                # [bq]
    delta = delta_ref[0]                            # [bq]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        i = pl.program_id(1)
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG)
    p = jnp.exp(s - lse[:, None])                   # [bq, S]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * scale
    dq_ref[0] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(scale, causal, block_k, q_ref, k_ref, v_ref,
                          do_ref, lse_ref, delta_ref, dk_ref, dv_ref):
    """dk/dv for one kv block against the full Q/dO of its (batch, head):
    the transposed score strip [block_k, Sq] rebuilds from the saved
    logsumexp, dv = P^T @ dO and dk = dS^T @ Q accumulate in f32 on the
    MXU."""
    from jax.experimental import pallas as pl
    q = q_ref[0].astype(jnp.float32)                # [Sq, D]
    k = k_ref[0].astype(jnp.float32)                # [bk, D]
    v = v_ref[0].astype(jnp.float32)                # [bk, D]
    do = do_ref[0].astype(jnp.float32)              # [Sq, D]
    lse = lse_ref[0]                                # [Sq]
    delta = delta_ref[0]                            # [Sq]
    st = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32) * scale
    if causal:
        j = pl.program_id(1)
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, st.shape, 0)
        q_pos = jax.lax.broadcasted_iota(jnp.int32, st.shape, 1)
        st = jnp.where(k_pos <= q_pos, st, _NEG)
    pt = jnp.exp(st - lse[None, :])                 # [bk, Sq]
    dv_ref[0] = jax.lax.dot_general(
        pt, do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dpt = jax.lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    dst = pt * (dpt - delta[None, :]) * scale
    dk_ref[0] = jax.lax.dot_general(
        dst, q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q):
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    B, H, S, D = q.shape
    Skv = k.shape[2]
    # largest divisor of S <= block_q, so an awkward block_q degrades to
    # the best legal tiling instead of cliff-diving to 1-row blocks
    bq = _row_block(S, 1, budget=min(block_q, S))
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, Skv, D)
    vf = v.reshape(B * H, Skv, D)
    kernel = functools.partial(_flash_fwd_kernel, scale, bool(causal), bq)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct(qf.shape, q.dtype),
                   jax.ShapeDtypeStruct((B * H, S), jnp.float32)],
        grid=(B * H, S // bq),
        in_specs=[pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0)),
                  pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0))],
        out_specs=[pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, bq), lambda b, i: (b, i))],
        interpret=interpret_mode())(qf, kf, vf)
    return out.reshape(B, H, S, D), lse


def _flash_backward(q, k, v, o, lse, do, causal, scale, block_q):
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    B, H, S, D = q.shape
    Skv = k.shape[2]
    bq = _row_block(S, 1, budget=min(block_q, S))
    bk = _row_block(Skv, 1, budget=min(block_q, Skv))
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, Skv, D)
    vf = v.reshape(B * H, Skv, D)
    dof = do.reshape(B * H, S, D)
    # delta = rowsum(dO * O) — elementwise O(S*D), cheap in plain XLA
    delta = jnp.sum(dof.astype(jnp.float32) *
                    o.reshape(B * H, S, D).astype(jnp.float32), axis=-1)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale, bool(causal), bq),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(B * H, S // bq),
        in_specs=[pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0)),
                  pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0)),
                  pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, bq), lambda b, i: (b, i)),
                  pl.BlockSpec((1, bq), lambda b, i: (b, i))],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        interpret=interpret_mode())(qf, kf, vf, dof, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale, bool(causal), bk),
        out_shape=[jax.ShapeDtypeStruct(kf.shape, k.dtype),
                   jax.ShapeDtypeStruct(vf.shape, v.dtype)],
        grid=(B * H, Skv // bk),
        in_specs=[pl.BlockSpec((1, S, D), lambda b, j: (b, 0, 0)),
                  pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
                  pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
                  pl.BlockSpec((1, S, D), lambda b, j: (b, 0, 0)),
                  pl.BlockSpec((1, S), lambda b, j: (b, 0)),
                  pl.BlockSpec((1, S), lambda b, j: (b, 0))],
        out_specs=[pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
                   pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0))],
        interpret=interpret_mode())(qf, kf, vf, dof, lse, delta)
    return (dq.reshape(B, H, S, D), dk.reshape(B, H, Skv, D),
            dv.reshape(B, H, Skv, D))


@functools.lru_cache(maxsize=None)
def _flash_vjp(causal, scale, block_q):
    """custom_vjp wrapper per hashable (causal, scale, block_q) static
    config — the lru_cache keeps one stable function identity per config
    so jit caches don't churn."""

    @jax.custom_vjp
    def f(q, k, v):
        return _flash_forward(q, k, v, causal, scale, block_q)[0]

    def f_fwd(q, k, v):
        o, lse = _flash_forward(q, k, v, causal, scale, block_q)
        return o, (q, k, v, o, lse)

    def f_bwd(res, do):
        q, k, v, o, lse = res
        return _flash_backward(q, k, v, o, lse, do, causal, scale, block_q)

    f.defvjp(f_fwd, f_bwd)
    return f


def flash_attention(q, k, v, causal=False, scale=None, block_q=128):
    """Fused flash attention, forward AND backward as Pallas kernels.

    q/k/v: [B, H, S, D].  The grid walks (batch*heads, q blocks); each
    step holds one q block plus its head's full K/V in VMEM (S*D per
    operand — S=8k at D=128 bf16 is 2MB, comfortably on chip), so the
    S x S score matrix never touches HBM.  The forward additionally saves
    a per-row logsumexp strip; the ``jax.custom_vjp`` backward recomputes
    the probabilities from it in two more Pallas kernels (dq over q
    blocks; dk/dv over kv blocks), keeping backward HBM traffic O(S*D)
    too.  Sequences larger than VMEM shard S over the 'sp' mesh axis
    first (parallel.ring_attention) and run this kernel per shard.
    Routing/fallback policy lives in ``mx.kernels.attention``
    (reference analog: hand-written fused CUDA attention via RTC,
    src/common/rtc.cc).
    """
    q = jnp.asarray(q)
    k = jnp.asarray(k)
    v = jnp.asarray(v)
    B, H, S, D = q.shape
    Skv = k.shape[2]
    if causal and Skv != S:
        raise ValueError("causal flash attention needs matching q/kv "
                         "lengths, got Sq=%d Skv=%d" % (S, Skv))
    if v.shape != k.shape:
        raise ValueError("k and v shapes differ: %s vs %s"
                         % (k.shape, v.shape))
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    return _flash_vjp(bool(causal), scale, int(block_q))(q, k, v)


@register("pallas_flash_attention")
def pallas_flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                           **_):
    """Flash attention via Pallas (mx.nd.pallas_flash_attention) —
    differentiable; see ``flash_attention`` for the kernel story."""
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           block_q=block_q)


# ------------------------------------------------------- paged attention
def _paged_attn_kernel(scale, quant, *refs):
    """One row block of single-query paged attention: ``rows`` (batch,
    head) pairs, each attending its page-gathered context of K slots.

    This is the online-softmax attend in its degenerate one-block form —
    a decode query is a single row, so the whole gathered context of a
    row block lives in VMEM and the stable (max, sum) accumulation
    happens on chip in f32 in one pass; no partial-block merge is ever
    needed.  Masked slots pin to the ``-1e30`` floor of
    ``parallel.ring_attention._block_attn``, so ``exp`` underflows to an
    EXACT 0.0 in both the denominator and the value sum — the bitwise
    contract the greedy-parity oracle rides on.  With ``quant`` the K/V
    blocks arrive int8 and dequantize INSIDE the kernel (one f32
    broadcast multiply per row), so HBM traffic stays at the int8 byte
    count — the entire point of int8 KV pages."""
    if quant:
        q_ref, k_ref, v_ref, valid_ref, ks_ref, vs_ref, o_ref = refs
    else:
        q_ref, k_ref, v_ref, valid_ref, o_ref = refs
    q = q_ref[:]                                    # [rows, D]
    k = k_ref[:]                                    # [rows, K, D]
    v = v_ref[:]
    if quant:
        k = k.astype(jnp.float32) * ks_ref[:][..., None]
        v = v.astype(jnp.float32) * vs_ref[:][..., None]
    s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * scale
    s = jnp.where(valid_ref[:], s, _NEG)            # [rows, K]
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=-1, keepdims=True)
    acc = jax.lax.dot_general(e.astype(v.dtype), v,
                              (((1,), (1,)), ((0,), (0,))))
    o_ref[:] = (acc / l.astype(acc.dtype)).astype(o_ref.dtype)


def pallas_paged_attention(q, k, v, valid, scale=None, k_scale=None,
                           v_scale=None, block_bh=None):
    """Paged-attention decode kernel: one query row per (batch, head)
    against its page-gathered context.

    q [B, H, 1, Dh]; k/v [B, H, K, Dh] gathered through a page table
    (slots past the true length hold stale or clipped-sentinel data);
    valid [B, K] masks exactly the real positions.  With
    ``k_scale``/``v_scale`` ([B, H, K] f32 per-row scales from
    ``mx.quantization.quantize_rows``) the K/V operands are int8 pages
    and dequantize inside the kernel.

    The grid walks blocks of ``block_bh`` (batch, head) rows (None =
    derive from the VMEM budget); each step holds its rows' full
    gathered K/V in VMEM.  The math is row-independent, so EVERY legal
    block size computes identical bits — which is why the
    mx.perf.autotune "paged" search can tune it freely under the bitwise
    greedy-parity contract.  Routing/fallback policy lives in
    ``mx.kernels.paged_attention``."""
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    q = jnp.asarray(q)
    k = jnp.asarray(k)
    v = jnp.asarray(v)
    B, H, Sq, D = q.shape
    if Sq != 1:
        raise ValueError("paged attention takes one query row per "
                         "sequence, got Sq=%d" % Sq)
    K = k.shape[2]
    if v.shape != k.shape:
        raise ValueError("k and v shapes differ: %s vs %s"
                         % (k.shape, v.shape))
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    quant = k_scale is not None
    BH = B * H
    qf = q.reshape(BH, D)
    kf = k.reshape(BH, K, D)
    vf = v.reshape(BH, K, D)
    validf = jnp.broadcast_to(valid[:, None, :], (B, H, K)).reshape(BH, K)
    # per-row VMEM: the gathered K/V dominate; scales/mask/q are noise
    row_bytes = 2 * K * D * k.dtype.itemsize \
        + K * (1 + 8 * int(quant)) + D * (q.dtype.itemsize + 4)
    if block_bh is None:
        rows = _row_block(BH, row_bytes)
    else:
        rows = _row_block(BH, 1, budget=min(int(block_bh), BH))
    if rows == 1 and BH > 1:
        # XLA lowers the degenerate one-row dot_general through a
        # different reduction than the multi-row form (last-ulp drift),
        # which would break the bitwise greedy-parity contract — snap up
        # to the smallest real divisor instead.
        rows = next(r for r in range(2, BH + 1) if BH % r == 0)
    operands = [qf, kf, vf, validf]
    in_specs = [pl.BlockSpec((rows, D), lambda i: (i, 0)),
                pl.BlockSpec((rows, K, D), lambda i: (i, 0, 0)),
                pl.BlockSpec((rows, K, D), lambda i: (i, 0, 0)),
                pl.BlockSpec((rows, K), lambda i: (i, 0))]
    if quant:
        operands += [jnp.asarray(k_scale, jnp.float32).reshape(BH, K),
                     jnp.asarray(v_scale, jnp.float32).reshape(BH, K)]
        in_specs += [pl.BlockSpec((rows, K), lambda i: (i, 0)),
                     pl.BlockSpec((rows, K), lambda i: (i, 0))]
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, scale, quant),
        out_shape=jax.ShapeDtypeStruct((BH, D), q.dtype),
        grid=(BH // rows,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((rows, D), lambda i: (i, 0)),
        interpret=interpret_mode())(*operands)
    return out.reshape(B, H, 1, D)


# ------------------------------------------- fused optimizer+cast epilogue
def _sgd_epilogue_kernel(momentum, w_ref, g_ref, mom_ref, lr_ref, wd_ref,
                         lp_ref, w_out_ref, mom_out_ref):
    """SGD+momentum update and low-precision cast in one VMEM pass: the
    f32 master row block is read once, the new master, momentum and cast
    weight are written — no intermediate HBM arrays between the stages."""
    w = w_ref[:]
    g = g_ref[:] + wd_ref[0, 0] * w
    mom = momentum * mom_ref[:] + lr_ref[0, 0] * g
    nw = w - mom
    w_out_ref[:] = nw
    mom_out_ref[:] = mom
    lp_ref[:] = nw.astype(lp_ref.dtype)


def _sgd_nomom_epilogue_kernel(w_ref, g_ref, lr_ref, wd_ref, lp_ref,
                               w_out_ref):
    w = w_ref[:]
    g = g_ref[:] + wd_ref[0, 0] * w
    nw = w - lr_ref[0, 0] * g
    w_out_ref[:] = nw
    lp_ref[:] = nw.astype(lp_ref.dtype)


def _adam_epilogue_kernel(beta1, beta2, eps, w_ref, g_ref, m_ref, v_ref,
                          lr_t_ref, wd_ref, lp_ref, w_out_ref, m_out_ref,
                          v_out_ref):
    """Adam update + cast in one VMEM pass; the bias-corrected lr_t is
    precomputed outside (it depends on the traced step count, not the
    row block) and rides in as a (1,1) scalar block."""
    w = w_ref[:]
    g = g_ref[:] + wd_ref[0, 0] * w
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    nw = w - lr_t_ref[0, 0] * m / (jnp.sqrt(v) + eps)
    w_out_ref[:] = nw
    m_out_ref[:] = m
    v_out_ref[:] = v
    lp_ref[:] = nw.astype(lp_ref.dtype)


def _flat2d(a):
    if a.ndim >= 2:
        return a.reshape(-1, a.shape[-1])
    if a.ndim == 1:
        return a.reshape(1, -1)
    return a.reshape(1, 1)


def _epilogue_call(kernel, arrays, scalars, out_dtypes, block_rows=None):
    """Launch an elementwise epilogue kernel over same-shape operands:
    arrays flatten to 2-D and stream through shared row blocks; scalars
    ride as (1,1) blocks pinned to every grid step.  ``block_rows``
    overrides the VMEM-budget row-block derivation (mx.perf.autotune
    passes measured winners through); it still snaps to the largest
    divisor of n that fits, so an awkward tuned value can never break
    the exact-tiling requirement."""
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    shape = arrays[0].shape
    flats = [_flat2d(a) for a in arrays]
    n, d = flats[0].shape
    itemsize = max(f.dtype.itemsize for f in flats)
    row_bytes = d * itemsize * (len(arrays) + len(out_dtypes))
    if block_rows is None:
        rows = _row_block(n, row_bytes)
    else:
        rows = _row_block(n, 1, budget=min(int(block_rows), n))
    scal = [jnp.asarray(s, jnp.float32).reshape(1, 1) for s in scalars]
    outs = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((n, d), dt) for dt in out_dtypes],
        grid=(n // rows,),
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0))
                  for _ in flats] +
                 [pl.BlockSpec((1, 1), lambda i: (0, 0)) for _ in scal],
        out_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0))
                   for _ in out_dtypes],
        interpret=interpret_mode())(*(flats + scal))
    return [o.reshape(shape) for o in outs]


def fused_sgd_step(weight, grad, state, lr, wd, momentum, out_dtype=None,
                   block_rows=None):
    """Single-kernel SGD(+momentum) update with cast epilogue.

    ``weight`` is the f32 master; returns
    ``(weight_cast[out_dtype], new_master, new_state)`` — identical math
    and op order to ``SGD.step`` followed by ``astype``, so the result is
    bitwise-equal to the master-copy round trip it replaces.
    ``block_rows`` is the tunable row-block size (None = derive from the
    VMEM budget); the math is row-wise, so any block size computes the
    same bits."""
    weight = jnp.asarray(weight)
    grad = jnp.asarray(grad)
    out_dtype = jnp.dtype(out_dtype) if out_dtype is not None \
        else weight.dtype
    if momentum == 0.0:
        lp, nw = _epilogue_call(
            _sgd_nomom_epilogue_kernel, [weight, grad], [lr, wd],
            [out_dtype, weight.dtype], block_rows=block_rows)
        return lp, nw, None
    lp, nw, mom = _epilogue_call(
        functools.partial(_sgd_epilogue_kernel, momentum),
        [weight, grad, state], [lr, wd],
        [out_dtype, weight.dtype, state.dtype], block_rows=block_rows)
    return lp, nw, mom


def fused_adam_step(weight, grad, m, v, lr_t, wd, beta1, beta2, eps,
                    out_dtype=None, block_rows=None):
    """Single-kernel Adam update with cast epilogue (see
    ``fused_sgd_step``); ``lr_t`` is the bias-corrected learning rate the
    caller computes from the traced step count."""
    weight = jnp.asarray(weight)
    grad = jnp.asarray(grad)
    out_dtype = jnp.dtype(out_dtype) if out_dtype is not None \
        else weight.dtype
    lp, nw, nm, nv = _epilogue_call(
        functools.partial(_adam_epilogue_kernel, beta1, beta2, eps),
        [weight, grad, m, v], [lr_t, wd],
        [out_dtype, weight.dtype, m.dtype, v.dtype], block_rows=block_rows)
    return lp, nw, (nm, nv)


# ------------------------------------------------------- fused elementwise
def _scale_bias_relu_kernel(x_ref, scale_ref, bias_ref, o_ref):
    """Fused y = relu(x * scale + bias) — the classic post-GEMM epilogue."""
    o_ref[:] = jnp.maximum(x_ref[:] * scale_ref[:] + bias_ref[:], 0.0)


@register("pallas_scale_bias_relu", differentiable=False)
def pallas_scale_bias_relu(data, scale, bias, **_):
    """Fused per-feature epilogue y = relu(x*scale + bias)
    (mx.nd.pallas_scale_bias_relu); scale/bias broadcast over the last
    axis INSIDE the kernel, so HBM reads stay B*D + 2*D."""
    from jax.experimental import pallas as pl
    from ..rtc import interpret_mode
    x = jnp.asarray(data)
    d = x.shape[-1]
    flat = x.reshape(-1, d)
    n = flat.shape[0]
    s = jnp.asarray(scale).reshape(1, d).astype(x.dtype)
    b = jnp.asarray(bias).reshape(1, d).astype(x.dtype)
    rows = _row_block(n, d * flat.dtype.itemsize)
    out = pl.pallas_call(
        _scale_bias_relu_kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        grid=(n // rows,),
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0)),
                  pl.BlockSpec((1, d), lambda i: (0, 0)),
                  pl.BlockSpec((1, d), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        interpret=interpret_mode())(flat, s, b)
    return out.reshape(x.shape)
