"""Operator registry and eager dispatcher.

Reference design: NNVM op registry with per-op attributes
(FInferShape/FInferType/FCompute..., include/mxnet/op_attr_types.h:217-282) and
the imperative dispatcher Imperative::Invoke → PushFCompute
(src/imperative/imperative_utils.h:395) pushing kernels to the ThreadedEngine.

TPU-native re-design: an op is a *pure jax function* plus metadata.  Eager
dispatch is a direct call — jax's async dispatch replaces the engine — and
differentiability comes from taping a ``jax.vjp`` at call time instead of an
FGradient graph pass.  The same pure functions serve the Symbol executor and
hybridized (jit) paths, so there is exactly one lowering per op.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import jax
import numpy as _np

__all__ = ["Operator", "register", "get", "apply_op", "list_ops"]

_REGISTRY: Dict[str, "Operator"] = {}


class Operator:
    """Metadata wrapper for a registered op.

    Parameters
    ----------
    name : canonical op name (reference NNVM name where one exists).
    fn : pure function ``fn(*arrays, **attrs) -> array | tuple(arrays)``.
    differentiable : False for ops with no gradient (argmax, comparisons...).
    num_outputs : static output count (informational).
    aliases : extra registry names.
    """

    __slots__ = ("name", "fn", "differentiable", "num_outputs", "sparse_vjp")

    def __init__(self, name, fn, differentiable=True, num_outputs=1):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.num_outputs = num_outputs
        # optional (in_arrays, attrs, cotangents) -> per-NDArray-input cts
        # hook producing sparse cotangents (RowSparseTangent) instead of the
        # generic jax.vjp; active when the call passes sparse_grad=True
        self.sparse_vjp = None


def register(name, differentiable=True, num_outputs=1, aliases=()):
    """Decorator: register a pure jax function as an op."""

    def deco(fn):
        op = Operator(name, fn, differentiable, num_outputs)
        _REGISTRY[name] = op
        for a in aliases:
            _REGISTRY[a] = op
        return fn

    return deco


def get(name: str) -> Operator:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise AttributeError("operator %r is not registered" % (name,)) from None


def list_ops():
    return sorted(_REGISTRY)


def _float0_to_none(ct):
    if ct is None:
        return None
    if getattr(ct, "dtype", None) == jax.dtypes.float0:
        return None
    return ct


def apply_op(op, *inputs, **attrs):
    """Eager-execute ``op`` on NDArray inputs, taping a vjp when recording.

    Returns NDArray or list of NDArrays (matching the op's output arity).
    """
    from .. import _tape
    from ..ndarray.ndarray import NDArray, _wrap

    if isinstance(op, str):
        op = get(op)

    in_arrays = []
    nd_inputs = []
    for x in inputs:
        if isinstance(x, NDArray):
            nd_inputs.append(x)
            in_arrays.append(x._data)
        else:
            in_arrays.append(x)

    recording = _tape.is_recording() and op.differentiable and nd_inputs

    if recording and op.sparse_vjp is not None and attrs.get("sparse_grad"):
        # sparse-cotangent path (Embedding sparse_grad=True): the weight
        # gradient stays (rows, values) — never a dense scatter-add image —
        # so huge embeddings train with O(rows-touched) gradient memory
        # (reference: src/operator/tensor/indexing_op.cc row_sparse grad)
        out_vals = op.fn(*in_arrays, **attrs)
        multi = isinstance(out_vals, (tuple, list))
        outs = [_wrap(v) for v in (out_vals if multi else (out_vals,))]
        # the hook returns one cotangent per *positional* input; the tape
        # node records only the NDArray inputs, so select those positions
        # (same alignment the generic path gets via nd_idx)
        nd_pos = [i for i, x in enumerate(inputs) if isinstance(x, NDArray)]

        def sparse_vjp_fn(cotangents, _op=op, _in=tuple(in_arrays),
                          _attrs=dict(attrs), _nd_pos=tuple(nd_pos)):
            cts = _op.sparse_vjp(_in, _attrs, cotangents)
            return tuple(cts[i] for i in _nd_pos)

        _tape.record_node(
            nd_inputs, outs, sparse_vjp_fn, name=op.name,
            hogr_error="%s with sparse_grad=True produces a row-sparse "
                       "cotangent that cannot be re-taped; use "
                       "sparse_grad=False for create_graph=True "
                       "higher-order gradients" % op.name)
        return outs if multi else outs[0]

    if recording:
        nd_idx = [i for i, x in enumerate(inputs) if isinstance(x, NDArray)]

        def pure(*diff_arrays):
            full = list(in_arrays)
            for i, a in zip(nd_idx, diff_arrays):
                full[i] = a
            return op.fn(*full, **attrs)

        diff_in = [in_arrays[i] for i in nd_idx]
        out_vals, vjp = jax.vjp(pure, *diff_in)
        from ..engine import naive_engine_enabled
        if naive_engine_enabled():
            jax.block_until_ready(out_vals)
        multi = isinstance(out_vals, (tuple, list))
        outs = [_wrap(v) for v in (out_vals if multi else (out_vals,))]

        def vjp_fn(cotangents, _vjp=vjp, _multi=multi):
            cts = tuple(cotangents) if _multi else cotangents[0]
            in_cts = _vjp(cts)
            return tuple(_float0_to_none(c) for c in in_cts)

        _tape.record_node(nd_inputs, outs, vjp_fn, name=op.name,
                          primal_fn=pure, primal_multi=multi)
        return outs if multi else outs[0]

    out_vals = op.fn(*in_arrays, **attrs)
    from ..engine import naive_engine_enabled
    if naive_engine_enabled():
        # NaiveEngine debug mode: synchronous per-op completion
        jax.block_until_ready(out_vals)
    if isinstance(out_vals, (tuple, list)):
        return [_wrap(v) for v in out_vals]
    return _wrap(out_vals)


def invoke(name, *inputs, **attrs):
    """Convenience: apply by name (used by generated NDArray methods)."""
    return apply_op(get(name), *inputs, **attrs)
