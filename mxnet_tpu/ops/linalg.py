"""Advanced linear-algebra ops.

Reference: src/operator/tensor/la_op.cc (NNVM ops _linalg_gemm, _linalg_potri,
_linalg_trmm, _linalg_sumlogdiag, _linalg_extractdiag/_makediag,
_linalg_extracttrian/_maketrian, _linalg_gelqf, _linalg_syevd,
_linalg_inverse, _linalg_det, _linalg_slogdet) and contrib/krprod.cc
(khatri_rao).  TPU-native: each op is a jnp.linalg / lax.linalg lowering;
XLA's batched LAPACK-style kernels replace the reference's per-batch BLAS
loops, and gradients come from jax's built-in linalg JVP/VJP rules instead of
hand-written _backward_* ops.

gemm2/potrf/trsm/syrk live in tensor.py (registered in round 1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("linalg_gemm", aliases=("_linalg_gemm",))
def _linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                 beta=1.0, axis=-2, **_):
    """C' = alpha*op(A)op(B) + beta*C (reference la_op.cc:40)."""
    a = jnp.asarray(A)
    b = jnp.asarray(B)
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * jnp.asarray(C)


@register("linalg_potri", aliases=("_linalg_potri",))
def _linalg_potri(A, **_):
    """Inverse of SPD matrix FROM its Cholesky factor L: (L L^T)^-1
    (reference la_op.cc:240)."""
    L = jnp.asarray(A)
    eye = jnp.broadcast_to(jnp.eye(L.shape[-1], dtype=L.dtype), L.shape)
    Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)


@register("linalg_trmm", aliases=("_linalg_trmm",))
def _linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                 alpha=1.0, **_):
    """Triangular matrix multiply alpha*op(A)*B (reference la_op.cc:298)."""
    a = jnp.asarray(A)
    if not lower:
        a = jnp.triu(a)
    else:
        a = jnp.tril(a)
    if transpose:
        a = jnp.swapaxes(a, -1, -2)
    b = jnp.asarray(B)
    out = jnp.matmul(b, a) if rightside else jnp.matmul(a, b)
    return alpha * out


@register("linalg_sumlogdiag", aliases=("_linalg_sumlogdiag",))
def _linalg_sumlogdiag(A, **_):
    """sum(log(diag(A))) per batch matrix (reference la_op.cc:423)."""
    a = jnp.asarray(A)
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_extractdiag", aliases=("_linalg_extractdiag",))
def _linalg_extractdiag(A, offset=0, **_):
    return jnp.diagonal(jnp.asarray(A), offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag", aliases=("_linalg_makediag",))
def _linalg_makediag(A, offset=0, **_):
    a = jnp.asarray(A)
    n = a.shape[-1] + abs(offset)
    out_shape = a.shape[:-1] + (n, n)
    idx = jnp.arange(a.shape[-1])
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    out = jnp.zeros(out_shape, a.dtype)
    return out.at[..., rows, cols].set(a)


def _trian_indices(n, offset, lower):
    if lower:
        r, c = jnp.tril_indices(n, k=offset)
    else:
        r, c = jnp.triu_indices(n, k=offset)
    return r, c


@register("linalg_extracttrian", aliases=("_linalg_extracttrian",))
def _linalg_extracttrian(A, offset=0, lower=True, **_):
    """Pack a triangle of each matrix into a vector (reference la_op.cc:569)."""
    a = jnp.asarray(A)
    r, c = _trian_indices(a.shape[-1], offset, lower)
    return a[..., r, c]


@register("linalg_maketrian", aliases=("_linalg_maketrian",))
def _linalg_maketrian(A, offset=0, lower=True, **_):
    """Unpack a vector back into a triangular matrix (reference la_op.cc:627)."""
    a = jnp.asarray(A)
    m = a.shape[-1]
    # m = n*(n+1)/2 - adjustment for offset; solve for n
    k = abs(offset)
    # number of packed elements for size n with offset: full triangle of
    # (n - k) plus nothing else; invert n from m
    nk = int((-1 + (1 + 8 * m) ** 0.5) / 2)
    n = nk + k
    r, c = _trian_indices(n, offset, lower)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., r, c].set(a)


@register("linalg_gelqf", aliases=("_linalg_gelqf",), num_outputs=2)
def _linalg_gelqf(A, **_):
    """LQ factorization A = L·Q with Q's rows orthonormal
    (reference la_op.cc:752).  Lowered via QR of Aᵀ."""
    a = jnp.asarray(A)
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_syevd", aliases=("_linalg_syevd",), num_outputs=2)
def _linalg_syevd(A, **_):
    """Symmetric eigendecomposition, returns (U, lambda) with rows of U the
    eigenvectors: A = Uᵀ diag(lambda) U (reference la_op.cc:823)."""
    w, v = jnp.linalg.eigh(jnp.asarray(A))
    return jnp.swapaxes(v, -1, -2), w


@register("linalg_inverse", aliases=("_linalg_inverse", "inverse"))
def _linalg_inverse(A, **_):
    return jnp.linalg.inv(jnp.asarray(A))


@register("linalg_det", aliases=("_linalg_det", "det"))
def _linalg_det(A, **_):
    return jnp.linalg.det(jnp.asarray(A))


@register("linalg_slogdet", aliases=("_linalg_slogdet", "slogdet"),
          num_outputs=2)
def _linalg_slogdet(A, **_):
    sign, logabs = jnp.linalg.slogdet(jnp.asarray(A))
    return sign, logabs


@register("khatri_rao")
def _khatri_rao(*matrices, **_):
    """Column-wise Kronecker product (reference contrib/krprod.cc)."""
    mats = [jnp.asarray(m) for m in matrices]
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[1])
    return out
