"""FFT ops.

Reference: src/operator/contrib/fft-inl.h / ifft-inl.h — cuFFT C2C over the
last axis with real input and interleaved (re, im) output, unnormalized in
both directions (so ifft(fft(x)) == n*x, the cuFFT convention).

TPU-native: jnp.fft lowerings; XLA compiles FFT natively on TPU.  The
interleaved-pair layout of the reference API is preserved so symbols/models
using _contrib_fft port unchanged.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


@register("_contrib_fft", aliases=("fft",))
def _fft(data, compute_size=128, **_):
    """Real input (..., n) -> interleaved complex output (..., 2n)."""
    x = jnp.asarray(data)
    f = jnp.fft.fft(x.astype(jnp.float32), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(x.shape[:-1] + (2 * x.shape[-1],)).astype(x.dtype)


@register("_contrib_ifft", aliases=("ifft",))
def _ifft(data, compute_size=128, **_):
    """Interleaved complex input (..., 2n) -> real output (..., n),
    unnormalized (scaled by n) per the reference's cuFFT convention."""
    x = jnp.asarray(data)
    n = x.shape[-1] // 2
    pairs = x.reshape(x.shape[:-1] + (n, 2)).astype(jnp.float32)
    z = pairs[..., 0] + 1j * pairs[..., 1]
    out = jnp.fft.ifft(z, axis=-1).real * n
    return out.astype(x.dtype)
