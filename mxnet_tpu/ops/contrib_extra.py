"""Contrib operator completion: quantized graph ops, RPN proposals,
position-sensitive ROI pooling, and assorted contrib math.

Reference files are cited per op.  Same fixed-shape TPU design rules as
ops/contrib.py: no dynamic output counts — suppressed/invalid entries are
marked, not removed; per-ROI work is vmapped; box-region sums use integral
images (cumsum) so every ROI costs O(1) gathers instead of a dynamic
pixel loop.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .registry import register, get, _REGISTRY
from .contrib import _corner_iou, _nms_one

__all__ = []


# ---------------------------------------------------------- small math ops

@register("_contrib_quadratic", aliases=("quadratic",))
def _quadratic(data, a=0.0, b=0.0, c=0.0, **_):
    """a*x^2 + b*x + c (reference src/operator/contrib/quadratic_op.cc —
    the tutorial op; kept for script parity)."""
    x = jnp.asarray(data)
    return a * x * x + b * x + c


@register("_contrib_allclose", aliases=("allclose",), differentiable=False)
def _allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=True, **_):
    """1.0 iff allclose (reference contrib/allclose_op.cc)."""
    ok = jnp.allclose(jnp.asarray(a), jnp.asarray(b), rtol=rtol, atol=atol,
                      equal_nan=bool(equal_nan))
    return ok.astype(jnp.float32).reshape((1,))


@register("_contrib_arange_like", aliases=("arange_like",),
          differentiable=False)
def _arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, **_):
    """arange shaped like data (reference contrib/tensor ops arange_like —
    transformer position-id helper)."""
    d = jnp.asarray(data)
    if axis is None:
        n = d.size
        out = start + step * (jnp.arange(n) // repeat)
        return out.reshape(d.shape).astype(d.dtype)
    n = d.shape[axis]
    out = (start + step * (jnp.arange(n) // repeat)).astype(d.dtype)
    shape = [1] * d.ndim
    shape[axis] = n
    return jnp.broadcast_to(out.reshape(shape), d.shape)


@register("_contrib_index_copy", aliases=("index_copy",))
def _index_copy(old, index, new, **_):
    """Copy new[i] into old[index[i]] (reference contrib/index_copy.cc)."""
    idx = jnp.asarray(index).astype(jnp.int32).ravel()
    return jnp.asarray(old).at[idx].set(jnp.asarray(new))


@register("_contrib_index_array", aliases=("index_array",),
          differentiable=False)
def _index_array(data, axes=None, **_):
    """Per-element N-d indices (reference contrib/index_array.cc): output
    shape data.shape + (len(axes) or ndim,)."""
    d = jnp.asarray(data)
    ax = tuple(axes) if axes else tuple(range(d.ndim))
    grids = jnp.meshgrid(*[jnp.arange(s) for s in d.shape], indexing="ij")
    return jnp.stack([grids[a] for a in ax], axis=-1).astype(jnp.int32)


@register("_contrib_getnnz", aliases=("getnnz",), differentiable=False)
def _getnnz(data, axis=None, **_):
    """Count stored (nonzero) values (reference contrib/nnz.cc, CSR)."""
    d = jnp.asarray(data)
    if axis is None:
        return jnp.sum(d != 0).astype(jnp.int32).reshape((1,))
    return jnp.sum(d != 0, axis=axis).astype(jnp.int32)


@register("_contrib_edge_id", aliases=("edge_id",), differentiable=False)
def _edge_id(indptr, indices, edge_data, u, v, **_):
    """Edge ids for (u,v) queries over a CSR graph whose data holds edge
    ids (reference src/operator/contrib/dgl_graph.cc EdgeID); -1 where no
    edge.  Inputs are the CSR triple as arrays (the CSRNDArray container
    unpacks itself at the mx.nd.contrib.edge_id call site)."""
    ip = jnp.asarray(indptr).astype(jnp.int32)
    ci = jnp.asarray(indices).astype(jnp.int32)
    ed = jnp.asarray(edge_data)
    uu = jnp.asarray(u).astype(jnp.int32).ravel()
    vv = jnp.asarray(v).astype(jnp.int32).ravel()

    def one(ui, vi):
        start, stop = ip[ui], ip[ui + 1]
        pos = jnp.arange(ci.shape[0])
        hit = (pos >= start) & (pos < stop) & (ci == vi)
        return jnp.where(jnp.any(hit), ed[jnp.argmax(hit)], -1.0)

    return jax.vmap(one)(uu, vv)


@register("_contrib_count_sketch", aliases=("count_sketch",),
          differentiable=False)
def _count_sketch(data, h, s, out_dim=None, **_):
    """Count-sketch projection (reference contrib/count_sketch.cu): out[:,
    h[j]] += s[j] * data[:, j]."""
    x = jnp.asarray(data)
    hh = jnp.asarray(h).astype(jnp.int32).ravel()
    ss = jnp.asarray(s).ravel()
    out = jnp.zeros(x.shape[:-1] + (int(out_dim),), x.dtype)
    return out.at[..., hh].add(x * ss)


@register("_contrib_hawkesll", aliases=("hawkesll",), num_outputs=2)
def _hawkesll(mu, alpha, beta, state, lags, marks, valid_length, max_time,
              **_):
    """Log-likelihood of a marked self-exciting Hawkes process with
    exponential decay (reference src/operator/contrib/hawkes_ll.cc).

    mu (K,) or (B,K) background rates; alpha (K,) branching; beta (K,)
    decay; state (B,K) prior excitation; lags/marks (B,T); valid_length
    (B,); max_time (B,).  Returns (ll (B,), new_state (B,K)) — identical
    recursion to the reference kernel, expressed as a lax.scan over T.
    """
    mu_ = jnp.atleast_1d(jnp.asarray(mu, jnp.float32))
    al = jnp.asarray(alpha, jnp.float32).ravel()
    be = jnp.asarray(beta, jnp.float32).ravel()
    st0 = jnp.asarray(state, jnp.float32)
    lg = jnp.asarray(lags, jnp.float32)
    mk = jnp.asarray(marks).astype(jnp.int32)
    vl = jnp.asarray(valid_length).astype(jnp.int32).ravel()
    mt = jnp.asarray(max_time, jnp.float32).ravel()
    B, T = lg.shape
    K = st0.shape[-1]
    mu_b = jnp.broadcast_to(mu_, (B, K))

    def step(carry, inp):
        ll, state, t_acc = carry
        lag, mark, pos = inp
        decay = jnp.exp(-be[None, :] * lag[:, None])
        state_d = state * decay
        lam = mu_b + state_d                         # (B,K) intensities
        lam_m = jnp.take_along_axis(lam, mark[:, None], 1)[:, 0]
        valid = (pos < vl).astype(jnp.float32)
        ll = ll + valid * jnp.log(jnp.maximum(lam_m, 1e-30))
        # compensator increment over the lag interval
        comp = jnp.sum((state - state_d) / be[None, :], axis=1) \
            + jnp.sum(mu_b, axis=1) * lag
        ll = ll - valid * comp
        onehot = jax.nn.one_hot(mark, K, dtype=jnp.float32)
        state = state_d + valid[:, None] * onehot * (al * be)[None, :]
        return (ll, state, t_acc + valid * lag), None

    (ll, state, t_sum), _ = lax.scan(
        step, (jnp.zeros(B), st0, jnp.zeros(B)),
        (lg.T, mk.T, jnp.arange(T)))
    # tail compensator from the last event to max_time
    rem = jnp.maximum(mt - t_sum, 0.0)
    decay = jnp.exp(-be[None, :] * rem[:, None])
    ll = ll - jnp.sum(mu_b, axis=1) * rem \
        - jnp.sum(state * (1 - decay) / be[None, :], axis=1)
    return ll, state * decay


@register("_contrib_AdaptiveAvgPooling2D",
          aliases=("AdaptiveAvgPooling2D", "adaptive_avg_pool2d"))
def _adaptive_avg_pool2d(data, output_size=(1, 1), **_):
    """Adaptive average pooling (reference
    src/operator/contrib/adaptive_avg_pooling.cc).

    TPU-native formulation: the variable-window averages are exactly a pair
    of fixed matmuls  W_h @ X @ W_w^T  with precomputed (static-shape)
    overlap-fraction weight matrices — MXU work instead of per-window
    gather loops.
    """
    d = jnp.asarray(data)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    elif len(output_size) == 1:
        output_size = (output_size[0], output_size[0])
    oh, ow = int(output_size[0]), int(output_size[1])
    H, W = d.shape[2], d.shape[3]

    def weights(out_n, in_n):
        w = _np.zeros((out_n, in_n), _np.float32)
        for o in range(out_n):
            lo = (o * in_n) // out_n
            hi = -(-((o + 1) * in_n) // out_n)  # ceil
            w[o, lo:hi] = 1.0 / (hi - lo)
        return jnp.asarray(w)

    wh = weights(oh, H)
    ww = weights(ow, W)
    return jnp.einsum("oh,nchw,pw->ncop", wh, d, ww)


# ------------------------------------------------------------ quantization
# Completes the int8 graph-op set around the existing quantized FC/conv
# (reference src/operator/quantization/*.cc).  Same convention as
# ops/contrib.py: symmetric ranges, (out, min, max) outputs.

def _range_pair(min_range, max_range):
    amax = jnp.maximum(jnp.abs(jnp.asarray(min_range, jnp.float32)),
                       jnp.abs(jnp.asarray(max_range, jnp.float32)))
    return -amax, amax


@register("_contrib_quantize", aliases=("quantize",), differentiable=False,
          num_outputs=3)
def _quantize(data, min_range, max_range, out_type="int8", **_):
    """f32 -> int8 with explicit range (reference quantization/quantize.cc;
    the calib-range form of the existing quantize_v2)."""
    lo, hi = _range_pair(min_range, max_range)
    s = 127.0 / jnp.maximum(hi, 1e-12)
    q = jnp.clip(jnp.round(jnp.asarray(data) * s), -127, 127)
    return q.astype(jnp.int8), lo.reshape((1,)), hi.reshape((1,))


@register("_contrib_requantize", aliases=("requantize",),
          differentiable=False, num_outputs=3)
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None, **_):
    """int32 accumulator -> int8 with (re)calibrated range (reference
    quantization/requantize.cc)."""
    x = jnp.asarray(data).astype(jnp.float32)
    lo32, hi32 = _range_pair(min_range, max_range)
    real = x * (hi32 / 2147483647.0)
    if min_calib_range is not None and max_calib_range is not None:
        lo, hi = _range_pair(min_calib_range, max_calib_range)
    else:
        hi = jnp.max(jnp.abs(real))
        lo = -hi
    s = 127.0 / jnp.maximum(hi, 1e-12)
    q = jnp.clip(jnp.round(real * s), -127, 127)
    return q.astype(jnp.int8), jnp.reshape(lo, (1,)), jnp.reshape(hi, (1,))


@register("_contrib_quantized_act", aliases=("quantized_act",),
          differentiable=False, num_outputs=3)
def _quantized_act(data, min_data, max_data, act_type="relu", **_):
    """int8 activation (reference quantized_activation.cc): relu keeps the
    int8 grid; ranges pass through clipped at zero."""
    q = jnp.asarray(data)
    lo, hi = _range_pair(min_data, max_data)
    if act_type == "relu":
        return jnp.maximum(q, 0), jnp.zeros((1,)), hi.reshape((1,))
    raise ValueError("quantized_act supports relu only (reference parity)")


@register("_contrib_quantized_flatten", aliases=("quantized_flatten",),
          differentiable=False, num_outputs=3)
def _quantized_flatten(data, min_data, max_data, **_):
    q = jnp.asarray(data)
    lo, hi = _range_pair(min_data, max_data)
    return (q.reshape(q.shape[0], -1), lo.reshape((1,)), hi.reshape((1,)))


@register("_contrib_quantized_concat", aliases=("quantized_concat",),
          differentiable=False, num_outputs=3)
def _quantized_concat(*args, dim=1, num_args=None, **_):
    """int8 concat (reference quantized_concat.cc): inputs are N data
    tensors followed by N mins and N maxes; output rescales every part to
    the widest range so the int8 grid is shared."""
    n = num_args if num_args is not None else len(args) // 3
    datas = [jnp.asarray(a).astype(jnp.float32) for a in args[:n]]
    mins = [jnp.asarray(a) for a in args[n:2 * n]]
    maxs = [jnp.asarray(a) for a in args[2 * n:3 * n]]
    amaxs = [jnp.maximum(jnp.abs(lo).max(), jnp.abs(hi).max())
             for lo, hi in zip(mins, maxs)]
    amax = amaxs[0]
    for a in amaxs[1:]:
        amax = jnp.maximum(amax, a)
    parts = [jnp.clip(jnp.round(d * (a / jnp.maximum(amax, 1e-12))),
                      -127, 127)
             for d, a in zip(datas, amaxs)]
    out = jnp.concatenate(parts, axis=dim).astype(jnp.int8)
    return out, (-amax).reshape((1,)), amax.reshape((1,))


@register("_contrib_quantized_elemwise_add",
          aliases=("quantized_elemwise_add",), differentiable=False,
          num_outputs=3)
def _quantized_elemwise_add(lhs, rhs, min_lhs, max_lhs, min_rhs, max_rhs,
                            **_):
    """int8 add with range merge (reference quantized_elemwise_add.cc)."""
    _, ah = _range_pair(min_lhs, max_lhs)
    _, bh = _range_pair(min_rhs, max_rhs)
    fa = jnp.asarray(lhs).astype(jnp.float32) * (ah / 127.0)
    fb = jnp.asarray(rhs).astype(jnp.float32) * (bh / 127.0)
    out = fa + fb
    amax = ah + bh
    q = jnp.clip(jnp.round(out * (127.0 / jnp.maximum(amax, 1e-12))),
                 -127, 127)
    return q.astype(jnp.int8), (-amax).reshape((1,)), amax.reshape((1,))


@register("_contrib_quantized_pooling", aliases=("quantized_pooling",),
          differentiable=False, num_outputs=3)
def _quantized_pooling(data, min_data, max_data, kernel=(2, 2), pool_type="max",
                       stride=None, pad=None, global_pool=False, **_):
    """int8 pooling (reference quantized_pooling.cc): max pool stays on the
    int8 grid exactly; avg pool averages in f32 and re-rounds."""
    from .nn import _pooling
    lo, hi = _range_pair(min_data, max_data)
    q = jnp.asarray(data)
    out = _pooling(q.astype(jnp.float32), kernel=kernel, pool_type=pool_type,
                   stride=stride, pad=pad, global_pool=global_pool)
    out = jnp.round(out) if pool_type != "max" else out
    return (jnp.clip(out, -127, 127).astype(jnp.int8),
            lo.reshape((1,)), hi.reshape((1,)))


@register("_contrib_quantized_batch_norm", aliases=("quantized_batch_norm",),
          differentiable=False, num_outputs=3)
def _quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                          min_data, max_data, eps=1e-3, **_):
    """int8 inference BatchNorm (reference quantized_batch_norm.cc): folds
    the affine transform in f32, recalibrates the output range."""
    _, hi = _range_pair(min_data, max_data)
    x = jnp.asarray(data).astype(jnp.float32) * (hi / 127.0)
    g = jnp.asarray(gamma)
    b = jnp.asarray(beta)
    mm = jnp.asarray(moving_mean)
    mv = jnp.asarray(moving_var)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    y = (x - mm.reshape(shape)) / jnp.sqrt(mv.reshape(shape) + eps) \
        * g.reshape(shape) + b.reshape(shape)
    amax = jnp.max(jnp.abs(y))
    q = jnp.clip(jnp.round(y * (127.0 / jnp.maximum(amax, 1e-12))),
                 -127, 127)
    return q.astype(jnp.int8), (-amax).reshape((1,)), amax.reshape((1,))


@register("_contrib_calibrate_entropy", aliases=("calibrate_entropy",),
          differentiable=False, num_outputs=2)
def _calibrate_entropy(hist, hist_edges, num_quantized_bins=255, **_):
    """KL-divergence threshold calibration (reference
    quantization/calibrate.cc); delegates to the python implementation in
    contrib/quantization.py (host-side, runs once at calibration time)."""
    from ..contrib.quantization import _kl_threshold
    h = _np.asarray(hist)
    e = _np.asarray(hist_edges)
    t = _kl_threshold(h, e, int(num_quantized_bins))
    return (jnp.asarray([-t], jnp.float32), jnp.asarray([t], jnp.float32))


# ------------------------------------------------------------ RPN proposals

def _enum_anchors(scales, ratios, feat_h, feat_w, stride):
    base = float(stride)
    cx = cy = (base - 1) / 2.0
    anchors = []
    for r in ratios:
        size = base * base
        ws = _np.round(_np.sqrt(size / r))
        hs = _np.round(ws * r)
        for s in scales:
            w, h = ws * s, hs * s
            anchors.append([cx - (w - 1) / 2, cy - (h - 1) / 2,
                            cx + (w - 1) / 2, cy + (h - 1) / 2])
    A = _np.asarray(anchors, _np.float32)              # (A,4)
    sx = _np.arange(feat_w) * stride
    sy = _np.arange(feat_h) * stride
    gx, gy = _np.meshgrid(sx, sy)
    shifts = _np.stack([gx.ravel(), gy.ravel(), gx.ravel(), gy.ravel()], 1)
    all_a = (A[None, :, :] + shifts[:, None, :]).reshape(-1, 4)
    return jnp.asarray(all_a)                          # (H*W*A, 4)


def _proposal_one(scores, deltas, anchors, im_info, rpn_pre_nms_top_n,
                  rpn_post_nms_top_n, threshold, rpn_min_size):
    """Single-image RPN proposal generation (static shapes)."""
    # decode deltas (dx,dy,dw,dh) against anchors
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    cx = dx * aw + acx
    cy = dy * ah + acy
    w = jnp.exp(jnp.clip(dw, -10, 10)) * aw
    h = jnp.exp(jnp.clip(dh, -10, 10)) * ah
    x1 = jnp.clip(cx - w / 2, 0, im_info[1] - 1)
    y1 = jnp.clip(cy - h / 2, 0, im_info[0] - 1)
    x2 = jnp.clip(cx + w / 2, 0, im_info[1] - 1)
    y2 = jnp.clip(cy + h / 2, 0, im_info[0] - 1)
    min_size = rpn_min_size * im_info[2]
    keep = ((x2 - x1 + 1) >= min_size) & ((y2 - y1 + 1) >= min_size)
    scores = jnp.where(keep, scores, -1e9)
    k = min(rpn_pre_nms_top_n, scores.shape[0])
    top_scores, top_idx = lax.top_k(scores, k)
    boxes = jnp.stack([x1, y1, x2, y2], 1)[top_idx]
    entries = jnp.concatenate([top_scores[:, None], boxes], 1)  # (k,5)
    nms = _nms_one(entries, 0.0, threshold, rpn_post_nms_top_n,
                   score_index=0, coord_start=1, id_index=-1,
                   force_suppress=True)
    out = nms[:rpn_post_nms_top_n]
    s = out[:, 0]
    rois = jnp.where(s[:, None] > 0, out[:, 1:5], 0.0)
    return rois, jnp.maximum(s, 0.0)[:, None]


@register("_contrib_Proposal", aliases=("Proposal", "_contrib_MultiProposal",
                                        "MultiProposal"),
          differentiable=False, num_outputs=2)
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
              feature_stride=16, output_score=False, iou_loss=False, **_):
    """Region-proposal generation (reference
    src/operator/contrib/proposal.cc, multi_proposal.cc — MultiProposal is
    the batched form; this implementation vmaps over the batch either way).

    cls_prob (B, 2A, H, W), bbox_pred (B, 4A, H, W), im_info (B, 3).
    Returns (rois (B*post_n, 5) with batch index in col 0, scores).  Fixed
    post_n output with zero padding replaces the reference's dynamic keep
    list.
    """
    cp = jnp.asarray(cls_prob)
    bp = jnp.asarray(bbox_pred)
    info = jnp.asarray(im_info)
    B, A2, H, W = cp.shape
    A = A2 // 2
    if A != len(scales) * len(ratios):
        raise ValueError(
            "Proposal: cls_prob has %d anchor channels but scales x ratios "
            "gives %d anchors" % (A, len(scales) * len(ratios)))
    anchors = _enum_anchors(scales, ratios, H, W, feature_stride)
    # fg scores: second half of the 2A channel block, layout (A,H,W)
    fg = cp[:, A:, :, :].transpose(0, 2, 3, 1).reshape(B, -1)   # (B,HWA)
    deltas = bp.transpose(0, 2, 3, 1).reshape(B, -1, 4)

    rois, scores = jax.vmap(
        lambda s, d, ii: _proposal_one(
            s, d, anchors, ii, int(rpn_pre_nms_top_n),
            int(rpn_post_nms_top_n), float(threshold),
            float(rpn_min_size)))(fg, deltas, info)
    batch_ids = jnp.repeat(jnp.arange(B, dtype=rois.dtype),
                           rois.shape[1])[:, None]
    out = jnp.concatenate([batch_ids,
                           rois.reshape(-1, 4)], 1)
    return out, scores.reshape(-1, 1)


# ----------------------------------------- position-sensitive ROI pooling

def _tap_bilinear(feat, y, x):
    """Bilinear tap of (C, H, W) features at one float point; zero outside
    the image (the reference's boundary rule)."""
    C, H, W = feat.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = y - y0
    wx = x - x0

    def corner(yy, xx):
        ok = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
        yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        return feat[:, yc, xc] * ok.astype(feat.dtype)

    top = corner(y0, x0) * (1 - wx) + corner(y0, x0 + 1) * wx
    bot = corner(y0 + 1, x0) * (1 - wx) + corner(y0 + 1, x0 + 1) * wx
    return top * (1 - wy) + bot * wy


def _integral(x):
    """2-D integral image over the trailing axes (H, W)."""
    c = jnp.cumsum(jnp.cumsum(x, axis=-2), axis=-1)
    return jnp.pad(c, [(0, 0)] * (x.ndim - 2) + [(1, 0), (1, 0)])


def _box_mean(ii, y0, y1, x0, x1):
    """Mean over [y0,y1)x[x0,x1) from an integral image (..., H+1, W+1)."""
    y0c = jnp.clip(y0, 0, ii.shape[-2] - 1)
    y1c = jnp.clip(y1, 0, ii.shape[-2] - 1)
    x0c = jnp.clip(x0, 0, ii.shape[-1] - 1)
    x1c = jnp.clip(x1, 0, ii.shape[-1] - 1)
    s = (ii[..., y1c, x1c] - ii[..., y0c, x1c]
         - ii[..., y1c, x0c] + ii[..., y0c, x0c])
    area = jnp.maximum((y1c - y0c) * (x1c - x0c), 1)
    return s / area


@register("_contrib_PSROIPooling", aliases=("PSROIPooling",),
          differentiable=False)
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                   pooled_size=1, group_size=0, **_):
    """Position-sensitive ROI pooling (reference
    src/operator/contrib/psroi_pooling.cc): output channel (c, gy, gx)
    averages input channel c*G*G + gy*G + gx over the (gy,gx) bin of the
    ROI.  Integral-image bin sums keep every ROI O(1)."""
    x = jnp.asarray(data)
    r = jnp.asarray(rois)
    G = int(group_size) if group_size else int(pooled_size)
    P = int(pooled_size)
    C = int(output_dim)
    ii_all = _integral(x)                   # (B, C*G*G, H+1, W+1)

    def one_roi(roi):
        ii = ii_all[roi[0].astype(jnp.int32)]  # roi batch index
        x1 = roi[1] * spatial_scale
        y1 = roi[2] * spatial_scale
        x2 = roi[3] * spatial_scale
        y2 = roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        out = jnp.zeros((C, P, P), x.dtype)
        for gy in range(P):
            for gx in range(P):
                yy0 = jnp.floor(y1 + rh * gy / P).astype(jnp.int32)
                yy1 = jnp.ceil(y1 + rh * (gy + 1) / P).astype(jnp.int32)
                xx0 = jnp.floor(x1 + rw * gx / P).astype(jnp.int32)
                xx1 = jnp.ceil(x1 + rw * (gx + 1) / P).astype(jnp.int32)
                cg = jnp.arange(C) * G * G + min(gy, G - 1) * G \
                    + min(gx, G - 1)
                vals = _box_mean(ii[cg], yy0, yy1, xx0, xx1)
                out = out.at[:, gy, gx].set(vals)
        return out

    return jax.vmap(one_roi)(r)


@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",), differentiable=False,
          num_outputs=2)
def _deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                              output_dim=1, pooled_size=1, group_size=0,
                              part_size=0, sample_per_part=4, trans_std=0.1,
                              no_trans=False, **_):
    """Deformable PS-ROI pooling (reference
    src/operator/contrib/deformable_psroi_pooling.cc): each bin's sampling
    window shifts by a learned offset; bins are averaged from
    sample_per_part^2 bilinear taps."""
    x = jnp.asarray(data)
    r = jnp.asarray(rois)
    P = int(pooled_size)
    G = int(group_size) if group_size else P
    C = int(output_dim)
    S = int(sample_per_part)
    tr = None if (no_trans or trans is None) else jnp.asarray(trans)

    def one_roi(roi, ridx):
        feat = x[roi[0].astype(jnp.int32)]             # roi batch index
        x1 = roi[1] * spatial_scale - 0.5
        y1 = roi[2] * spatial_scale - 0.5
        x2 = roi[3] * spatial_scale + 0.5
        y2 = roi[4] * spatial_scale + 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / P, rh / P
        out = jnp.zeros((C, P, P), x.dtype)
        for gy in range(P):
            for gx in range(P):
                if tr is not None:
                    dx = tr[ridx, 0, min(gy, tr.shape[2] - 1),
                            min(gx, tr.shape[3] - 1)] * trans_std * rw
                    dy = tr[ridx, 1, min(gy, tr.shape[2] - 1),
                            min(gx, tr.shape[3] - 1)] * trans_std * rh
                else:
                    dx = dy = 0.0
                cg = jnp.arange(C) * G * G + min(gy, G - 1) * G \
                    + min(gx, G - 1)
                acc = jnp.zeros((C,), x.dtype)
                for sy in range(S):
                    for sx in range(S):
                        yy = y1 + gy * bin_h + (sy + 0.5) * bin_h / S + dy
                        xx = x1 + gx * bin_w + (sx + 0.5) * bin_w / S + dx
                        acc = acc + _tap_bilinear(
                            feat[cg], jnp.asarray(yy), jnp.asarray(xx))
                out = out.at[:, gy, gx].set(acc / (S * S))
        return out

    idx = jnp.arange(r.shape[0])
    pooled = jax.vmap(one_roi)(r, idx)
    return pooled, jnp.zeros_like(pooled)


@register("_contrib_RROIAlign", aliases=("RROIAlign",),
          differentiable=False)
def _rroi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0, **_):
    """Rotated ROI align (reference src/operator/contrib/rroi_align.cc):
    rois are (batch, cx, cy, w, h, angle_deg); the pooled grid is rotated
    into image space and sampled bilinearly."""
    x = jnp.asarray(data)
    r = jnp.asarray(rois)
    if isinstance(pooled_size, int):
        pooled_size = (pooled_size, pooled_size)
    ph, pw = int(pooled_size[0]), int(pooled_size[1])

    def one_roi(roi):
        feat = x[roi[0].astype(jnp.int32)]             # roi batch index
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        w = jnp.maximum(roi[3] * spatial_scale, 1.0)
        h = jnp.maximum(roi[4] * spatial_scale, 1.0)
        ang = roi[5] * jnp.pi / 180.0
        cos, sin = jnp.cos(ang), jnp.sin(ang)
        ys = (jnp.arange(ph) + 0.5) / ph - 0.5        # (-.5, .5) grid
        xs = (jnp.arange(pw) + 0.5) / pw - 0.5
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        lx = gx * w
        ly = gy * h
        ix = cx + lx * cos - ly * sin
        iy = cy + lx * sin + ly * cos
        return jax.vmap(
            lambda yy, xx: _tap_bilinear(feat, yy, xx),
            in_axes=(0, 0), out_axes=1)(iy.ravel(), ix.ravel()).reshape(
                (feat.shape[0], ph, pw))

    return jax.vmap(one_roi)(r)


# ----------------------------------------------------------------- aliases

_CONTRIB_ALIASES = {
    "_contrib_ctc_loss": "ctc_loss",
    "_contrib_CTCLoss": "ctc_loss",
    "CTCLoss": "ctc_loss",
    "_contrib_box_non_maximum_suppression": "box_nms",
    "_contrib_boolean_mask": "boolean_mask",
    # SparseEmbedding IS Embedding with a row_sparse gradient; the
    # sparse_grad attr selects the sparse vjp path (ops/tensor.py)
    "_contrib_SparseEmbedding": "Embedding",
    # cross-device BatchNorm statistics: inside a pjit-sharded step the BN
    # moment reduction is already global (psum over the mesh), which IS
    # SyncBatchNorm's semantics (reference contrib/sync_batch_norm.cc)
    "_contrib_SyncBatchNorm": "BatchNorm",
    "SyncBatchNorm": "BatchNorm",
}

for _alias, _target in _CONTRIB_ALIASES.items():
    if _alias not in _REGISTRY:
        _REGISTRY[_alias] = get(_target)
