"""Neural-network ops.

Reference: src/operator/nn/ (fully_connected.cc:255-322, convolution-inl.h,
batch_norm.cc, pooling.cc, softmax, dropout, layer_norm ...; cuDNN/MKLDNN
kernel dispatch).  TPU-native: each op is a single jax/lax lowering — conv and
FC map straight onto the MXU via lax.conv_general_dilated / jnp.dot, norms and
activations are VPU elementwise code that XLA fuses into neighbors.  The NCHW
default layout of the reference API is preserved at the op boundary; XLA's
layout assignment re-tiles internally for the MXU, so no NHWC rewrite is
forced on users.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# ------------------------------------------------------------------ dense

@register("FullyConnected", aliases=("fully_connected",))
def _fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                     flatten=True, **_):
    x = jnp.asarray(data)
    if flatten:
        x = x.reshape(x.shape[0], -1)
    out = jnp.dot(x, jnp.asarray(weight).T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ------------------------------------------------------------------ conv

def _conv_dims(ndim):
    # spatial rank -> (lhs, rhs, out) layout strings, NC-first like reference
    spatial = "DHW"[-ndim:]
    return ("NC" + spatial, "OI" + spatial, "NC" + spatial)


def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    return t if len(t) == n else t + (t[-1],) * (n - len(t))


@register("Convolution", aliases=("convolution",))
def _convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                 pad=None, num_filter=None, num_group=1, no_bias=False,
                 layout=None, **_):
    x = jnp.asarray(data)
    w = jnp.asarray(weight)
    ndim = x.ndim - 2
    stride = _tup(stride, ndim)
    dilate = _tup(dilate, ndim)
    pad = _tup(pad if pad is not None else 0, ndim)
    pad = pad if isinstance(pad[0], tuple) else tuple((p, p) for p in pad)
    nhwc = ndim == 2 and _nhwc_internal()
    rhs_spec = "HWIO" if (ndim == 2 and _HWIO_WEIGHTS) else None
    if nhwc:
        # channels-LAST internal layout (docs/PERF_NOTES.md): channels map
        # to the TPU's 128-lane minor dimension, which is where the
        # HBM-bound 1x1 convs of a ResNet want them.  The logical API
        # stays NCHW; XLA cancels the transposes between back-to-back
        # convs, so only the graph edges pay a relayout.
        xin = jnp.transpose(x, (0, 2, 3, 1))
        dn = lax.conv_dimension_numbers(xin.shape, w.shape,
                                        ("NHWC", rhs_spec or "OIHW",
                                         "NHWC"))
    else:
        xin = x
        lhs_spec, _, out_spec = _conv_dims(ndim)
        dn = lax.conv_dimension_numbers(
            x.shape, w.shape,
            (lhs_spec, rhs_spec or "OI" + "DHW"[-ndim:], out_spec))
    out = lax.conv_general_dilated(
        xin, w, window_strides=stride, padding=pad, rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32
        else None)
    if nhwc:
        out = jnp.transpose(out, (0, 3, 1, 2))
    out = out.astype(x.dtype)
    if bias is not None and not no_bias:
        out = out + jnp.asarray(bias).reshape((1, -1) + (1,) * ndim)
    return out


def _nhwc_internal():
    from .. import config as _config
    return _config.get("conv.internal_layout") == "NHWC"


# Trace-scoped flag: SPMDTrainer sets this while tracing its jitted step
# after converting the conv weights it owns to HWIO (channels-last
# end-to-end, docs/PERF_NOTES.md).  Module state rather than a config knob
# so eager paths outside the trainer (which still hold OIHW weights) are
# never misinterpreted.
_HWIO_WEIGHTS = False


def set_hwio_weights(on):
    """Flip the HWIO weight interpretation; returns the previous value."""
    global _HWIO_WEIGHTS
    prev = _HWIO_WEIGHTS
    _HWIO_WEIGHTS = bool(on)
    return prev


@register("Deconvolution", aliases=("deconvolution",))
def _deconvolution(data, weight, bias=None, kernel=None, stride=None,
                   dilate=None, pad=None, adj=None, num_filter=None,
                   num_group=1, no_bias=True, **_):
    x = jnp.asarray(data)
    w = jnp.asarray(weight)  # (C_in, C_out/g, *k) — reference layout
    ndim = x.ndim - 2
    stride = _tup(stride, ndim)
    pad = _tup(pad if pad is not None else 0, ndim)
    k = w.shape[2:]
    # transposed conv = gradient of conv: use conv_general_dilated with
    # lhs_dilation=stride and flipped kernel
    wt = jnp.swapaxes(w, 0, 1)  # (C_out/g, C_in, *k)
    wt = jnp.flip(wt, axis=tuple(range(2, 2 + ndim)))
    pads = tuple((k[i] - 1 - pad[i], k[i] - 1 - pad[i] + (adj[i] if adj else 0))
                 for i in range(ndim))
    dn = lax.conv_dimension_numbers(x.shape, wt.shape, _conv_dims(ndim))
    out = lax.conv_general_dilated(
        x, wt, window_strides=(1,) * ndim, padding=pads,
        lhs_dilation=stride, dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None and not no_bias:
        out = out + jnp.asarray(bias).reshape((1, -1) + (1,) * ndim)
    return out


# ------------------------------------------------------------------ pooling

@register("Pooling", aliases=("pooling",))
def _pooling(data, kernel=None, pool_type="max", global_pool=False,
             stride=None, pad=None, pooling_convention="valid",
             count_include_pad=True, **_):
    x = jnp.asarray(data)
    ndim = x.ndim - 2
    if global_pool:
        axes = tuple(range(2, x.ndim))
        if pool_type == "max":
            return jnp.max(x, axis=axes, keepdims=True)
        return jnp.mean(x, axis=axes, keepdims=True)
    kernel = _tup(kernel, ndim)
    stride = _tup(stride if stride is not None else kernel, ndim)
    pad = _tup(pad if pad is not None else 0, ndim)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1.0
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    if pool_type == "lp":
        p = 2.0
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pads)
        return s ** (1.0 / p)
    raise ValueError("unknown pool_type %r" % pool_type)


# ------------------------------------------------------------------ norms

@register("BatchNorm", aliases=("batch_norm",), num_outputs=3)
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                axis=1, training=False, **_):
    """Returns (out, batch_mean, batch_var).  Moving-stat update is done by
    the caller (gluon BatchNorm layer) — pure-functional split of the
    reference's in-op aux-state mutation (src/operator/nn/batch_norm.cc)."""
    x = jnp.asarray(data)
    g = jnp.asarray(gamma)
    if fix_gamma:
        g = jnp.ones_like(g)
    # Statistics and the normalization arithmetic run in f32 even when the
    # activations are bf16 (mixed-precision policy): the reduction over
    # N*H*W elements loses too much in bf16, and XLA fuses the widened
    # elementwise chain into the surrounding ops at no extra HBM cost.
    xf = x.astype(jnp.float32)
    red_axes = tuple(i for i in range(x.ndim) if i != axis)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    if training and not use_global_stats:
        from .. import config as _config
        if _config.get("bn_two_pass_stats"):
            # exact two-pass variance for pathological offset-heavy inputs
            # (mean/std ratio beyond ~3000 at cold start) — costs an extra
            # HBM read of the activations per step
            mean = jnp.mean(xf, axis=red_axes)
            var = jnp.var(xf, axis=red_axes)
        else:
            # Single-pass SHIFTED statistics: sums of d and d^2
            # (d = x - shift) land in ONE multi-output XLA fusion — one HBM
            # read of the activations, where jnp.var's two-pass form
            # re-reads the tensor after the mean is known (and again in its
            # vjp).  BN stats dominate the non-MXU time of a ResNet step,
            # so this is the hot spot.  The shift is the moving mean: free
            # (fuses into the same pass; a data-derived proxy was measured
            # to break producer fusion, +20% step time) and it tracks the
            # batch mean from step 2 on, so E[d^2]-E[d]^2 cancellation
            # cannot ignite once stats are warm.  The exposure is step 1
            # with |mean|/std beyond ~3000 (f32 accumulation absorbs
            # anything smaller); conv outputs under zero-mean init are
            # nowhere near that, and `mx.config.set("bn_two_pass_stats",
            # True)` selects the exact path for data that is.
            shift = jnp.asarray(moving_mean).astype(jnp.float32)\
                .reshape(shape)
            d = xf - shift
            dm = jnp.mean(d, axis=red_axes)
            d2 = jnp.mean(jnp.square(d), axis=red_axes)
            var = jnp.maximum(d2 - jnp.square(dm), 0.0)
            mean = dm + shift.reshape(-1)
    else:
        mean = jnp.asarray(moving_mean).astype(jnp.float32)
        var = jnp.asarray(moving_var).astype(jnp.float32)
    # Fold the normalization into one scale+bias per channel so the
    # per-element chain is a single fused multiply-add.
    scale = (lax.rsqrt(var + eps) * g.astype(jnp.float32)).reshape(shape)
    bias = (jnp.asarray(beta).astype(jnp.float32).reshape(shape)
            - mean.reshape(shape) * scale)
    out = xf * scale + bias
    return out.astype(x.dtype), mean, var


@register("LayerNorm", aliases=("layer_norm",))
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, **_):
    x = jnp.asarray(data)
    mean, var = _moments(x, (axis % x.ndim,))
    out = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    out = (out * jnp.asarray(gamma).reshape(shape)
           + jnp.asarray(beta).reshape(shape))
    return out.astype(x.dtype)


def _moments(x, axes):
    """Two-pass (mean, var) in f32, keepdims.  Layer/Group/InstanceNorm
    reduce over small per-sample axes, so the extra read of the two-pass
    form is cheap — and unlike E[x^2]-E[x]^2 it cannot catastrophically
    cancel for large-mean activations (residual streams drift).  BatchNorm,
    whose N*H*W reduction IS the hot path, uses a shifted single-pass in
    _batch_norm instead.  f32 accumulation also keeps bf16/fp16 inputs from
    overflowing in jnp.square."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    return mean, var


@register("GroupNorm", aliases=("group_norm",))
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5, **_):
    x = jnp.asarray(data)  # (N, C, ...)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, num_groups, c // num_groups) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean, var = _moments(xg, axes)
    xn = ((xg.astype(jnp.float32) - mean)
          * lax.rsqrt(var + eps)).reshape(x.shape)
    shape = (1, c) + (1,) * (x.ndim - 2)
    out = (xn * jnp.asarray(gamma).reshape(shape)
           + jnp.asarray(beta).reshape(shape))
    return out.astype(x.dtype)


@register("InstanceNorm", aliases=("instance_norm",))
def _instance_norm(data, gamma, beta, eps=1e-3, **_):
    x = jnp.asarray(data)
    axes = tuple(range(2, x.ndim))
    mean, var = _moments(x, axes)
    xn = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + eps)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    out = (xn * jnp.asarray(gamma).reshape(shape)
           + jnp.asarray(beta).reshape(shape))
    return out.astype(x.dtype)


# ------------------------------------------------------------------ softmax

@register("softmax")
def _softmax(data, axis=-1, temperature=None, length=None, **_):
    x = jnp.asarray(data)
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        steps = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = steps.reshape(shape) < jnp.expand_dims(jnp.asarray(length), axis)
        x = jnp.where(mask, x, -jnp.inf)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def _log_softmax(data, axis=-1, temperature=None, **_):
    x = jnp.asarray(data)
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def _softmin(data, axis=-1, **_):
    return jax.nn.softmax(-jnp.asarray(data), axis=axis)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        multi_output, normalization):
    x = jnp.asarray(data)
    return jax.nn.softmax(x, axis=-1 if not multi_output else 1)


@jax.custom_vjp
def _softmax_output_core(data, label):
    return jax.nn.softmax(data, axis=-1)


def _smo_fwd(data, label):
    p = jax.nn.softmax(data, axis=-1)
    return p, (p, label)


def _smo_bwd(res, g):
    # reference semantics: gradient is (p - onehot(label)), independent of the
    # incoming cotangent (SoftmaxOutput defines its own loss;
    # src/operator/softmax_output-inl.h)
    p, label = res
    onehot = jax.nn.one_hot(label.astype(jnp.int32), p.shape[-1], dtype=p.dtype)
    return ((p - onehot) / p.shape[0], jnp.zeros_like(label))


_softmax_output_core.defvjp(_smo_fwd, _smo_bwd)


@register("SoftmaxOutput", aliases=("softmax_output",))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    use_ignore=False, multi_output=False,
                    normalization="batch", **_):
    return _softmax_output_core(jnp.asarray(data), jnp.asarray(label))


# ------------------------------------------------------------------ act

@register("Activation", aliases=("activation",))
def _activation(data, act_type="relu", **_):
    x = jnp.asarray(data)
    if act_type == "relu":
        return jax.nn.relu(x)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return jax.nn.softplus(x)
    if act_type == "softsign":
        return jax.nn.soft_sign(x)
    raise ValueError("unknown act_type %r" % act_type)


@register("LeakyReLU", aliases=("leaky_relu",))
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334, **_):
    x = jnp.asarray(data)
    if act_type == "leaky":
        return jax.nn.leaky_relu(x, slope)
    if act_type == "prelu":
        g = jnp.asarray(gamma)
        shape = (1, -1) + (1,) * (x.ndim - 2) if x.ndim > 1 else (-1,)
        return jnp.where(x >= 0, x, g.reshape(shape) * x)
    if act_type == "elu":
        return jax.nn.elu(x, slope)
    if act_type == "selu":
        return jax.nn.selu(x)
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "rrelu":
        return jax.nn.leaky_relu(x, (lower_bound + upper_bound) / 2.0)
    raise ValueError("unknown act_type %r" % act_type)


# ------------------------------------------------------------------ dropout

@register("Dropout", aliases=("dropout",))
def _dropout(data, p=0.5, mode="training", axes=(), training=False, **_):
    x = jnp.asarray(data)
    if not training and mode != "always":
        return x
    if p <= 0.0:
        return x
    from ..random import next_key
    shape = list(x.shape)
    for ax in axes:
        shape[ax] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(next_key(), keep, tuple(shape))
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


# ------------------------------------------------------------------ losses

@register("CTCLoss", aliases=("ctc_loss",))
def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False,
              blank_label="first", **_):
    """data: (T, B, V) activations (pre-softmax); label: (B, L) padded with -1
    or 0.  Reference: src/operator/nn/ctc_loss.cc.  TPU lowering via optax."""
    import optax
    x = jnp.transpose(jnp.asarray(data), (1, 0, 2))  # (B, T, V)
    lab = jnp.asarray(label).astype(jnp.int32)
    B, T, V = x.shape
    if use_data_lengths and data_lengths is not None:
        dl = jnp.asarray(data_lengths).astype(jnp.int32)
        logitpad = (jnp.arange(T)[None, :] >= dl[:, None]).astype(x.dtype)
    else:
        logitpad = jnp.zeros((B, T), x.dtype)
    if use_label_lengths and label_lengths is not None:
        ll = jnp.asarray(label_lengths).astype(jnp.int32)
        labpad = (jnp.arange(lab.shape[1])[None, :] >= ll[:, None]).astype(x.dtype)
    else:
        labpad = (lab < 0).astype(x.dtype) if blank_label == "first" else (lab <= 0).astype(x.dtype)
    if blank_label == "first":
        # optax uses blank=0 like the reference's default
        pass
    lab = jnp.maximum(lab, 0)
    return optax.ctc_loss(x, logitpad, lab, labpad)


# ------------------------------------------------------------------ misc

@register("BlockGrad", aliases=("stop_gradient", "block_grad"))
def _block_grad(data, **_):
    return lax.stop_gradient(jnp.asarray(data))


@register("identity", aliases=("_copy",))
def _identity(data, **_):
    return jnp.asarray(data)


@register("make_loss", aliases=("MakeLoss",))
def _make_loss(data, grad_scale=1.0, **_):
    return jnp.asarray(data) * 1.0


@register("UpSampling", aliases=("upsampling",))
def _upsampling(data, scale=2, sample_type="nearest", **_):
    x = jnp.asarray(data)
    out = jnp.repeat(jnp.repeat(x, scale, axis=-2), scale, axis=-1)
    return out


# ------------------------------------------------- round-3 coverage widening

@register("LRN", aliases=("lrn",), num_outputs=2)
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **_):
    """Local response normalization across channels (reference
    src/operator/nn/lrn.cc).  Returns (out, norm_scale) like the reference's
    two-output registration."""
    x = jnp.asarray(data)
    half = nsize // 2
    sq = jnp.square(x)
    # windowed channel sum via padded cumulative trick
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    windows = sum(pad[:, i:i + x.shape[1]] for i in range(nsize))
    scale = knorm + (alpha / nsize) * windows
    return x / jnp.power(scale, beta), scale


@register("SoftmaxActivation", aliases=("softmax_activation",))
def _softmax_activation(data, mode="instance", **_):
    """Deprecated-in-reference but still registered op
    (src/operator/nn/softmax_activation.cc)."""
    x = jnp.asarray(data)
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


@register("hard_sigmoid")
def _hard_sigmoid(data, alpha=0.2, beta=0.5, **_):
    x = jnp.asarray(data)
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register("softmax_cross_entropy")
def _softmax_cross_entropy(data, label, **_):
    """Total CE of logits vs int labels, scalar output (reference
    src/operator/loss_binary_op.cc)."""
    x = jnp.asarray(data)
    lab = jnp.asarray(label).astype(jnp.int32).ravel()
    logp = jax.nn.log_softmax(x, axis=-1)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked)


def _regression_output(name, fwd, grad):
    """Shared frame for the *RegressionOutput heads (reference
    src/operator/regression_output.cc): forward transforms data, backward
    IGNORES the incoming cotangent and emits its own per-example gradient —
    these ops define their loss implicitly."""

    @jax.custom_vjp
    def core(data, label):
        return fwd(data)

    def core_fwd(data, label):
        out = fwd(data)
        return out, (out, label, data.shape[0])

    def core_bwd(res, g):
        out, label, batch = res
        return (grad(out, label) / batch, jnp.zeros_like(label))

    core.defvjp(core_fwd, core_bwd)

    @register(name, aliases=(_snake(name),))
    def op(data, label, grad_scale=1.0, **_):
        return core(jnp.asarray(data),
                    jnp.asarray(label).astype(jnp.asarray(data).dtype))
    return op


def _snake(name):
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i and not name[i - 1].isupper():
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


_regression_output("LinearRegressionOutput",
                   lambda x: x,
                   lambda out, label: out - label.reshape(out.shape))
_regression_output("LogisticRegressionOutput",
                   jax.nn.sigmoid,
                   lambda out, label: out - label.reshape(out.shape))
_regression_output("MAERegressionOutput",
                   lambda x: x,
                   lambda out, label: jnp.sign(out - label.reshape(out.shape)))


@register("SVMOutput", aliases=("svm_output",))
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False, **_):
    """Forward is identity; the hinge-loss gradient is defined by the op
    (reference src/operator/svm_output.cc)."""
    x = jnp.asarray(data)
    lab = jnp.asarray(label).astype(jnp.int32)

    @jax.custom_vjp
    def core(d, l):
        return d

    def core_fwd(d, l):
        return d, (d, l)

    def core_bwd(res, g):
        d, l = res
        onehot = jax.nn.one_hot(l, d.shape[-1], dtype=d.dtype)
        signed = jnp.where(onehot > 0, d, -d)
        viol = (margin - signed) > 0
        if use_linear:
            gd = jnp.where(viol, jnp.where(onehot > 0, -1.0, 1.0), 0.0)
        else:
            gd = jnp.where(viol, 2.0 * (margin - signed)
                           * jnp.where(onehot > 0, -1.0, 1.0), 0.0)
        return (regularization_coefficient * gd.astype(d.dtype),
                jnp.zeros_like(l))

    core.defvjp(core_fwd, core_bwd)
    return core(x, lab)
