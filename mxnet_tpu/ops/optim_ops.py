"""Optimizer update ops (registry-level).

Reference: src/operator/optimizer_op.cc (sgd/adam/rmsprop/ftrl/ftml/nag/
signum families, multi-tensor variants :320-656) and contrib/adamw.cc,
contrib/multi_sum_sq.cc, contrib/multi_lars.cc, contrib/lamb (la
mb_update_phase1/2).

TPU-native re-design: the reference ops MUTATE weight/state tensors in
place; here every op is pure and RETURNS the updated tensors (weight first,
then states) — in-place semantics don't exist on immutable jax.Arrays, and
the functional form is what a jitted train step needs anyway.  The gluon
Trainer path uses optimizer/optimizer.py's step() functions; these registry
ops provide script-level parity (mx.nd.sgd_update etc.) and feed the op
sweep.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep(grad, rescale_grad, clip_gradient):
    g = jnp.asarray(grad) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update")
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=False, **_):
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient)
    return w - lr * (g + wd * w)


@register("sgd_mom_update", num_outputs=2)
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False,
                    **_):
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient)
    m = momentum * jnp.asarray(mom) - lr * (g + wd * w)
    return w + m, m


@register("mp_sgd_update", num_outputs=2)
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **_):
    """Multi-precision sgd: master f32 copy updated, low-precision weight
    recast from it (reference optimizer_op.cc:589)."""
    w32 = jnp.asarray(weight32)
    g = _prep(grad, rescale_grad, clip_gradient).astype(jnp.float32)
    new32 = w32 - lr * (g + wd * w32)
    return new32.astype(jnp.asarray(weight).dtype), new32


@register("mp_sgd_mom_update", num_outputs=3)
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **_):
    w32 = jnp.asarray(weight32)
    g = _prep(grad, rescale_grad, clip_gradient).astype(jnp.float32)
    m = momentum * jnp.asarray(mom) - lr * (g + wd * w32)
    new32 = w32 + m
    return new32.astype(jnp.asarray(weight).dtype), m, new32


@register("nag_mom_update", num_outputs=2)
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, **_):
    """Nesterov momentum (reference optimizer_op.cc:710)."""
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient) + wd * w
    m = momentum * jnp.asarray(mom) + g
    return w - lr * (g + momentum * m), m


@register("mp_nag_mom_update", num_outputs=3)
def _mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **_):
    w32 = jnp.asarray(weight32)
    g = _prep(grad, rescale_grad, clip_gradient).astype(jnp.float32) \
        + wd * w32
    m = momentum * jnp.asarray(mom) + g
    new32 = w32 - lr * (g + momentum * m)
    return new32.astype(jnp.asarray(weight).dtype), m, new32


@register("adam_update", num_outputs=3)
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=False, **_):
    """Adam step WITHOUT bias correction — the reference kernel expects the
    caller to fold the correction into lr (optimizer_op.cc:656)."""
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient) + wd * w
    m = beta1 * jnp.asarray(mean) + (1 - beta1) * g
    v = beta2 * jnp.asarray(var) + (1 - beta2) * g * g
    return w - lr * m / (jnp.sqrt(v) + epsilon), m, v


@register("ftml_update", num_outputs=4)
def _ftml_update(weight, grad, d, v, z, lr=0.001, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                 clip_grad=-1.0, **_):
    """FTML (reference optimizer_op.cc:624)."""
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_grad) + wd * w
    v_new = beta2 * jnp.asarray(v) + (1 - beta2) * g * g
    d_new = (1 - beta1 ** t) / lr * \
        (jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * jnp.asarray(d)
    z_new = beta1 * jnp.asarray(z) + (1 - beta1) * g - sigma * w
    return -z_new / d_new, d_new, v_new, z_new


@register("rmsprop_update", num_outputs=2)
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0, **_):
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient) + wd * w
    n_new = gamma1 * jnp.asarray(n) + (1 - gamma1) * g * g
    new_w = w - lr * g / (jnp.sqrt(n_new) + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, n_new


@register("rmspropalex_update", num_outputs=4)
def _rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0, **_):
    """RMSProp with Graves' centered variant (reference
    optimizer_op.cc:811)."""
    w = jnp.asarray(weight)
    gr = _prep(grad, rescale_grad, clip_gradient) + wd * w
    n_new = gamma1 * jnp.asarray(n) + (1 - gamma1) * gr * gr
    g_new = gamma1 * jnp.asarray(g) + (1 - gamma1) * gr
    delta_new = gamma2 * jnp.asarray(delta) - \
        lr * gr / jnp.sqrt(n_new - g_new * g_new + epsilon)
    new_w = w + delta_new
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, n_new, g_new, delta_new


@register("ftrl_update", num_outputs=3)
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0, **_):
    """FTRL-proximal (reference optimizer_op.cc:852)."""
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient)
    n_old = jnp.asarray(n)
    n_new = n_old + g * g
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n_old)) / lr
    z_new = jnp.asarray(z) + g - sigma * w
    new_w = jnp.where(
        jnp.abs(z_new) <= lamda1,
        jnp.zeros_like(w),
        -(z_new - jnp.sign(z_new) * lamda1)
        / ((beta + jnp.sqrt(n_new)) / lr + wd))
    return new_w, z_new, n_new


@register("signsgd_update")
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, **_):
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient)
    return w - lr * jnp.sign(g + wd * w)


@register("signum_update", num_outputs=2)
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, **_):
    """Signum (reference optimizer_op.cc:73)."""
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient)
    m = momentum * jnp.asarray(mom) - (1 - momentum) * (g + wd * w)
    new_w = (1 - lr * wd_lh) * w + lr * jnp.sign(m)
    return new_w, m


# ------------------------------------------------------------- multi-tensor

@register("multi_sum_sq", differentiable=False,
          aliases=("_contrib_multi_sum_sq",))
def _multi_sum_sq(*arrays, num_arrays=None, **_):
    """Per-array sum of squares in one call (reference
    contrib/multi_sum_sq.cc — the LARS norm pre-pass)."""
    n = num_arrays if num_arrays is not None else len(arrays)
    return jnp.stack([jnp.sum(jnp.square(jnp.asarray(a)))
                      for a in arrays[:n]])


@register("multi_lars", differentiable=False,
          aliases=("_contrib_multi_lars",))
def _multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
                eps=1e-8, rescale_grad=1.0, **_):
    """Layer-wise adaptive LR scaling (reference contrib/multi_lars.cc)."""
    lr = jnp.asarray(lrs)
    wn = jnp.sqrt(jnp.asarray(weights_sum_sq))
    gn = jnp.sqrt(jnp.asarray(grads_sum_sq)) * rescale_grad
    wd = jnp.asarray(wds)
    trust = eta * wn / (gn + wd * wn + eps)
    return jnp.where((wn > 0) & (gn > 0), lr * trust, lr)


def _multi_pairs(tensors, per):
    n = len(tensors) // per
    return [tensors[i * per:(i + 1) * per] for i in range(n)]


@register("multi_sgd_update", num_outputs=-1)
def _multi_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0,
                      clip_gradient=-1.0, num_weights=None, **_):
    """Fused sgd over N (weight, grad) pairs (reference
    optimizer_op.cc:320); returns the N updated weights."""
    outs = []
    for i, (w, g) in enumerate(_multi_pairs(args, 2)):
        outs.append(_sgd_update(w, g, lr=lrs[i], wd=wds[i],
                                rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient))
    return tuple(outs)


@register("multi_sgd_mom_update", num_outputs=-1)
def _multi_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          num_weights=None, **_):
    """Fused momentum sgd over N (weight, grad, mom) triples; returns N
    updated weights followed by N updated momenta."""
    ws, ms = [], []
    for i, (w, g, m) in enumerate(_multi_pairs(args, 3)):
        nw, nm = _sgd_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                 wd=wds[i], rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
        ws.append(nw)
        ms.append(nm)
    return tuple(ws) + tuple(ms)


@register("multi_mp_sgd_update", num_outputs=-1)
def _multi_mp_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=None, **_):
    ws, w32s = [], []
    for i, (w, g, w32) in enumerate(_multi_pairs(args, 3)):
        nw, n32 = _mp_sgd_update(w, g, w32, lr=lrs[i], wd=wds[i],
                                 rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
        ws.append(nw)
        w32s.append(n32)
    return tuple(ws) + tuple(w32s)


@register("multi_mp_sgd_mom_update", num_outputs=-1)
def _multi_mp_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0,
                             rescale_grad=1.0, clip_gradient=-1.0,
                             num_weights=None, **_):
    ws, ms, w32s = [], [], []
    for i, (w, g, m, w32) in enumerate(_multi_pairs(args, 4)):
        nw, nm, n32 = _mp_sgd_mom_update(
            w, g, m, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        ws.append(nw)
        ms.append(nm)
        w32s.append(n32)
    return tuple(ws) + tuple(ms) + tuple(w32s)


# ------------------------------------------------------------ adamw / lamb

@register("_adamw_update", aliases=("adamw_update",), num_outputs=3)
def _adamw_update(weight, grad, mean, var, rescale_grad, lr=0.001, beta1=0.9,
                  beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                  clip_gradient=-1.0, **_):
    """AdamW with decoupled weight decay (reference contrib/adamw.cc:79).
    rescale_grad is a TENSOR input (dynamic loss scale)."""
    w = jnp.asarray(weight)
    g = jnp.asarray(grad) * jnp.asarray(rescale_grad)
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * jnp.asarray(mean) + (1 - beta1) * g
    v = beta2 * jnp.asarray(var) + (1 - beta2) * g * g
    new_w = w - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * w)
    return new_w, m, v


@register("_mp_adamw_update", aliases=("mp_adamw_update",), num_outputs=4)
def _mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad,
                     lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                     eta=1.0, clip_gradient=-1.0, **_):
    w32 = jnp.asarray(weight32)
    g = (jnp.asarray(grad) * jnp.asarray(rescale_grad)).astype(jnp.float32)
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * jnp.asarray(mean) + (1 - beta1) * g
    v = beta2 * jnp.asarray(var) + (1 - beta2) * g * g
    new32 = w32 - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * w32)
    return new32.astype(jnp.asarray(weight).dtype), m, v, new32


@register("lamb_update_phase1", num_outputs=3)
def _lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                        epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0, **_):
    """LAMB phase 1: the raw update direction (reference contrib lamb op)."""
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient)
    m = beta1 * jnp.asarray(mean) + (1 - beta1) * g
    v = beta2 * jnp.asarray(var) + (1 - beta2) * g * g
    if bias_correction:
        mh = m / (1 - beta1 ** t)
        vh = v / (1 - beta2 ** t)
    else:
        mh, vh = m, v
    return mh / (jnp.sqrt(vh) + epsilon) + wd * w, m, v


@register("lamb_update_phase2")
def _lamb_update_phase2(weight, g, r1, r2, lr=0.001, lower_bound=-1.0,
                        upper_bound=-1.0, **_):
    """LAMB phase 2: trust-ratio scaled apply."""
    w = jnp.asarray(weight)
    r1v = jnp.asarray(r1)
    r2v = jnp.asarray(r2)
    if lower_bound is not None and lower_bound >= 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where((r1v > 0) & (r2v > 0), r1v / r2v,
                      jnp.ones_like(r1v))
    return w - lr * ratio * jnp.asarray(g)


@register("_multi_adamw_update", aliases=("multi_adamw_update",),
          num_outputs=-1)
def _multi_adamw_update(*args, lrs=(), wds=(), etas=(), beta1=0.9,
                        beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                        num_weights=None, **_):
    """Fused AdamW over N (weight, grad, mean, var) quadruples with ONE
    trailing rescale_grad tensor (reference contrib/adamw.cc:143 — inputs
    are 4*N+1); a NaN/Inf/0 scale skips the whole update, the dynamic-loss-
    scale contract.  Returns N weights, then N means, then N vars."""
    scale = jnp.asarray(args[-1]).reshape(())
    ok = jnp.isfinite(scale) & (scale != 0)
    safe = jnp.where(ok, scale, 1.0)
    ws, ms, vs = [], [], []
    for i, (w, g, m, v) in enumerate(_multi_pairs(args[:-1], 4)):
        w = jnp.asarray(w)
        g = jnp.asarray(g) * safe
        if clip_gradient is not None and clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        nm = beta1 * jnp.asarray(m) + (1 - beta1) * g
        nv = beta2 * jnp.asarray(v) + (1 - beta2) * g * g
        nw = w - etas[i] * (lrs[i] * nm / (jnp.sqrt(nv) + epsilon)
                            + wds[i] * w)
        ws.append(jnp.where(ok, nw, w))
        ms.append(jnp.where(ok, nm, jnp.asarray(m)))
        vs.append(jnp.where(ok, nv, jnp.asarray(v)))
    return tuple(ws) + tuple(ms) + tuple(vs)


@register("_multi_mp_adamw_update", aliases=("multi_mp_adamw_update",),
          num_outputs=-1)
def _multi_mp_adamw_update(*args, lrs=(), wds=(), etas=(), beta1=0.9,
                           beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                           num_weights=None, **_):
    """Multi-precision fused AdamW: N (weight, grad, mean, var, weight32)
    quintuples + trailing rescale_grad (reference contrib/adamw.cc)."""
    scale = jnp.asarray(args[-1]).reshape(())
    ok = jnp.isfinite(scale) & (scale != 0)
    safe = jnp.where(ok, scale, 1.0)
    ws, ms, vs, w32s = [], [], [], []
    for i, (w, g, m, v, w32) in enumerate(_multi_pairs(args[:-1], 5)):
        w32 = jnp.asarray(w32)
        g = (jnp.asarray(g) * safe).astype(jnp.float32)
        if clip_gradient is not None and clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        nm = beta1 * jnp.asarray(m) + (1 - beta1) * g
        nv = beta2 * jnp.asarray(v) + (1 - beta2) * g * g
        n32 = w32 - etas[i] * (lrs[i] * nm / (jnp.sqrt(nv) + epsilon)
                               + wds[i] * w32)
        n32 = jnp.where(ok, n32, w32)
        ws.append(n32.astype(jnp.asarray(w).dtype))
        ms.append(jnp.where(ok, nm, jnp.asarray(m)))
        vs.append(jnp.where(ok, nv, jnp.asarray(v)))
        w32s.append(n32)
    return tuple(ws) + tuple(ms) + tuple(vs) + tuple(w32s)


# -------------------------------------------------- preloaded multi-tensor
# lrs/wds arrive as TENSOR inputs (the last two), so a whole LR schedule
# sweep stays on device (reference contrib/preloaded_multi_sgd-inl.h:239).

@register("preloaded_multi_sgd_update", num_outputs=-1)
def _preloaded_multi_sgd_update(*args, rescale_grad=1.0, clip_gradient=-1.0,
                                num_weights=None, **_):
    lrs = jnp.asarray(args[-2]).ravel()
    wds = jnp.asarray(args[-1]).ravel()
    outs = []
    for i, (w, g) in enumerate(_multi_pairs(args[:-2], 2)):
        outs.append(_sgd_update(w, g, lr=lrs[i], wd=wds[i],
                                rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient))
    return tuple(outs)


@register("preloaded_multi_sgd_mom_update", num_outputs=-1)
def _preloaded_multi_sgd_mom_update(*args, momentum=0.0, rescale_grad=1.0,
                                    clip_gradient=-1.0, num_weights=None,
                                    **_):
    lrs = jnp.asarray(args[-2]).ravel()
    wds = jnp.asarray(args[-1]).ravel()
    ws, ms = [], []
    for i, (w, g, m) in enumerate(_multi_pairs(args[:-2], 3)):
        nw, nm = _sgd_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                 wd=wds[i], rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
        ws.append(nw)
        ms.append(nm)
    return tuple(ws) + tuple(ms)


@register("preloaded_multi_mp_sgd_update", num_outputs=-1)
def _preloaded_multi_mp_sgd_update(*args, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=None, **_):
    lrs = jnp.asarray(args[-2]).ravel()
    wds = jnp.asarray(args[-1]).ravel()
    ws, w32s = [], []
    for i, (w, g, w32) in enumerate(_multi_pairs(args[:-2], 3)):
        nw, n32 = _mp_sgd_update(w, g, w32, lr=lrs[i], wd=wds[i],
                                 rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
        ws.append(nw)
        w32s.append(n32)
    return tuple(ws) + tuple(w32s)


@register("preloaded_multi_mp_sgd_mom_update", num_outputs=-1)
def _preloaded_multi_mp_sgd_mom_update(*args, momentum=0.0, rescale_grad=1.0,
                                       clip_gradient=-1.0, num_weights=None,
                                       **_):
    lrs = jnp.asarray(args[-2]).ravel()
    wds = jnp.asarray(args[-1]).ravel()
    ws, ms, w32s = [], [], []
    for i, (w, g, m, w32) in enumerate(_multi_pairs(args[:-2], 4)):
        nw, nm, n32 = _mp_sgd_mom_update(
            w, g, m, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        ws.append(nw)
        ms.append(nm)
        w32s.append(n32)
    return tuple(ws) + tuple(ms) + tuple(w32s)


# --------------------------------------------------------- adagrad / sparse

@register("_sparse_adagrad_update", num_outputs=2)
def _sparse_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-5,
                           rescale_grad=1.0, clip_gradient=-1.0, **_):
    """Elementwise AdaGrad step (reference optimizer_op.cc
    _sparse_adagrad_update; history += g*g per ELEMENT).  Registry-level
    inputs are dense images; the O(rows-touched) sparse path lives in
    optimizer.AdaGrad.step_rows, which the Trainer dispatches for
    row_sparse grads."""
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient)
    h = jnp.asarray(history) + g * g
    return w - lr * g / (jnp.sqrt(h) + epsilon), h


@register("_contrib_group_adagrad_update",
          aliases=("group_adagrad_update",), num_outputs=2)
def _group_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-5,
                          rescale_grad=1.0, clip_gradient=-1.0, **_):
    """Group-AdaGrad (reference contrib/optimizer_op.cc
    _contrib_group_adagrad_update): ONE accumulator per row — history +=
    mean(g*g over the row)."""
    w = jnp.asarray(weight)
    g = _prep(grad, rescale_grad, clip_gradient)
    if g.ndim > 1:
        h = jnp.asarray(history) + jnp.mean(g * g, axis=tuple(
            range(1, g.ndim)), keepdims=True)
    else:
        h = jnp.asarray(history) + g * g
    return w - lr * g / (jnp.sqrt(h) + epsilon), h


# ------------------------------------------------------- loss-scale helpers

@register("all_finite", differentiable=False)
def _all_finite(data, init_output=True, **_):
    """1.0 iff every element is finite (reference contrib/all_finite.cc) —
    the dynamic-loss-scaling overflow check."""
    return jnp.all(jnp.isfinite(jnp.asarray(data))).astype(jnp.float32) \
        .reshape((1,))


@register("multi_all_finite", differentiable=False)
def _multi_all_finite(*arrays, num_arrays=None, init_output=True, **_):
    n = num_arrays if num_arrays is not None else len(arrays)
    ok = jnp.array(True)
    for a in arrays[:n]:
        ok = ok & jnp.all(jnp.isfinite(jnp.asarray(a)))
    return ok.astype(jnp.float32).reshape((1,))


@register("reset_arrays", differentiable=False, num_outputs=-1)
def _reset_arrays(*arrays, num_arrays=None, **_):
    """Zero N arrays in one fused call (reference contrib/reset_arrays.cc —
    gradient clearing between accumulation windows)."""
    n = num_arrays if num_arrays is not None else len(arrays)
    return tuple(jnp.zeros_like(jnp.asarray(a)) for a in arrays[:n])
