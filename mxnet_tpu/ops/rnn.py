"""Recurrent ops.

Reference: the fused stateful RNN operator ``src/operator/rnn.cc:652`` with
cuDNN path ``src/operator/rnn-inl.h:427`` — modes rnn_relu/rnn_tanh/lstm/gru,
multi-layer, bidirectional, TNC layout.

TPU-native: recurrence is a ``lax.scan`` over time — the idiomatic XLA
compiler-friendly control flow (SURVEY.md §7 stage 9).  The per-step cell is a
pair of MXU matmuls; XLA hoists the weight transposes and fuses the gate math.
Layers/directions unroll in Python (static), matching how cuDNN internally
iterates layers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _cell_step(mode, x_proj, h, c, h2h_w, h2h_b):
    """One timestep given precomputed input projection x_proj."""
    hp = jnp.dot(h, h2h_w.T) + h2h_b
    if mode == "rnn_relu":
        return jax.nn.relu(x_proj + hp), c
    if mode == "rnn_tanh":
        return jnp.tanh(x_proj + hp), c
    if mode == "lstm":
        gates = x_proj + hp
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        return o * jnp.tanh(c_new), c_new
    if mode == "gru":
        # reference/cuDNN gate order: reset, update, new
        xr, xz, xn = jnp.split(x_proj, 3, axis=-1)
        hr, hz, hn = jnp.split(hp, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        return (1.0 - z) * n + z * h, c
    raise ValueError("unknown RNN mode %r" % mode)


def rnn_layer_scan(data, i2h_w, i2h_b, h2h_w, h2h_b, h0, c0, mode,
                   reverse=False):
    """Scan one direction of one layer.  data: (T, B, I); returns
    (out (T,B,H), h_T, c_T)."""
    x = jnp.asarray(data)
    # hoist the input projection out of the scan: one big MXU matmul over
    # (T*B, I) instead of T small ones
    x_proj = jnp.dot(x, jnp.asarray(i2h_w).T) + jnp.asarray(i2h_b)
    if reverse:
        x_proj = jnp.flip(x_proj, axis=0)

    def step(carry, xp):
        h, c = carry
        h_new, c_new = _cell_step(mode, xp, h, c, jnp.asarray(h2h_w),
                                  jnp.asarray(h2h_b))
        return (h_new, c_new), h_new

    (h_t, c_t), out = lax.scan(step, (jnp.asarray(h0), jnp.asarray(c0)), x_proj)
    if reverse:
        out = jnp.flip(out, axis=0)
    return out, h_t, c_t


@register("RNN", num_outputs=3)
def _rnn(data, parameters, state, state_cell=None, state_size=None,
         num_layers=1, bidirectional=False, mode="lstm", p=0.0,
         state_outputs=True, lstm_state_clip_min=None,
         lstm_state_clip_max=None, training=False, use_sequence_length=False,
         sequence_length=None, **_):
    """Fused multi-layer RNN (reference: src/operator/rnn.cc:652).

    data: (T, B, I); parameters: flat 1-D cuDNN-layout weights; state:
    (L*D, B, H); state_cell for lstm.  Returns (out, h_n[, c_n]).
    """
    x = jnp.asarray(data)
    w = jnp.asarray(parameters)
    h0_all = jnp.asarray(state)
    c0_all = jnp.asarray(state_cell) if state_cell is not None else jnp.zeros_like(h0_all)
    T, B, I = x.shape
    if h0_all.shape[1] != B:
        # batch-agnostic initial state (symbol.zeros with an unknown batch
        # dim lowers to size 1) — lax.scan needs the carry at full batch
        h0_all = jnp.broadcast_to(h0_all, (h0_all.shape[0], B,
                                           h0_all.shape[2]))
    if c0_all.shape[1] != B:
        c0_all = jnp.broadcast_to(c0_all, (c0_all.shape[0], B,
                                           c0_all.shape[2]))
    H = int(state_size)
    L = int(num_layers)
    D = 2 if bidirectional else 1
    ngates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]

    # slice the flat parameter blob: the layout lives in ONE place
    # (mxnet_tpu/rnn/_fused_layout.py, the cuDNN order of reference
    # rnn-inl.h GetRnnParamSize) shared with pack/unpack and the
    # FusedRNN initializer
    from ..rnn._fused_layout import fused_rnn_group_slices
    gb = ngates * H
    weights = [[None] * D for _ in range(L)]
    groups = fused_rnn_group_slices(I, H, L, mode, bool(bidirectional))
    for grp, (iw_off, iw_shape, hw_off, hw_shape, ib_off, hb_off) \
            in enumerate(groups):
        layer, d = divmod(grp, D)
        weights[layer][d] = [
            w[iw_off:iw_off + gb * iw_shape[1]].reshape(iw_shape),
            w[hw_off:hw_off + gb * H].reshape(hw_shape),
            w[ib_off:ib_off + gb],
            w[hb_off:hb_off + gb],
        ]

    out = x
    h_n = []
    c_n = []
    for layer in range(L):
        layer_outs = []
        for d in range(D):
            i2h, h2h, i2h_b, h2h_b = weights[layer][d]
            idx = layer * D + d
            o, h_t, c_t = rnn_layer_scan(out, i2h, i2h_b, h2h, h2h_b,
                                         h0_all[idx], c0_all[idx], mode,
                                         reverse=(d == 1))
            if mode == "lstm" and lstm_state_clip_min is not None:
                c_t = jnp.clip(c_t, lstm_state_clip_min, lstm_state_clip_max)
            layer_outs.append(o)
            h_n.append(h_t)
            c_n.append(c_t)
        out = jnp.concatenate(layer_outs, axis=-1) if D == 2 else layer_outs[0]
        if p > 0.0 and training and layer != L - 1:
            from ..random import next_key
            mask = jax.random.bernoulli(next_key(), 1.0 - p, out.shape)
            out = jnp.where(mask, out / (1.0 - p), 0.0).astype(out.dtype)

    h_n = jnp.stack(h_n)
    if mode == "lstm":
        return out, h_n, jnp.stack(c_n)
    return out, h_n, jnp.zeros_like(h_n)
