"""Control-flow operators: foreach, while_loop, cond.

Reference: ``src/operator/control_flow.cc:1089-1255`` — `_foreach`,
`_while_loop`, `_cond` run a sub-graph per iteration with state threading;
Python frontend ``python/mxnet/ndarray/contrib.py`` (foreach :216,
while_loop :340, cond :480).

TPU-native: under a trace (hybridized/jit) these lower to ``lax.scan`` /
``lax.while_loop`` / ``lax.cond`` — XLA's native loops.  In eager recording
mode they run as Python loops so the autograd tape sees each step (the
reference's imperative path does the same graph-per-step execution).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import _tape

__all__ = ["foreach", "while_loop", "cond"]


def _to_nd(x):
    from ..ndarray.ndarray import NDArray, _wrap
    if isinstance(x, (list, tuple)):
        return type(x)(_to_nd(i) for i in x)
    if isinstance(x, NDArray):
        return x
    return _wrap(jnp.asarray(x))


def _to_val(x):
    from ..ndarray.ndarray import NDArray
    if isinstance(x, (list, tuple)):
        return type(x)(_to_val(i) for i in x)
    if isinstance(x, NDArray):
        return x._data
    return x


def _eager_like():
    """True when we should run python-level loops (tape active)."""
    return _tape.is_recording()


def foreach(body, data, init_states):
    """Run body over the leading axis of data, threading states
    (reference: contrib.py foreach :216).
    """
    from ..ndarray.ndarray import NDArray, _wrap

    single_data = isinstance(data, NDArray)
    single_state = isinstance(init_states, NDArray)
    datas = [data] if single_data else list(data)
    states = [init_states] if single_state else list(init_states)

    if _eager_like():
        outputs = []
        n = datas[0].shape[0]
        for i in range(n):
            eles = [d[i] for d in datas]
            eles = eles[0] if single_data else eles
            outs, states_out = body(eles, states[0] if single_state else states)
            states = [states_out] if single_state else list(states_out)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            outputs.append(outs)
        from ..ops.registry import invoke
        stacked = [invoke("stack", *[o[j] for o in outputs], axis=0)
                   for j in range(len(outputs[0]))]
        out = stacked[0] if len(stacked) == 1 else stacked
        final_states = states[0] if single_state else states
        return out, final_states

    # traced path: lax.scan over jax values
    def scan_body(carry, xs):
        carry_nd = [_wrap(c) for c in carry]
        xs_nd = [_wrap(x) for x in xs]
        outs, new_states = body(xs_nd[0] if single_data else xs_nd,
                                carry_nd[0] if single_state else carry_nd)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        if isinstance(new_states, NDArray):
            new_states = [new_states]
        return tuple(_to_val(s) for s in new_states), tuple(_to_val(o) for o in outs)

    carry0 = tuple(_to_val(s) for s in states)
    xs_vals = tuple(_to_val(d) for d in datas)
    final_carry, outs = lax.scan(scan_body, carry0, xs_vals)
    outs_nd = [_wrap(o) for o in outs]
    states_nd = [_wrap(c) for c in final_carry]
    out = outs_nd[0] if len(outs_nd) == 1 else outs_nd
    final_states = states_nd[0] if single_state else states_nd
    return out, final_states


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """while_loop with max_iterations bound
    (reference: contrib.py while_loop :340).

    Returns (outputs, final_loop_vars).  Like the reference, outputs are
    stacked per-step results padded to max_iterations.
    """
    from ..ndarray.ndarray import NDArray, _wrap

    single_var = isinstance(loop_vars, NDArray)
    lvars = [loop_vars] if single_var else list(loop_vars)
    if max_iterations is None:
        raise ValueError("max_iterations should be specified")

    if _eager_like():
        steps = 0
        outputs = []
        while steps < max_iterations and bool(
                cond_fn(*lvars).asscalar() if isinstance(
                    cond_fn(*lvars), NDArray) else cond_fn(*lvars)):
            step_out, lvars = func(*lvars)
            if not isinstance(step_out, (list, tuple)):
                step_out = [step_out]
            lvars = [lvars] if isinstance(lvars, NDArray) else list(lvars)
            outputs.append(step_out)
            steps += 1
        from ..ops.registry import invoke
        if outputs:
            stacked = [invoke("stack", *[o[j] for o in outputs], axis=0)
                       for j in range(len(outputs[0]))]
        else:
            stacked = []
        out = stacked[0] if len(stacked) == 1 else stacked
        return out, (lvars[0] if single_var else lvars)

    # traced: fixed-trip scan with predicate masking (keeps shapes static,
    # the XLA-friendly formulation of a bounded while)
    def scan_body(carry, _):
        alive, vals = carry
        vals_nd = [_wrap(v) for v in vals]
        pred = cond_fn(*vals_nd)
        pred = pred._data if isinstance(pred, NDArray) else jnp.asarray(pred)
        alive_now = jnp.logical_and(alive, pred.astype(bool).reshape(()))
        step_out, new_vals = func(*vals_nd)
        if not isinstance(step_out, (list, tuple)):
            step_out = [step_out]
        if isinstance(new_vals, NDArray):
            new_vals = [new_vals]
        new_vals = tuple(
            jnp.where(alive_now, _to_val(nv), v)
            for nv, v in zip(new_vals, vals))
        outs = tuple(_to_val(o) for o in step_out)
        return (alive_now, new_vals), outs

    carry0 = (jnp.asarray(True), tuple(_to_val(v) for v in lvars))
    (alive, final_vals), outs = lax.scan(scan_body, carry0, None,
                                         length=int(max_iterations))
    outs_nd = [_wrap(o) for o in outs]
    vars_nd = [_wrap(v) for v in final_vals]
    out = outs_nd[0] if len(outs_nd) == 1 else outs_nd
    return out, (vars_nd[0] if single_var else vars_nd)


def cond(pred, then_func, else_func):
    """If-then-else (reference: contrib.py cond :480)."""
    from ..ndarray.ndarray import NDArray, _wrap

    if _eager_like():
        p = pred.asscalar() if isinstance(pred, NDArray) else pred
        return then_func() if p else else_func()

    pv = pred._data if isinstance(pred, NDArray) else jnp.asarray(pred)

    def _then(_):
        out = then_func()
        return tuple(_to_val(o) for o in (out if isinstance(out, (list, tuple)) else [out]))

    def _else(_):
        out = else_func()
        return tuple(_to_val(o) for o in (out if isinstance(out, (list, tuple)) else [out]))

    outs = lax.cond(pv.astype(bool).reshape(()), _then, _else, operand=None)
    outs_nd = [_wrap(o) for o in outs]
    return outs_nd[0] if len(outs_nd) == 1 else outs_nd
