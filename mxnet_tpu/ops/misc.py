"""Legacy-name parity ops: scalar arithmetic, v1 aliases, AMP casts, misc.

Closes the operator long tail identified in the round-3 audit.  Three kinds
of entries:

* real ops the registry lacked (add_n, amp_cast, _histogram, _slice_assign,
  _split_v2, _square_sum, ...) — implemented here with jnp lowerings;
* scalar-operand forms (reference src/operator/tensor/
  elemwise_binary_scalar_op_basic.cc) — in this framework scalars embed as
  traced constants, so these exist for script/graph parity and lower to the
  same XLA ops;
* pure aliases the reference keeps for backward compatibility
  (src/operator/tensor/elemwise_binary_broadcast_op_basic.cc add_alias
  chains, the CamelCase v0.x names) — registered as registry aliases of the
  canonical ops.

The generated audit (docs/OP_AUDIT.md, tools/op_audit.py) enumerates every
reference symbol against this registry.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import register, get, _REGISTRY


# --------------------------------------------------------------- scalar ops
# (reference elemwise_binary_scalar_op_basic.cc / _extended.cc / _logic.cc)

def _scalar_table():
    return {
        "_plus_scalar": lambda a, s: a + s,
        "_minus_scalar": lambda a, s: a - s,
        "_rminus_scalar": lambda a, s: s - a,
        "_mul_scalar": lambda a, s: a * s,
        "_div_scalar": lambda a, s: a / s,
        "_rdiv_scalar": lambda a, s: s / a,
        "_mod_scalar": lambda a, s: jnp.mod(a, s),
        "_rmod_scalar": lambda a, s: jnp.mod(jnp.full_like(a, s), a),
        "_power_scalar": lambda a, s: jnp.power(a, s),
        "_rpower_scalar": lambda a, s: jnp.power(jnp.full_like(a, s), a),
        "_maximum_scalar": lambda a, s: jnp.maximum(a, s),
        "_minimum_scalar": lambda a, s: jnp.minimum(a, s),
        "_hypot_scalar": lambda a, s: jnp.hypot(a, jnp.full_like(a, s)),
        "_equal_scalar": lambda a, s: (a == s).astype(a.dtype),
        "_not_equal_scalar": lambda a, s: (a != s).astype(a.dtype),
        "_greater_scalar": lambda a, s: (a > s).astype(a.dtype),
        "_greater_equal_scalar": lambda a, s: (a >= s).astype(a.dtype),
        "_lesser_scalar": lambda a, s: (a < s).astype(a.dtype),
        "_lesser_equal_scalar": lambda a, s: (a <= s).astype(a.dtype),
        "_logical_and_scalar": lambda a, s:
            ((a != 0) & bool(s)).astype(a.dtype),
        "_logical_or_scalar": lambda a, s:
            ((a != 0) | bool(s)).astype(a.dtype),
        "_logical_xor_scalar": lambda a, s:
            ((a != 0) ^ bool(s)).astype(a.dtype),
        "_scatter_plus_scalar": lambda a, s: a + s,
        "_scatter_minus_scalar": lambda a, s: a - s,
    }


_CAMEL_OF_SCALAR = {
    "_plus_scalar": "_PlusScalar", "_minus_scalar": "_MinusScalar",
    "_rminus_scalar": "_RMinusScalar", "_mul_scalar": "_MulScalar",
    "_div_scalar": "_DivScalar", "_rdiv_scalar": "_RDivScalar",
    "_mod_scalar": "_ModScalar", "_rmod_scalar": "_RModScalar",
    "_power_scalar": "_PowerScalar", "_rpower_scalar": "_RPowerScalar",
    "_maximum_scalar": "_MaximumScalar", "_minimum_scalar": "_MinimumScalar",
    "_hypot_scalar": "_HypotScalar", "_equal_scalar": "_EqualScalar",
    "_not_equal_scalar": "_NotEqualScalar",
    "_greater_scalar": "_GreaterScalar",
    "_greater_equal_scalar": "_GreaterEqualScalar",
    "_lesser_scalar": "_LesserScalar",
    "_lesser_equal_scalar": "_LesserEqualScalar",
    "_logical_and_scalar": "_LogicalAndScalar",
    "_logical_or_scalar": "_LogicalOrScalar",
    "_logical_xor_scalar": "_LogicalXorScalar",
}


def _register_scalar_ops():
    nondiff = {"_equal_scalar", "_not_equal_scalar", "_greater_scalar",
               "_greater_equal_scalar", "_lesser_scalar",
               "_lesser_equal_scalar", "_logical_and_scalar",
               "_logical_or_scalar", "_logical_xor_scalar"}
    for name, fn in _scalar_table().items():
        aliases = ()
        if name in _CAMEL_OF_SCALAR:
            aliases = (_CAMEL_OF_SCALAR[name],)

        def impl(data, scalar=0.0, _fn=fn, **_):
            return _fn(jnp.asarray(data), scalar)

        register(name, differentiable=name not in nondiff,
                 aliases=aliases)(impl)


_register_scalar_ops()


# ------------------------------------------------------------- legacy alias
# reference keeps the v0.x CamelCase names working (add_alias chains)

_LEGACY_ALIASES = {
    # binary broadcast family
    "_Plus": "broadcast_add", "_add": "broadcast_add",
    "_plus": "broadcast_add", "_grad_add": "broadcast_add",
    "broadcast_plus": "broadcast_add",
    "_Minus": "broadcast_sub", "_sub": "broadcast_sub",
    "_minus": "broadcast_sub", "broadcast_minus": "broadcast_sub",
    "_Mul": "broadcast_mul", "_mul": "broadcast_mul",
    "_Div": "broadcast_div", "_div": "broadcast_div",
    "_Mod": "broadcast_mod", "_mod": "broadcast_mod",
    "_Power": "broadcast_power",
    "_Maximum": "broadcast_maximum", "_maximum": "broadcast_maximum",
    "_Minimum": "broadcast_minimum", "_minimum": "broadcast_minimum",
    "_Hypot": "broadcast_hypot", "_hypot": "broadcast_hypot",
    "_Equal": "broadcast_equal", "equal": "broadcast_equal",
    "_Not_Equal": "broadcast_not_equal", "not_equal": "broadcast_not_equal",
    "_Greater": "broadcast_greater", "greater": "broadcast_greater",
    "_Greater_Equal": "broadcast_greater_equal",
    "greater_equal": "broadcast_greater_equal",
    "_Lesser": "broadcast_lesser", "less": "broadcast_lesser",
    "_Lesser_Equal": "broadcast_lesser_equal",
    "less_equal": "broadcast_lesser_equal",
    "_Logical_And": "broadcast_logical_and",
    "_logical_and": "broadcast_logical_and",
    "_Logical_Or": "broadcast_logical_or",
    "_logical_or": "broadcast_logical_or",
    "_Logical_Xor": "broadcast_logical_xor",
    "_logical_xor": "broadcast_logical_xor",
    "broadcast_axes": "broadcast_axis",
    # misc canonical-name aliases
    "choose_element_0index": "pick",
    "_shuffle": "shuffle",
    "_ravel_multi_index": "ravel_multi_index",
    "_linalg_gemm2": "linalg_gemm2", "_linalg_potrf": "linalg_potrf",
    "_linalg_syrk": "linalg_syrk", "_linalg_trsm": "linalg_trsm",
    "SliceChannel": "split",
    "Softmax": "softmax",
    # v1 legacy layer ops: forward-compatible lowering to the modern ops
    # (reference keeps *_v1 kernels for old graphs; numerics match for the
    # supported layouts)
    "BatchNorm_v1": "BatchNorm",
    "Convolution_v1": "Convolution",
    "Pooling_v1": "Pooling",
}


def _register_aliases():
    for alias, target in _LEGACY_ALIASES.items():
        if alias in _REGISTRY:
            continue
        try:
            _REGISTRY[alias] = get(target)
        except AttributeError:
            raise RuntimeError(
                "legacy alias %r -> missing target %r" % (alias, target))


# ---------------------------------------------------------------- real ops

@register("add_n", aliases=("ElementWiseSum",))
def _add_n(*arrays, num_args=None, **_):
    """Sum of N tensors in one op (reference
    src/operator/tensor/elemwise_sum.cc; alias ElementWiseSum)."""
    n = num_args if num_args is not None else len(arrays)
    out = jnp.asarray(arrays[0])
    for a in arrays[1:n]:
        out = out + jnp.asarray(a)
    return out


@register("amp_cast")
def _amp_cast(data, dtype="float32", **_):
    """AMP-inserted cast (reference src/operator/tensor/amp_cast.cc)."""
    from ..base import dtype_np
    return jnp.asarray(data).astype(dtype_np(dtype))


@register("amp_multicast", num_outputs=-1)
def _amp_multicast(*arrays, num_outputs=None, cast_narrow=False, **_):
    """Cast N tensors to a common width (reference amp_cast.cc
    amp_multicast): widest dtype wins, or the narrowest with cast_narrow."""
    n = num_outputs if num_outputs is not None else len(arrays)
    arrs = [jnp.asarray(a) for a in arrays[:n]]

    def width(d):
        bits = jnp.finfo(d).bits if jnp.issubdtype(d, jnp.floating) else 0
        return -bits if cast_narrow else bits

    target = max((a.dtype for a in arrs), key=width)
    return tuple(a.astype(target) for a in arrs)


@register("cast_storage", differentiable=True)
def _cast_storage(data, stype="default", **_):
    """Storage-type cast (reference src/operator/tensor/cast_storage.cc).
    Arrays are dense jax buffers at the registry level; the sparse
    *containers* (ndarray/sparse.py) carry stype — so the value is the
    identity and the NDArray layer re-wraps by stype."""
    return jnp.asarray(data)


@register("_histogram", aliases=("histogram",), differentiable=False,
          num_outputs=2)
def _histogram(data, bins=None, bin_cnt=10, range=None, **_):
    """Histogram (reference src/operator/tensor/histogram.cc).  With a bins
    tensor the edges are explicit; otherwise bin_cnt uniform bins over
    range (default: data min/max)."""
    d = jnp.asarray(data).ravel()
    if bins is not None and getattr(bins, "ndim", 0) > 0:
        edges = jnp.asarray(bins)
        counts = jnp.histogram(d, bins=edges)[0]
        return counts, edges
    lo, hi = (range if range is not None
              else (jnp.min(d), jnp.max(d)))
    counts, edges = jnp.histogram(d, bins=int(bin_cnt), range=(lo, hi))
    return counts, edges


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs, **_):
    """Identity on lhs; rhs only contributes graph attrs (reference
    elemwise_unary_op_basic.cc — the sparse-grad plumbing node)."""
    return jnp.asarray(lhs)


@register("_zeros_without_dtype", differentiable=False)
def _zeros_without_dtype(shape=(), ctx=None, **_):
    return jnp.zeros(shape, jnp.float32)


@register("_rnn_param_concat")
def _rnn_param_concat(*arrays, dim=0, num_args=None, **_):
    """Concatenate RNN parameter blocks (reference rnn.cc
    _rnn_param_concat): plain concat whose gradient splits back."""
    n = num_args if num_args is not None else len(arrays)
    return jnp.concatenate([jnp.asarray(a) for a in arrays[:n]], axis=dim)


@register("_split_v2", aliases=("split_v2",), num_outputs=-1)
def _split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0, **_):
    """split with either section counts or explicit indices (reference
    src/operator/tensor/matrix_op.cc _split_v2)."""
    d = jnp.asarray(data)
    if sections and sections > 0:
        parts = jnp.split(d, sections, axis=axis)
    else:
        parts = jnp.split(d, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("_square_sum", aliases=("square_sum",))
def _square_sum(data, axis=None, keepdims=False, **_):
    """sum(x**2) fused (reference src/operator/tensor/square_sum.cc — the
    row_sparse-aware norm helper)."""
    d = jnp.asarray(data)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sum(d * d, axis=ax, keepdims=keepdims)


@register("_sparse_retain", aliases=("sparse_retain",))
def _sparse_retain(data, indices, **_):
    """Dense-image semantics of sparse_retain (reference
    sparse_retain-inl.h): zero out every row NOT in indices.  The
    container-level O(rows) path is ndarray.sparse.retain."""
    d = jnp.asarray(data)
    idx = jnp.asarray(indices).astype(jnp.int32).ravel()
    mask = jnp.zeros((d.shape[0],), bool).at[idx].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (d.ndim - 1)), d, 0)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices, shape=None, **_):
    """Scatter-write rhs into lhs at indices (reference matrix_op.cc
    _scatter_set_nd — the backward of gather_nd with overwrite)."""
    idx = jnp.asarray(indices).astype(jnp.int32)
    return jnp.asarray(lhs).at[tuple(idx[i] for i in
                                     range(idx.shape[0]))].set(
        jnp.asarray(rhs))


@register("_scatter_elemwise_div")
def _scatter_elemwise_div(lhs, rhs, **_):
    """Elementwise div writing through a sparse lhs pattern (reference
    elemwise_binary_op_basic.cc _scatter_elemwise_div); dense image: plain
    division."""
    return jnp.asarray(lhs) / jnp.asarray(rhs)


@register("_slice_assign", aliases=("_crop_assign",))
def _slice_assign(lhs, rhs, begin=(), end=(), step=(), **_):
    """Functional slice-assignment (reference matrix_op.cc _slice_assign;
    x[a:b] = y lowers here) — .at[].set is the XLA-native form."""
    d = jnp.asarray(lhs)
    sl = _make_slices(d, begin, end, step)
    return d.at[sl].set(jnp.asarray(rhs))


@register("_slice_assign_scalar", aliases=("_crop_assign_scalar",))
def _slice_assign_scalar(data, begin=(), end=(), step=(), scalar=0.0, **_):
    d = jnp.asarray(data)
    sl = _make_slices(d, begin, end, step)
    return d.at[sl].set(scalar)


def _make_slices(d, begin, end, step):
    out = []
    step = step or (None,) * len(begin)
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) else None
        out.append(slice(b, e, s if s not in (0,) else None))
    return tuple(out)


@register("fix")
def _fix(data, **_):
    """Round toward zero (reference elemwise_unary_op_basic.cc fix)."""
    return jnp.trunc(jnp.asarray(data))


@register("_unravel_index", aliases=("unravel_index",),
          differentiable=False)
def _unravel_index(data, shape=None, **_):
    idx = jnp.asarray(data).astype(jnp.int32)  # x64 stays off on TPU
    coords = jnp.unravel_index(idx.ravel(), shape)
    return jnp.stack(coords).reshape((len(shape),) + idx.shape)


@register("_sample_unique_zipfian", differentiable=False, num_outputs=2)
def _sample_unique_zipfian(range_max=None, shape=None, **_):
    """Unique log-uniform (zipfian) candidate sampling (reference
    contrib/unique_zipfian_op.cc, used by sampled-softmax training).
    Eager host-side sampling: candidate sets are data-pipeline inputs, not
    jit-internal values."""
    n = int(_np.prod(shape)) if shape else 1
    out = set()
    log_rm = _np.log(range_max)
    trials = 0
    rng = _np.random
    while len(out) < n:
        draw = _np.exp(rng.uniform(0, log_rm, size=n * 2)) \
            .astype(_np.int64)
        draw = draw[draw < range_max]
        trials += len(draw)
        for v in draw:
            out.add(int(v))
            if len(out) == n:
                break
    samples = _np.asarray(sorted(out)[:n], _np.int32).reshape(shape)
    # expected counts under the zipfian proposal for each sample
    probs = _np.log1p(1.0 / (samples + 1)) / log_rm
    counts = probs * trials
    return jnp.asarray(samples), jnp.asarray(counts)


@register("Crop", num_outputs=1)
def _crop_legacy(*args, offset=(0, 0), h_w=(0, 0), center_crop=False, **_):
    """v0.x Crop layer (reference src/operator/crop.cc): crop args[0] to
    h_w (or to args[1]'s spatial shape) at offset / center."""
    d = jnp.asarray(args[0])
    if len(args) > 1:
        ref_a = jnp.asarray(args[1])
        th, tw = ref_a.shape[2], ref_a.shape[3]
    else:
        th, tw = h_w
    H, W = d.shape[2], d.shape[3]
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = offset
    return d[:, :, y0:y0 + th, x0:x0 + tw]


@register("IdentityAttachKLSparseReg")
def _identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                   penalty=0.001, momentum=0.9, **_):
    """Identity forward with a KL sparsity penalty attached in training
    (reference src/operator/identity_attach_KL_sparse_reg.cc).  The penalty
    is a regularization term users add to the loss in this framework
    (functional design: losses compose instead of ops mutating gradients);
    forward semantics (identity) are exact."""
    return jnp.asarray(data)


# ------------------------------------------------------------- image block
# (reference src/operator/image/image_random.cc + resize.cc / crop.cc)

@register("_image_to_tensor", aliases=("image_to_tensor",))
def _image_to_tensor(data, **_):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference
    image/image_random.cc ToTensor)."""
    d = jnp.asarray(data).astype(jnp.float32) / 255.0
    if d.ndim == 3:
        return jnp.transpose(d, (2, 0, 1))
    return jnp.transpose(d, (0, 3, 1, 2))


@register("_image_normalize", aliases=("image_normalize",))
def _image_normalize(data, mean=(0.0,), std=(1.0,), **_):
    d = jnp.asarray(data)
    m = jnp.asarray(mean, d.dtype).reshape((-1, 1, 1))
    s = jnp.asarray(std, d.dtype).reshape((-1, 1, 1))
    return (d - m) / s


@register("_image_resize", aliases=("image_resize",))
def _image_resize(data, size=(), keep_ratio=False, interp=1, **_):
    """Resize HWC or NHWC images (reference image/resize.cc) via
    jax.image.resize — bilinear for interp=1, nearest otherwise."""
    d = jnp.asarray(data)
    if isinstance(size, int):
        size = (size, size)
    elif len(size) == 1:
        size = (size[0], size[0])
    w, h = size  # reference order: (w, h)
    method = "bilinear" if interp == 1 else "nearest"
    if d.ndim == 3:
        return jax.image.resize(d, (h, w, d.shape[2]), method=method)
    return jax.image.resize(d, (d.shape[0], h, w, d.shape[3]),
                            method=method)


@register("_image_crop", aliases=("image_crop",))
def _image_crop(data, x=0, y=0, width=1, height=1, **_):
    d = jnp.asarray(data)
    if d.ndim == 3:
        return d[y:y + height, x:x + width, :]
    return d[:, y:y + height, x:x + width, :]


_register_aliases()


# ------------------------------------------------- STE / gradient-shaping
# jax.custom_vjp carries the nonstandard gradients; apply_op's jax.vjp
# taping composes with it transparently.

@jax.custom_vjp
def _round_ste_fn(x):
    return jnp.round(x)


_round_ste_fn.defvjp(lambda x: (jnp.round(x), None),
                     lambda _, g: (g,))


@register("_contrib_round_ste", aliases=("round_ste",))
def _round_ste(data, **_):
    """Round with straight-through gradient (reference
    contrib/stes_op.cc RoundSTE — quantization-aware training)."""
    return _round_ste_fn(jnp.asarray(data))


@jax.custom_vjp
def _sign_ste_fn(x):
    return jnp.sign(x)


_sign_ste_fn.defvjp(lambda x: (jnp.sign(x), None),
                    lambda _, g: (g,))


@register("_contrib_sign_ste", aliases=("sign_ste",))
def _sign_ste(data, **_):
    """Sign with straight-through gradient (reference contrib/stes_op.cc
    SignSTE)."""
    return _sign_ste_fn(jnp.asarray(data))


def _make_gradmult(scalar):
    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None), lambda _, g: (g * scalar,))
    return f


@register("_contrib_gradientmultiplier", aliases=("gradientmultiplier",))
def _gradientmultiplier(data, scalar=1.0, **_):
    """Identity forward, gradient scaled by `scalar` (reference
    contrib/gradient_multiplier_op.cc — gradient-reversal layers use
    scalar=-1)."""
    return _make_gradmult(scalar)(jnp.asarray(data))


@register("_contrib_div_sqrt_dim", aliases=("div_sqrt_dim",))
def _div_sqrt_dim(data, **_):
    """x / sqrt(last_dim) (reference contrib/transformer.cc
    _contrib_div_sqrt_dim — attention-score scaling)."""
    d = jnp.asarray(data)
    return d / jnp.sqrt(jnp.asarray(d.shape[-1], d.dtype))
