"""Operator registry + lowering library (see registry.py for the design)."""
from .registry import Operator, apply_op, get, invoke, list_ops, register
from . import tensor  # noqa: F401  (registers tensor ops)
from . import nn  # noqa: F401  (registers nn ops)
from . import rnn  # noqa: F401  (registers recurrent ops)
from . import control_flow  # noqa: F401  (registers foreach/while_loop/cond)
from . import contrib  # noqa: F401  (registers bbox/NMS/ROI detection ops)
from . import linalg  # noqa: F401  (registers _linalg_* ops)
from . import random_ops  # noqa: F401  (registers _random_*/sample_* ops)
from . import spatial  # noqa: F401  (registers sampler/warp/deformable ops)
from . import signal  # noqa: F401  (registers fft/ifft)
from . import optim_ops  # noqa: F401  (registers *_update optimizer ops)
from . import misc  # noqa: F401  (registers scalar/legacy-alias/misc ops)
from . import contrib_extra  # noqa: F401  (quantized/proposal/psroi/graph)
from . import pallas_kernels  # noqa: F401  (registers pallas_* kernels)

__all__ = ["Operator", "apply_op", "get", "invoke", "list_ops", "register"]
