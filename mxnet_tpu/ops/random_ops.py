"""Registry-level random sampling ops.

Reference: src/operator/random/sample_op.cc (_random_uniform/_normal/_gamma/
_exponential/_poisson/_negative_binomial/_generalized_negative_binomial/
_randint + *_like variants), multisample_op.cc (sample_* taking per-row
distribution parameter tensors) and sample_multinomial_op.cc.

TPU-native: every sampler draws from the framework PRNG stream
(mxnet_tpu/random.py — jax.random splittable keys behind mx.random.seed,
replacing the reference's per-device mt19937/Philox state,
include/mxnet/random_generator.h).  Samplers are non-differentiable
registry ops, matching the reference's FGradient-less registration.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _key():
    from ..random import next_key
    return next_key()


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _f(dtype):
    return jnp.float32 if dtype in (None, "None") else jnp.dtype(dtype)


# ------------------------------------------------- fixed-parameter samplers

@register("_random_uniform", differentiable=False,
          aliases=("random_uniform", "uniform"))
def _random_uniform(low=0.0, high=1.0, shape=None, dtype=None, **_):
    return jax.random.uniform(_key(), _shape(shape), _f(dtype), low, high)


@register("_random_normal", differentiable=False,
          aliases=("random_normal", "normal"))
def _random_normal(loc=0.0, scale=1.0, shape=None, dtype=None, **_):
    return loc + scale * jax.random.normal(_key(), _shape(shape), _f(dtype))


@register("_random_gamma", differentiable=False, aliases=("random_gamma",))
def _random_gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, **_):
    return beta * jax.random.gamma(_key(), alpha, _shape(shape), _f(dtype))


@register("_random_exponential", differentiable=False,
          aliases=("random_exponential",))
def _random_exponential(lam=1.0, shape=None, dtype=None, **_):
    return jax.random.exponential(_key(), _shape(shape), _f(dtype)) / lam


@register("_random_poisson", differentiable=False,
          aliases=("random_poisson",))
def _random_poisson(lam=1.0, shape=None, dtype=None, **_):
    out = jax.random.poisson(_key(), lam, _shape(shape))
    return out.astype(_f(dtype))


def _neg_binomial(key, k, p, shape, dtype):
    """NB(k, p) = Poisson(Gamma(k, (1-p)/p)) — the standard mixture
    construction (the reference samples it the same way on GPU)."""
    k1, k2 = jax.random.split(key)
    rate = jax.random.gamma(k1, k, shape) * ((1.0 - p) / p)
    return jax.random.poisson(k2, rate, shape).astype(dtype)


@register("_random_negative_binomial", differentiable=False,
          aliases=("random_negative_binomial",))
def _random_negative_binomial(k=1, p=1.0, shape=None, dtype=None, **_):
    return _neg_binomial(_key(), float(k), float(p), _shape(shape), _f(dtype))


@register("_random_generalized_negative_binomial", differentiable=False,
          aliases=("random_generalized_negative_binomial",))
def _random_gen_neg_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None, **_):
    """GNB(mu, alpha) = Poisson(Gamma(1/alpha, mu*alpha))."""
    k1, k2 = jax.random.split(_key())
    rate = jax.random.gamma(k1, 1.0 / alpha, _shape(shape)) * (mu * alpha)
    return jax.random.poisson(k2, rate, _shape(shape)).astype(_f(dtype))


@register("_random_randint", differentiable=False, aliases=("random_randint",
                                                            "randint"))
def _random_randint(low=0, high=1, shape=None, dtype=None, **_):
    dt = jnp.int32 if dtype in (None, "None") else jnp.dtype(dtype)
    return jax.random.randint(_key(), _shape(shape), int(low), int(high), dt)


# ------------------------------------------------------------ like samplers

@register("_random_uniform_like", differentiable=False,
          aliases=("uniform_like",))
def _random_uniform_like(data, low=0.0, high=1.0, **_):
    d = jnp.asarray(data)
    return jax.random.uniform(_key(), d.shape, d.dtype, low, high)


@register("_random_normal_like", differentiable=False,
          aliases=("normal_like",))
def _random_normal_like(data, loc=0.0, scale=1.0, **_):
    d = jnp.asarray(data)
    return loc + scale * jax.random.normal(_key(), d.shape, d.dtype)


@register("_random_gamma_like", differentiable=False)
def _random_gamma_like(data, alpha=1.0, beta=1.0, **_):
    d = jnp.asarray(data)
    return beta * jax.random.gamma(_key(), alpha, d.shape, d.dtype)


@register("_random_exponential_like", differentiable=False)
def _random_exponential_like(data, lam=1.0, **_):
    d = jnp.asarray(data)
    return jax.random.exponential(_key(), d.shape, d.dtype) / lam


@register("_random_poisson_like", differentiable=False)
def _random_poisson_like(data, lam=1.0, **_):
    d = jnp.asarray(data)
    return jax.random.poisson(_key(), lam, d.shape).astype(d.dtype)


@register("_random_negative_binomial_like", differentiable=False)
def _random_negative_binomial_like(data, k=1, p=1.0, **_):
    d = jnp.asarray(data)
    return _neg_binomial(_key(), float(k), float(p), d.shape, d.dtype)


@register("_random_generalized_negative_binomial_like", differentiable=False)
def _random_gen_neg_binomial_like(data, mu=1.0, alpha=1.0, **_):
    d = jnp.asarray(data)
    k1, k2 = jax.random.split(_key())
    rate = jax.random.gamma(k1, 1.0 / alpha, d.shape) * (mu * alpha)
    return jax.random.poisson(k2, rate, d.shape).astype(d.dtype)


# ------------------------------------- per-row parameter tensors (sample_*)

def _broadcast_draw(params, shape, draw):
    """Common frame of the reference's multisample ops
    (src/operator/random/multisample_op.cc): each element of the parameter
    tensor yields `shape` draws appended to its own dims."""
    extra = _shape(shape)
    ps = [jnp.asarray(p) for p in params]
    out_shape = ps[0].shape + extra
    ps = [p.reshape(p.shape + (1,) * len(extra)) for p in ps]
    return draw(out_shape, *ps)


@register("sample_uniform", differentiable=False, aliases=("_sample_uniform",))
def _sample_uniform(low, high, shape=None, dtype=None, **_):
    return _broadcast_draw(
        (low, high), shape,
        lambda s, lo, hi: lo + (hi - lo) *
        jax.random.uniform(_key(), s, _f(dtype)))


@register("sample_normal", differentiable=False, aliases=("_sample_normal",))
def _sample_normal(mu, sigma, shape=None, dtype=None, **_):
    return _broadcast_draw(
        (mu, sigma), shape,
        lambda s, m, sg: m + sg * jax.random.normal(_key(), s, _f(dtype)))


@register("sample_gamma", differentiable=False, aliases=("_sample_gamma",))
def _sample_gamma(alpha, beta, shape=None, dtype=None, **_):
    return _broadcast_draw(
        (alpha, beta), shape,
        lambda s, a, b: b * jax.random.gamma(_key(), a, s, _f(dtype)))


@register("sample_exponential", differentiable=False,
          aliases=("_sample_exponential",))
def _sample_exponential(lam, shape=None, dtype=None, **_):
    return _broadcast_draw(
        (lam,), shape,
        lambda s, l: jax.random.exponential(_key(), s, _f(dtype)) / l)


@register("sample_poisson", differentiable=False, aliases=("_sample_poisson",))
def _sample_poisson(lam, shape=None, dtype=None, **_):
    return _broadcast_draw(
        (lam,), shape,
        lambda s, l: jax.random.poisson(_key(), l, s).astype(_f(dtype)))


@register("sample_negative_binomial", differentiable=False,
          aliases=("_sample_negative_binomial",))
def _sample_negative_binomial(k, p, shape=None, dtype=None, **_):
    def draw(s, kk, pp):
        k1, k2 = jax.random.split(_key())
        rate = jax.random.gamma(k1, kk, s) * ((1.0 - pp) / pp)
        return jax.random.poisson(k2, rate, s).astype(_f(dtype))
    return _broadcast_draw((k, p), shape, draw)


@register("sample_generalized_negative_binomial", differentiable=False,
          aliases=("_sample_generalized_negative_binomial",))
def _sample_gen_negative_binomial(mu, alpha, shape=None, dtype=None, **_):
    def draw(s, m, a):
        k1, k2 = jax.random.split(_key())
        rate = jax.random.gamma(k1, 1.0 / a, s) * (m * a)
        return jax.random.poisson(k2, rate, s).astype(_f(dtype))
    return _broadcast_draw((mu, alpha), shape, draw)


@register("sample_multinomial", differentiable=False,
          aliases=("_sample_multinomial", "multinomial"), num_outputs=-1)
def _sample_multinomial(data, shape=None, get_prob=False, dtype="int32", **_):
    """Draw class indices from probability rows
    (reference sample_multinomial_op.cc).  With get_prob=True also returns
    the log-likelihood of each draw (the REINFORCE use case)."""
    p = jnp.asarray(data)
    n = 1 if shape in (None, ()) else \
        int(jnp.prod(jnp.asarray(_shape(shape))))
    logits = jnp.log(jnp.maximum(p, 1e-37))
    draws = jax.random.categorical(_key(), logits[..., None, :], axis=-1,
                                   shape=p.shape[:-1] + (n,))
    out_shape = p.shape[:-1] + _shape(shape) if shape not in (None, ()) \
        else p.shape[:-1]
    idx = draws.reshape(out_shape).astype(jnp.dtype(dtype))
    if not get_prob:
        return idx
    lp = jnp.take_along_axis(
        logits, idx.reshape(p.shape[:-1] + (-1,)).astype(jnp.int32),
        axis=-1).reshape(out_shape)
    return idx, lp
