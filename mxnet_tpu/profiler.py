"""``mx.profiler`` — profiling facade.

Reference: src/profiler/profiler.h:251 (per-thread event buffers, Chrome
tracing JSON dump via DumpProfile, aggregate per-op stats) + python frontend
python/mxnet/profiler.py (set_config/start/stop/dumps, scoped
Domain/Task/Frame/Counter/Marker APIs).

TPU-native: jax.profiler writes XPlane/TensorBoard traces (the Chrome-trace
analog, viewable in TensorBoard/Perfetto); `jax.profiler.TraceAnnotation`
replaces scoped tasks; the aggregate per-op table (`dumps(format='table')`)
is synthesized from our own host-side event records to preserve the
`mx.profiler` UX.
"""
from __future__ import annotations

import json
import os
import time
import threading

__all__ = ["set_config", "start", "stop", "pause", "resume", "dump", "dumps",
           "set_state", "Domain", "Task", "Frame", "Event", "Counter",
           "Marker", "scope", "profiler_scope", "counters", "reset_counters",
           "counter_increment"]

_CONFIG = {"profile_all": False, "filename": "profile.json",
           "aggregate_stats": True}
_STATE = {"running": False, "trace_dir": None, "last_trace_dir": None,
          "t0": None}
_EVENTS = []
_EVENTS_LOCK = threading.Lock()

# ------------------------------------------------------- dispatch counters
# Compile/dispatch observability for the fused train-step paths (Module's
# fused step and SPMDTrainer): recompile churn shows up as a rising
# `fused_compiles` count instead of having to be inferred from step-time
# jitter.  `host_syncs` counts the per-step host->device hyperparameter
# uploads (lr/wd schedule values that changed since the last step) — the
# only host traffic a healthy fused step pays.
#
# Since the telemetry PR these live on the mx.telemetry registry (one
# thread-safe home for every runtime metric); this facade keeps the PR-1
# API working and `counters()` now returns the FULL counter registry
# (dispatch + kvstore/io/engine counters) — the four dispatch names are
# always present.
_COUNTER_NAMES = ("fused_steps", "fused_compiles", "eager_steps",
                  "host_syncs")


def counter_increment(name, delta=1):
    """Bump a registry counter (unknown names are created on first use so
    callers can add ad-hoc counters without registering)."""
    from . import telemetry
    telemetry.counter(name).inc(delta)


def counters():
    """Snapshot of the counter registry: steps run per path, programs
    compiled, host syncs, plus any subsystem counters (kvstore.*, io.*).
    `fused_steps`/`eager_steps` count Module / SPMDTrainer train iterations
    by path, `fused_compiles` counts distinct compiled step programs (one
    per shape signature — a rising count at a fixed shape is recompile
    churn), `host_syncs` counts hyperparameter host->device uploads."""
    from . import telemetry
    return telemetry.snapshot()["counters"]


def reset_counters():
    from . import telemetry
    telemetry.reset_counters()


def set_config(**kwargs):
    """Accepts the reference's knobs (profile_symbolic, profile_imperative,
    profile_memory, profile_api, aggregate_stats, filename...); the ones
    meaningful on TPU map to the jax trace dir + host event table."""
    _CONFIG.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):
    import jax
    if _STATE["running"]:
        return
    # a new run invalidates the previous run's trace for implicit reads —
    # device_op_events() must never silently serve stale data mid-run
    _STATE["last_trace_dir"] = None
    trace_dir = _CONFIG.get("trace_dir") or os.path.splitext(
        _CONFIG["filename"])[0] + "_xplane"
    try:
        jax.profiler.start_trace(trace_dir)
        _STATE["trace_dir"] = trace_dir
    except Exception:
        _STATE["trace_dir"] = None  # device tracing unavailable: host only
    _STATE["running"] = True
    _STATE["t0"] = time.perf_counter()


def stop(profile_process="worker"):
    import jax
    if not _STATE["running"]:
        return
    if _STATE["trace_dir"] is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
    # the just-finished capture stays readable (dumps() right after stop()
    # is the normal UX) via last_trace_dir, but the ACTIVE dir is cleared:
    # a later device_op_events() during the next run can no longer silently
    # read this run's trace.  Explicit reads use the trace_dir= argument.
    _STATE["last_trace_dir"] = _STATE["trace_dir"]
    _STATE["trace_dir"] = None
    _STATE["running"] = False


def pause(profile_process="worker"):
    stop()


def resume(profile_process="worker"):
    start()


def _record(kind, name, t_start, t_end, args=None):
    with _EVENTS_LOCK:
        _EVENTS.append({"kind": kind, "name": name, "ts": t_start,
                        "dur": t_end - t_start, "args": args or {}})


def _latest_trace_file(trace_dir):
    """Newest Chrome-trace export inside a jax.profiler trace dir."""
    import glob
    files = glob.glob(os.path.join(trace_dir, "plugins", "profile", "*",
                                   "*.trace.json.gz"))
    return max(files, key=os.path.getmtime) if files else None


def device_op_events(trace_dir=None):
    """Parse the captured trace into per-DEVICE-op timing events.

    Returns {op_name: [durations_in_seconds]} from trace processes whose
    name marks a device plane ("/device:TPU:0" etc.) — the data the
    reference's aggregate_stats.cc collects from kernel timestamps.  Host
    python threads are excluded.  Empty dict when no device plane exists
    (e.g. CPU backend, which exports only host tracing).

    With no ``trace_dir`` argument the ACTIVE capture is read, falling back
    to the run that ``stop()`` just finished; a previous run's directory is
    never read implicitly once a new ``start()`` happens (pass ``trace_dir=``
    explicitly to inspect an old capture).
    """
    import glob
    import gzip

    trace_dir = trace_dir or _STATE.get("trace_dir") \
        or _STATE.get("last_trace_dir")
    if not trace_dir:
        return {}
    path = _latest_trace_file(trace_dir)
    if path is None:
        return {}
    with gzip.open(path) as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = e.get("args", {}).get("name", "")
            if "/device:" in pname.lower() or pname.startswith("TPU") or \
                    "accelerator" in pname.lower():
                device_pids.add(e["pid"])
    out = {}
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in device_pids:
            name = e.get("name", "")
            if name:
                out.setdefault(name, []).append(e.get("dur", 0) / 1e6)
    return out


def dump(finished=True, profile_process="worker"):
    """Write host-side events as Chrome tracing JSON next to the XPlane dir
    (reference: DumpProfile, src/profiler/profiler.h:299)."""
    with _EVENTS_LOCK:
        events = list(_EVENTS)
    trace = {"traceEvents": [
        {"name": e["name"], "cat": e["kind"], "ph": "X",
         "ts": e["ts"] * 1e6, "dur": e["dur"] * 1e6, "pid": 0, "tid": 0,
         "args": e["args"]} for e in events]}
    with open(_CONFIG["filename"], "w") as f:
        json.dump(trace, f)
    return _CONFIG["filename"]


def _stats_rows(samples):
    """name -> list[seconds] into aggregate rows."""
    agg = {}
    for name, durs in samples.items():
        agg[name] = {"count": len(durs), "total": sum(durs),
                     "min": min(durs), "max": max(durs)}
    return agg


def _format_table(agg, title, sort_by, ascending):
    rows = sorted(agg.items(), key=lambda kv: kv[1][sort_by],
                  reverse=not ascending)
    lines = [title,
             "%-40s %8s %12s %12s %12s" % ("Name", "Calls", "Total(ms)",
                                           "Min(ms)", "Max(ms)")]
    for name, s in rows:
        lines.append("%-40s %8d %12.3f %12.3f %12.3f"
                     % (name[:40], s["count"], s["total"] * 1e3,
                        s["min"] * 1e3, s["max"] * 1e3))
    return lines


def _format_timer_table(timers, sort_by, ascending):
    order = sort_by if sort_by in ("count", "total", "min", "max") \
        else "total"
    rows = sorted(timers.items(), key=lambda kv: kv[1][order],
                  reverse=not ascending)
    lines = ["Telemetry timers",
             "%-32s %8s %11s %10s %10s %10s %10s"
             % ("Name", "Calls", "Total(ms)", "Min(ms)", "Max(ms)",
                "P50(ms)", "P99(ms)")]
    for name, s in rows:
        lines.append("%-32s %8d %11.3f %10.3f %10.3f %10.3f %10.3f"
                     % (name[:32], s["count"], s["total"] * 1e3,
                        s["min"] * 1e3, s["max"] * 1e3, s["p50"] * 1e3,
                        s["p99"] * 1e3))
    return lines


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate stats table (reference: aggregate_stats.cc).

    Sections: DEVICE ops parsed from the captured jax.profiler trace
    (per-XLA-op kernel times on the TPU — the question "which op is slow on
    device"), host-side facade events (Task/Frame/scope), then the
    mx.telemetry registry — step/phase timers with percentiles, gauges
    (queue depths), and counters (dispatch paths, kvstore traffic).  The
    device section is present whenever a trace with a device plane was
    captured between start() and stop().

    ``reset=True`` clears BOTH the host event buffer and the telemetry
    registry (counters included — PR-1 left the dispatch counters running
    across resets, which made back-to-back profiled runs additive).
    """
    from . import telemetry
    with _EVENTS_LOCK:
        events = list(_EVENTS)
        if reset:
            _EVENTS.clear()
    snap = telemetry.snapshot()
    if reset:
        telemetry.reset()
    lines = []
    dev = device_op_events()
    if dev:
        lines += _format_table(_stats_rows(dev),
                               "Device ops (from XLA trace)", sort_by,
                               ascending)
        lines.append("")
    host = {}
    for e in events:
        host.setdefault(e["name"], []).append(e["dur"])
    lines += _format_table(_stats_rows(host) if host else {},
                           "Host events", sort_by, ascending)
    lines.append("")
    lines += _format_timer_table(snap["timers"], sort_by, ascending)
    lines.append("")
    lines.append("Gauges")
    for k in sorted(snap["gauges"]):
        lines.append("%-40s %12s" % (k, snap["gauges"][k]))
    if any(snap["counters"].values()):
        lines.append("")
        lines.append("Counters (dispatch + subsystem)")
        for k in sorted(snap["counters"]):
            lines.append("%-40s %12d" % (k, snap["counters"][k]))
    return "\n".join(lines)


class Domain:
    """Named grouping (reference: profiler.py Domain)."""

    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Scoped:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = "%s::%s" % (domain.name, name) if domain else name
        self._t0 = None
        self._ann = None

    def start(self):
        import jax
        self._t0 = time.perf_counter()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        return self

    def stop(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if self._t0 is not None:
            _record(self.__class__.__name__.lower(), self.name, self._t0,
                    time.perf_counter())
            self._t0 = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class Task(_Scoped):
    pass


class Frame(_Scoped):
    pass


class Event(_Scoped):
    pass


class Counter:
    def __init__(self, domain, name, value=None):
        self.name = "%s::%s" % (domain.name, name) if domain else name
        self.value = value or 0
        self._lock = threading.Lock()

    def set_value(self, value):
        with self._lock:
            self.value = value
        self._record_value(value)

    def _record_value(self, value):
        t = time.perf_counter()
        _record("counter", self.name, t, t, {"value": value})

    def increment(self, delta=1):
        # read-modify-write under the lock: concurrent increments from
        # engine/io threads must never lose updates
        with self._lock:
            self.value += delta
            value = self.value
        self._record_value(value)

    def decrement(self, delta=1):
        self.increment(-delta)


class Marker:
    def __init__(self, domain, name):
        self.name = "%s::%s" % (domain.name, name) if domain else name

    def mark(self, scope="process"):
        t = time.perf_counter()
        _record("marker", self.name, t, t)


class scope(_Scoped):
    """`with mx.profiler.scope('fwd'):` convenience."""

    def __init__(self, name="mxnet_tpu"):
        super().__init__(None, name)


profiler_scope = scope
