"""``mx.io`` — data iterators.

Reference: src/io/ (native chained iterator pipeline: parse → decode →
augment → batch → prefetch, SURVEY.md §3.5) and python/mxnet/io/
(`DataIter`, `NDArrayIter`, `MXDataIter` over the C iterators).

TPU-native re-design: host-side input pipelines stay in Python/NumPy (the
accelerator never touches them) with a background-thread prefetcher replacing
dmlc::ThreadedIter (src/io/iter_prefetcher.h:66).  Batches are plain host
arrays until the training step shards them onto the mesh — minimizing
host↔device transfers is the TPU analog of the reference's pinned-memory
pipeline.  RecordIO-backed image pipelines live in mxnet_tpu.image /
mxnet_tpu.recordio.
"""
from __future__ import annotations

import logging
import queue
import threading
import time as _time
from collections import namedtuple

import numpy as _np

from . import telemetry as _telemetry
from . import resilience as _resilience
from .ndarray.ndarray import NDArray, _wrap
import jax
import jax.numpy as jnp

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "DevicePrefetcher", "MNISTIter",
           "LibSVMIter", "ImageDetRecordIter", "ImageRecordIter",
           "ensure_staged", "is_staged", "bucket_sizes", "pick_bucket",
           "pad_rows_to"]

_LOG = logging.getLogger("mxnet_tpu.io")


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """Named shape/dtype descriptor (reference: python/mxnet/io/io.py
    DataDesc)."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), _np.dtype(dtype),
                               layout)


class DataBatch:
    """One batch: list of data arrays + list of label arrays + pad count."""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label if label is not None else []
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __repr__(self):
        shapes = [getattr(d, "shape", None) for d in self.data]
        return "DataBatch: data shapes %s" % (shapes,)


class DataIter:
    """Iterator protocol (reference: python/mxnet/io/io.py DataIter).

    Subclasses implement ``next()`` raising StopIteration, plus
    ``provide_data``/``provide_label`` and ``reset()``.
    """

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        raise NotImplementedError

    def __next__(self):
        # batch-fetch latency for every iterator on the pipeline boundary:
        # a slow p99 here means the chip starves waiting on host data.
        # Transient I/O errors (network filesystems, object stores) retry
        # with backoff; StopIteration passes straight through.
        t0 = _time.perf_counter()
        batch = _resilience.call_with_retry(self.next, kind="io",
                                            inject_faults=True)
        _telemetry.timer("io.batch_fetch").observe(
            _time.perf_counter() - t0)
        return batch

    # legacy pull-style API
    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            self._next_batch = None
            return False

    def getdata(self):
        return self._next_batch.data

    def getlabel(self):
        return self._next_batch.label

    def getindex(self):
        return self._next_batch.index

    def getpad(self):
        return self._next_batch.pad


def _as_arrays(data, prefix):
    """Normalize dict/list/array input to ordered [(name, ndarray)]."""
    if data is None:
        return []
    if isinstance(data, dict):
        items = list(data.items())
    elif isinstance(data, (list, tuple)):
        items = [("%s%d" % (prefix, i) if i else prefix, d)
                 for i, d in enumerate(data)]
    else:
        items = [(prefix, data)]
    out = []
    for name, d in items:
        if isinstance(d, NDArray):
            d = d.asnumpy()
        out.append((name, _np.asarray(d)))
    return out


class NDArrayIter(DataIter):
    """Batching iterator over in-memory arrays (reference:
    python/mxnet/io/io.py NDArrayIter: shuffle, pad/discard/roll_over
    last-batch handling)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _as_arrays(data, data_name)
        self.label = _as_arrays(label, label_name)
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        for _, d in self.data + self.label:
            assert d.shape[0] == self.num_data, "inconsistent data length"
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self._order = _np.arange(self.num_data)
        self.cursor = -batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(n, (self.batch_size,) + d.shape[1:], d.dtype)
                for n, d in self.data]

    @property
    def provide_label(self):
        return [DataDesc(n, (self.batch_size,) + d.shape[1:], d.dtype)
                for n, d in self.label]

    def reset(self):
        """pad: wrap-pad the final short batch. discard: drop it.
        roll_over: its samples lead the NEXT epoch (reference NDArrayIter
        semantics — no duplication within an epoch)."""
        leftover = None
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            leftover = self._order[self.cursor:self.num_data].copy()
        if self.shuffle:
            _np.random.shuffle(self._order)
        if leftover is not None and len(leftover):
            rest = self._order[~_np.isin(self._order, leftover)] \
                if self.shuffle else \
                self._order[:len(self._order) - len(leftover)]
            # leftover samples first, then the rest of the (re)ordered epoch
            self._order = _np.concatenate(
                [leftover, rest[:self.num_data - len(leftover)]])
        self.cursor = -self.batch_size

    def _slice(self, arrs):
        start = self.cursor
        end = start + self.batch_size
        out = []
        for _, d in arrs:
            idx = self._order[start:min(end, self.num_data)]
            part = d[idx]
            if end > self.num_data:  # pad by wrapping
                wrap = self._order[0:end - self.num_data]
                part = _np.concatenate([part, d[wrap]], axis=0)
            out.append(_wrap(jnp.asarray(part)))
        return out

    def next(self):
        self.cursor += self.batch_size
        if self.cursor >= self.num_data:
            raise StopIteration
        end = self.cursor + self.batch_size
        pad = max(0, end - self.num_data)
        if pad and self.last_batch_handle in ("discard", "roll_over"):
            # roll_over: leave cursor where it is; reset() rolls the unseen
            # samples into the next epoch
            raise StopIteration
        return DataBatch(self._slice(self.data), self._slice(self.label),
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)


class CSVIter(DataIter):
    """CSV reader (reference: src/io/iter_csv.cc:218) — eager numpy load,
    then NDArrayIter batching."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = self._load_csv(data_csv)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = self._load_csv(label_csv)
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")
        super().__init__(batch_size)

    @staticmethod
    def _load_csv(path):
        try:  # native fast parser (src/native/recordio.cc csv_parse_f32)
            from .native import csv_parse, available
            if available():
                arr = csv_parse(path)
                if arr is not None:
                    return arr
        except Exception:
            pass
        return _np.loadtxt(path, delimiter=",", dtype=_np.float32)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class MNISTIter(DataIter):
    """MNIST idx-format reader (reference: src/io/iter_mnist.cc:260)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 **kwargs):
        import gzip
        import struct

        def read_idx(path):
            op = gzip.open if path.endswith(".gz") else open
            with op(path, "rb") as f:
                magic = struct.unpack(">HBB", f.read(4))
                ndim = magic[2]
                dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(dims)

        img = read_idx(image).astype(_np.float32) / 255.0
        lbl = read_idx(label).astype(_np.float32)
        if flat:
            img = img.reshape(img.shape[0], -1)
        else:
            img = img.reshape(img.shape[0], 1, *img.shape[1:])
        self._inner = NDArrayIter({"data": img}, {"softmax_label": lbl},
                                  batch_size, shuffle=shuffle)
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class ResizeIter(DataIter):
    """Truncate/extend an iterator to a fixed number of batches
    (reference: python/mxnet/io/io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur >= self.size:
            raise StopIteration
        self.cur += 1
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()


# --------------------------------------------------------------------- #
# device staging — sharded H2D placement helpers (DALI/tf.data analog:
# the accelerator only ever sees decoded, padded, device-resident batches)
# --------------------------------------------------------------------- #

def _as_sharding(placement):
    """Normalize a placement spec to a jax Sharding (or None = default
    device).  Accepts None, a ``jax.Device``, any ``jax.sharding.Sharding``,
    or a zero-arg callable returning one of those (lazy resolution, e.g.
    ``lambda: trainer.batch_sharding`` before the trainer built its mesh)."""
    if placement is None:
        return None
    if callable(placement) and not isinstance(placement,
                                              jax.sharding.Sharding):
        placement = placement()
        if placement is None:
            return None
    if isinstance(placement, jax.Device):
        return jax.sharding.SingleDeviceSharding(placement)
    return placement


def _matches_sharding(x, sharding):
    """True if jax array ``x`` already lives under ``sharding``."""
    if sharding is None:
        return True
    try:
        return x.sharding.is_equivalent_to(sharding, x.ndim)
    except Exception:
        return x.sharding == sharding


def is_staged(x, placement=None):
    """True if ``x`` is already a device-resident jax array placed per
    ``placement`` (any device when ``placement`` is None)."""
    if isinstance(x, NDArray):
        x = x._data
    if not isinstance(x, jax.Array):
        return False
    return _matches_sharding(x, _as_sharding(placement))


def _stage_put(x, sharding, source):
    """One instrumented ``jax.device_put``: host memory (or a mis-placed
    device array) goes STRAIGHT to its final sharding — never through an
    intermediate commit to the default device (the double-copy this PR
    removes from ``SPMDTrainer._step_impl``)."""
    from . import tracing as _tracing
    if isinstance(x, NDArray):
        x = x._data
    nbytes = int(getattr(x, "nbytes", 0) or 0)
    t0 = _time.perf_counter()
    with _tracing.span("io.h2d", cat="io", source=source, bytes=nbytes):
        out = (jax.device_put(x, sharding) if sharding is not None
               else jax.device_put(x))
    _telemetry.timer("io.h2d_ms").observe((_time.perf_counter() - t0) * 1e3)
    _telemetry.counter("io.staged_bytes").inc(nbytes)
    if str(getattr(x, "dtype", "")) == "int8":
        # quantized payloads (deploy format v3 int8 weights) — lets the
        # serving dashboards attribute upload volume to int8 vs fp32
        _telemetry.counter("io.staged_int8_bytes").inc(nbytes)
    return out


def ensure_staged(x, placement=None, source="step"):
    """Return ``x`` as a device-resident jax array under ``placement``.

    Already-staged inputs (e.g. from a :class:`DevicePrefetcher`) pass
    through untouched — zero copies.  Anything else is fed straight to the
    sharded ``jax.device_put`` and counted as a SYNCHRONOUS caller-thread
    transfer (``io.h2d_sync`` + ``io.h2d_sync.<source>`` counters, next to
    the ``io.h2d_ms`` timer): in steady state with device prefetch on these
    counters must stay flat, which is how tests assert the hot loop never
    blocks on H2D.
    """
    if isinstance(x, NDArray):
        x = x._data
    sharding = _as_sharding(placement)
    if isinstance(x, jax.Array) and _matches_sharding(x, sharding):
        return x
    _telemetry.counter("io.h2d_sync").inc()
    _telemetry.counter("io.h2d_sync." + source).inc()
    return _stage_put(x, sharding, source)


def bucket_sizes(policy, batch_size):
    """Row-count buckets a ragged batch may be padded up to.

    ``"full"``  → one bucket: ``batch_size`` (zero recompiles per epoch),
    ``"pow2"``  → powers of two up to ``batch_size`` (≤ log2 N shapes),
    ``"off"``   → no padding (each ragged tail compiles a fresh program).

    Shared pad-bucket policy: ``DevicePrefetcher`` buckets training batches
    with it and ``mx.serving`` buckets coalesced inference requests with it,
    so both sides of the framework agree on which shapes ever reach the
    compiler.
    """
    policy = str(policy or "off").strip().lower()
    if policy in ("off", "none", ""):
        return ()
    if policy == "full":
        return (batch_size,)
    if policy == "pow2":
        sizes, b = [], 1
        while b < batch_size:
            sizes.append(b)
            b *= 2
        sizes.append(batch_size)
        return tuple(sizes)
    raise ValueError(
        "io.pad_buckets must be 'off', 'full' or 'pow2', got %r" % (policy,))


_bucket_sizes = bucket_sizes  # PR-5 internal name, kept for callers/tests


def pick_bucket(buckets, n):
    """Smallest bucket that fits ``n`` rows, or None when no bucket does
    (the caller keeps the natural shape)."""
    return next((b for b in buckets if b >= n), None)


def pad_rows_to(arr, target, fill=None):
    """Pad ``arr`` along axis 0 up to ``target`` rows.

    Default is wrap-padding — the NDArrayIter roll-over semantics, so fill
    rows hold real (repeated) samples and stay in-distribution for unmasked
    consumers.  With ``fill`` set, pad rows are that CONSTANT instead: the
    sentinel-id contract for integer index batches feeding sharded
    embeddings (docs/PERF_NOTES.md) — a sentinel >= the table's row count
    is masked out of both the lookup and the row-sparse update, so padded
    positions never gather real rows or touch the table.  Accepts numpy,
    jax arrays or NDArray; returns the same flavor it was given (host
    numpy stays host-side)."""
    raw = arr._data if isinstance(arr, NDArray) else arr
    host = _np.asarray(raw)
    n = host.shape[0]
    if fill is None:
        idx = _np.arange(target - n) % max(n, 1)
        tail = host[idx]
    else:
        tail = _np.full((target - n,) + host.shape[1:], fill, host.dtype)
    out = _np.concatenate([host, tail], axis=0)
    return _wrap(jnp.asarray(out)) if isinstance(arr, NDArray) else out


def _shutdown_prefetch_worker(thread, stop_event, q, deadline_s=5.0):
    """Stop a prefetch worker with a HARD deadline.

    Sets the stop event, keeps the ring drained so a blocked ``put``
    unblocks, and joins in slices until ``deadline_s``.  A worker that still
    won't die is surfaced (``io.prefetch_thread_leaked`` counter + warning).
    Returns True if the worker exited; on False the caller must NOT restart
    a new worker — the leaked thread still calls ``next()`` on the inner
    iterators, so rewinding them and consuming from a replacement would
    race two threads on one iterator."""
    stop_event.set()
    if thread is None:
        return True
    deadline = _time.perf_counter() + deadline_s
    while thread.is_alive():
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        remaining = deadline - _time.perf_counter()
        if remaining <= 0:
            break
        thread.join(timeout=min(0.2, remaining))
    if thread.is_alive():
        _telemetry.counter("io.prefetch_thread_leaked").inc()
        _LOG.warning(
            "prefetch worker did not stop within %.1fs and was leaked; "
            "the daemon thread will die with the process but its iterator "
            "state is now untrusted (io.prefetch_thread_leaked counter)",
            deadline_s)
        return False
    return True


class PrefetchingIter(DataIter):
    """Background-thread double buffering — the dmlc::ThreadedIter analog
    (src/io/iter_prefetcher.h:66,142).  Overlaps host batch prep with device
    compute; with jax async dispatch one prefetch depth is enough to keep the
    chip fed."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 depth=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        if depth is None:
            from . import config as _config
            depth = _config.get("io.prefetch_depth")
        self.iters = iters
        # Concurrency discipline (lock-checked by tools/mxlint.py): the
        # worker closes over snapshots of _stop/_queue, never reads them
        # through self, so the consumer thread may rebind them in reset()
        # without a lock — cross-thread handoff is the Queue itself.
        self._queue = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = None
        self._start()

    def _start(self):
        from . import tracing as _tracing
        stop = self._stop
        q = self._queue

        def worker():
            while not stop.is_set():
                try:
                    with _tracing.span("io.prefetch", cat="io"):
                        batches = [
                            _resilience.call_with_retry(
                                it.next, kind="io", inject_faults=True)
                            for it in self.iters]
                except StopIteration:
                    q.put(None)
                    return
                q.put(batches[0] if len(batches) == 1 else batches)

        # wrap_context snapshots the caller's contextvars so prefetch spans
        # keep the parent trace id across the thread hop
        self._thread = threading.Thread(
            target=_tracing.wrap_context(worker), daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        return self.iters[0].provide_data

    @property
    def provide_label(self):
        return self.iters[0].provide_label

    def reset(self):
        if not _shutdown_prefetch_worker(self._thread, self._stop,
                                         self._queue):
            raise RuntimeError(
                "prefetch worker did not stop within the reset deadline; "
                "refusing to rewind/restart while it may still consume the "
                "inner iterators — recreate the PrefetchingIter instead")
        for it in self.iters:
            it.reset()
        self._exhausted = False
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self._queue.maxsize)
        self._start()

    def next(self):
        if getattr(self, "_exhausted", False):
            raise StopIteration
        # depth sampled at consume time: a gauge pinned at 0 means the
        # prefetch thread can't keep ahead of the training loop
        _telemetry.gauge("io.prefetch_queue_depth").set(self._queue.qsize())
        item = self._queue.get()
        if item is None:
            self._exhausted = True
            raise StopIteration
        return item


class _WorkerFailure:
    """Queue sentinel carrying an exception out of the prefetch worker so
    the consumer re-raises it instead of hanging on an empty ring."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class DevicePrefetcher(DataIter):
    """Device-side prefetch: the training loop's tf.data/DALI analog.

    Wraps any :class:`DataIter` and, on a background thread (tracing
    ``wrap_context`` preserved, batch pulls under PR-4 retry/fault
    injection), (1) wrap-pads ragged batches up to a small set of bucketed
    row counts — ``DataBatch.pad`` counts the fill rows so losses/metrics
    can mask them — and (2) performs the sharded ``jax.device_put`` against
    the consumer's placement (a ``NamedSharding``, device, or lazy callable
    such as ``trainer.batch_sharding`` — re-invoked every batch until it
    yields a placement, so constructing the prefetcher before params/mesh
    exist is safe; early batches just stay host-side).  The consumer pops
    a depth-N ring
    of device-resident, donation-ready batches: ``Module._run_fused``,
    ``SPMDTrainer.step`` and ``gluon.Trainer`` see pre-placed arrays and the
    caller thread never blocks on H2D in steady state (``io.h2d_sync`` stays
    flat; transfers count under ``io.h2d_async``).

    Knobs: ``io.device_prefetch`` gates staging (off = host-side prefetch
    A/B baseline), ``io.prefetch_depth`` sizes the ring, ``io.pad_buckets``
    picks the bucket policy.  Telemetry: ``io.h2d_ms`` timer,
    ``io.staged_bytes``, ``io.ring_occupancy`` gauge,
    ``io.pad_recompiles_avoided``, plus ``io.h2d`` spans in the trace.
    """

    def __init__(self, iters, placement=None, depth=None, buckets=None,
                 rename_data=None, rename_label=None, pad_sentinel=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        from . import config as _config
        if depth is None:
            depth = _config.get("io.prefetch_depth")
        if buckets is None:
            buckets = _config.get("io.pad_buckets")
        self.iters = iters
        self._placement = placement
        self._pad_sentinel = pad_sentinel
        self._buckets = _bucket_sizes(buckets, self.batch_size)
        # Concurrency discipline (lock-checked by tools/mxlint.py): the
        # worker closes over snapshots of _stop/_queue/put; _seen_shapes
        # and the padding state are touched only from the worker thread
        # (reset() joins it before rebinding anything), so the class
        # needs no lock — cross-thread handoff is the Queue itself.
        self._seen_shapes = set()
        self._queue = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = None
        self._exhausted = False
        self._start()

    # ---------------------------------------------------------- padding
    def _rows(self, batch):
        for arr in batch.data:
            shape = getattr(arr, "shape", None)
            if shape:
                return int(shape[0])
        return None

    def _pad_rows(self, arr, target):
        """Instance seam over the shared :func:`pad_rows_to` (tests
        monkeypatch it to exercise the fallback path).  With
        ``pad_sentinel`` set, INTEGER-dtype arrays (embedding id batches)
        pad with the sentinel id instead of wrapped rows — the pad-masked
        loss discards those rows either way, but sentinel ids additionally
        never gather from or write to a sharded embedding table."""
        raw = arr._data if type(arr) is NDArray else arr
        if self._pad_sentinel is not None \
                and _np.issubdtype(_np.asarray(raw).dtype, _np.integer):
            return pad_rows_to(arr, target, fill=self._pad_sentinel)
        return pad_rows_to(arr, target)

    def _pad_to_bucket(self, batch):
        if not self._buckets:
            return batch
        n = self._rows(batch)
        if n is None:
            return batch
        target = pick_bucket(self._buckets, n)
        if target is None or target == n:
            return batch
        if not all(isinstance(a._data if type(a) is NDArray else a,
                              (jax.Array, _np.ndarray))
                   for a in list(batch.data) + list(batch.label)):
            # non-dense payloads (e.g. CSR batches) stage at natural shape
            return batch
        add = target - n
        try:
            data = [self._pad_rows(a, target) for a in batch.data]
            label = [self._pad_rows(a, target) for a in batch.label]
        except (TypeError, ValueError) as exc:
            # a dense batch that fails to wrap-pad is a real bug upstream
            # (e.g. mismatched leading dims) — count + warn so the shape
            # churn this re-buys is visible, never silently swallowed
            _telemetry.counter("io.pad_fallback").inc()
            _LOG.warning(
                "bucketed padding failed (%s); staging batch at natural "
                "row count %d — recompile churn possible "
                "(io.pad_fallback counter)", exc, n)
            return batch
        shape_key = tuple(tuple(getattr(a, "shape", ())) for a in data)
        if shape_key in self._seen_shapes:
            # this ragged tail would have compiled a fresh program
            _telemetry.counter("io.pad_recompiles_avoided").inc()
        return DataBatch(
            data, label, pad=int(batch.pad or 0) + add, index=batch.index,
            provide_data=self._repad_descs(batch.provide_data, target),
            provide_label=self._repad_descs(batch.provide_label, target))

    @staticmethod
    def _repad_descs(descs, rows):
        if not descs:
            return descs
        out = []
        for d in descs:
            if isinstance(d, DataDesc):
                out.append(DataDesc(d.name, (rows,) + tuple(d.shape[1:]),
                                    d.dtype, d.layout))
            else:
                name, shape = d[0], tuple(d[1])
                out.append((name, (rows,) + shape[1:]) + tuple(d[2:]))
        return out

    # ---------------------------------------------------------- staging
    def _stage_batch(self, batch, sharding):
        batch.data = [self._stage_one(a, sharding) for a in batch.data]
        batch.label = [self._stage_one(a, sharding) for a in batch.label]
        return batch

    def _stage_one(self, a, sharding):
        raw = a._data if type(a) is NDArray else a
        if not isinstance(raw, (jax.Array, _np.ndarray)):
            return a  # sparse / exotic payloads pass through host-side
        if isinstance(raw, jax.Array) and _matches_sharding(raw, sharding):
            return a
        _telemetry.counter("io.h2d_async").inc()
        staged = _stage_put(raw, sharding, "prefetch")
        return _wrap(staged) if isinstance(a, NDArray) else staged

    def _record_shapes(self, batch):
        self._seen_shapes.add(
            tuple(tuple(getattr(a, "shape", ())) for a in batch.data))

    def _resolve_placement(self):
        """Resolve the placement spec.  Returns ``(sharding, final)`` —
        ``final`` False means a lazy callable returned None (e.g.
        ``lambda: trainer.batch_sharding`` before params/mesh exist) and
        must be re-invoked on a later batch rather than cached, else every
        batch would silently stage to the default device forever."""
        p = self._placement
        lazy = callable(p) and not isinstance(p, jax.sharding.Sharding)
        sharding = _as_sharding(p)
        return sharding, not (lazy and sharding is None)

    # ----------------------------------------------------------- worker
    def _start(self):
        from . import tracing as _tracing
        from . import config as _config
        stop = self._stop
        q = self._queue

        def put(item):
            # bounded put that gives up when reset() is tearing us down,
            # so the worker can never deadlock against a full ring
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            sharding = _NOT_RESOLVED
            while not stop.is_set():
                try:
                    with _tracing.span("io.prefetch", cat="io"):
                        batches = [
                            _resilience.call_with_retry(
                                it.next, kind="io", inject_faults=True)
                            for it in self.iters]
                        batches = [self._pad_to_bucket(b) for b in batches]
                        if _config.get("io.device_prefetch"):
                            if sharding is _NOT_RESOLVED:
                                resolved, final = self._resolve_placement()
                                if final:
                                    sharding = resolved
                            if sharding is not _NOT_RESOLVED:
                                batches = [self._stage_batch(b, sharding)
                                           for b in batches]
                            # else: the lazy placement hasn't materialized
                            # yet — leave these batches host-side so the
                            # consumer's ensure_staged puts them on the
                            # REAL device (staging to the default device
                            # here would re-buy the double copy)
                        for b in batches:
                            self._record_shapes(b)
                except StopIteration:
                    put(None)
                    return
                except BaseException as exc:  # surface, don't hang consumer
                    put(_WorkerFailure(exc))
                    return
                if not put(batches[0] if len(batches) == 1 else batches):
                    return

        self._thread = threading.Thread(
            target=_tracing.wrap_context(worker), daemon=True,
            name="mx-device-prefetch")
        self._thread.start()

    # --------------------------------------------------------- consumer
    @property
    def provide_data(self):
        return self.iters[0].provide_data

    @property
    def provide_label(self):
        return self.iters[0].provide_label

    def reset(self):
        if not _shutdown_prefetch_worker(self._thread, self._stop,
                                         self._queue):
            raise RuntimeError(
                "device-prefetch worker did not stop within the reset "
                "deadline; refusing to rewind/restart while it may still "
                "consume the inner iterators — recreate the "
                "DevicePrefetcher instead")
        for it in self.iters:
            it.reset()
        self._exhausted = False
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self._queue.maxsize)
        self._start()

    def next(self):
        if self._exhausted:
            raise StopIteration
        # occupancy sampled at consume time: pinned at 0 means the staging
        # thread can't keep ahead of the training loop
        _telemetry.gauge("io.ring_occupancy").set(self._queue.qsize())
        item = self._queue.get()
        if item is None:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, _WorkerFailure):
            self._exhausted = True
            raise item.exc
        return item


class _NotResolved:
    """Sentinel: placement not yet resolved on the worker thread."""


_NOT_RESOLVED = _NotResolved()


class LibSVMIter(DataIter):
    """Batches of CSR data parsed from LibSVM text files (reference:
    src/io/iter_libsvm.cc, registered as LibSVMIter).

    Line format: ``label[,label2,...] idx:val idx:val ...``.  Data batches
    are ``CSRNDArray`` built per batch from the row slices — the sparse
    batching of the reference's iter_sparse_batchloader.h.  Labels come from
    the data file, or from ``label_libsvm`` (itself LibSVM-format sparse
    labels) when given.
    """

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=None, round_batch=True,
                 **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape) if not isinstance(
            data_shape, int) else (data_shape,)
        self._round_batch = round_batch
        rows, labels = self._parse(data_libsvm, self.data_shape[-1])
        self._rows = rows
        if label_libsvm is not None:
            lshape = tuple(label_shape) if label_shape else (1,)
            lrows, _ = self._parse(label_libsvm, lshape[-1])
            self._labels = _np.stack([
                self._densify(r, lshape[-1]) for r in lrows])
            if lshape == (1,):
                self._labels = self._labels[:, 0]
        else:
            self._labels = _np.asarray(labels, _np.float32)
        self.cur = 0

    @staticmethod
    def _parse(path, width):
        rows = []
        labels = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                labels.append(float(parts[0].split(",")[0]))
                idx = []
                val = []
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    idx.append(int(i))
                    val.append(float(v))
                rows.append((_np.asarray(idx, _np.int32),
                             _np.asarray(val, _np.float32)))
        return rows, labels

    @staticmethod
    def _densify(row, width):
        out = _np.zeros((width,), _np.float32)
        idx, val = row
        out[idx] = val
        return out

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self._labels.ndim == 1 else \
            (self.batch_size,) + self._labels.shape[1:]
        return [DataDesc("softmax_label", shp)]

    def reset(self):
        self.cur = 0

    def next(self):
        n = len(self._rows)
        if self.cur >= n:
            raise StopIteration
        take = list(range(self.cur, min(self.cur + self.batch_size, n)))
        pad = self.batch_size - len(take)
        if pad and self._round_batch:
            take += [j % n for j in range(pad)]  # wrap-pad like the reference
        self.cur += self.batch_size
        # sparse batching: concatenate row slices into one batch CSR
        width = self.data_shape[-1]
        indptr = [0]
        indices = []
        values = []
        for r in take:
            idx, val = self._rows[r]
            indices.extend(idx.tolist())
            values.extend(val.tolist())
            indptr.append(len(indices))
        from .ndarray.sparse import CSRNDArray
        data = CSRNDArray(_np.asarray(values, _np.float32), indptr, indices,
                          (len(take), width))
        label = _wrap(jnp.asarray(self._labels[take]))
        return DataBatch([data], [label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ImageDetRecordIter(DataIter):
    """Detection-record iterator (reference:
    src/io/iter_image_det_recordio.cc ImageDetRecordIter).

    Records are pack_img'ed with a flat float label of layout
    ``[A, B, extra..., obj0(id, xmin, ymin, xmax, ymax), obj1(...), ...]``
    where A = header length and B = values per object (the reference's
    im2rec detection format).  Batch labels are padded with -1 rows to
    ``label_pad_width`` objects so shapes stay static for jit — the
    reference pads identically (pad_label_value).
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=-1,
                 label_pad_width=-1, label_pad_value=-1.0, shuffle=False,
                 part_index=0, num_parts=1, aug_list=None, **kwargs):
        super().__init__(batch_size)
        from .image.image import ImageIter
        # reuse the image-record machinery for decode/augment/sharding
        self._img_iter = ImageIter(
            batch_size=batch_size, data_shape=data_shape,
            path_imgrec=path_imgrec, shuffle=shuffle, part_index=part_index,
            num_parts=num_parts, aug_list=aug_list if aug_list is not None
            else [], **kwargs)
        self.data_shape = tuple(data_shape)
        self._pad_value = float(label_pad_value)
        if label_pad_width > 0:
            self._pad_width = label_pad_width
        else:
            # scan labels once (headers only — no image decode) so every
            # batch has the same static label shape for jit
            from .recordio import unpack
            width = 1
            for key in self._img_iter._keys:
                header, _ = unpack(self._img_iter._rec.read_idx(key))
                width = max(width, len(self._objects(header.label)))
            self._pad_width = width
        self.cur = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size, self._pad_width, 5))]

    def reset(self):
        self._img_iter.reset()

    @staticmethod
    def _objects(flat):
        flat = _np.asarray(flat, _np.float32).ravel()
        if flat.size < 2:
            return _np.zeros((0, 5), _np.float32)
        header = int(flat[0])
        owidth = int(flat[1])
        body = flat[header:]
        nobj = len(body) // owidth
        return body[:nobj * owidth].reshape(nobj, owidth)[:, :5]

    def next(self):
        C, H, W = self.data_shape
        samples, pad = self._img_iter._batch_samples()
        batch_data = _np.zeros((self.batch_size, C, H, W), _np.float32)
        width = self._pad_width
        label = _np.full((self.batch_size, width, 5), self._pad_value,
                         _np.float32)
        for slot, d, l in samples:
            batch_data[slot] = d
            objs = self._objects(l)
            m = min(len(objs), width)
            label[slot, :m] = objs[:m]
        return DataBatch([_wrap(jnp.asarray(batch_data))],
                         [_wrap(jnp.asarray(label))], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


def ImageRecordIter(path_imgrec, batch_size, data_shape, **kwargs):
    """Classification RecordIO iterator (reference:
    src/io/iter_image_recordio_2.cc, registered as ImageRecordIter).

    Thin factory over mx.image.ImageIter, which implements the decode +
    augment + batch pipeline; kept here so reference scripts'
    ``mx.io.ImageRecordIter(...)`` call sites work unchanged.  Augmenter
    kwargs (resize/rand_crop/rand_mirror/mean/std...) pass through;
    engine-tuning knobs the XLA runtime owns (preprocess_threads,
    prefetch_buffer) are accepted and ignored.
    """
    from .image import ImageIter
    for ignored in ("preprocess_threads", "prefetch_buffer", "verify_decode",
                    "num_backup_threads"):
        kwargs.pop(ignored, None)
    if not kwargs.pop("round_batch", True):
        # round_batch=False changes partial-batch semantics (discard vs
        # roll-over); honor it rather than silently altering epoch behavior
        kwargs.setdefault("last_batch_handle", "discard")
    return ImageIter(batch_size=batch_size, data_shape=data_shape,
                     path_imgrec=path_imgrec, **kwargs)
