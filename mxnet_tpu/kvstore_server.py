"""``mx.kvstore_server`` (reference: python/mxnet/kvstore_server.py —
blocks a DMLC_ROLE=server process inside the ps-lite server loop).

TPU-native role collapse: there ARE no server processes — dist_sync is
peer allreduce over jax.distributed, so every launched process is a
worker.  `_init_kvstore_server_module` keeps old launch scripts working:
a process started with the server role exits cleanly instead of waiting
for pushes that will never arrive.
"""
from __future__ import annotations

import os
import sys

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    def __init__(self, kvstore):
        self.kvstore = kvstore

    def run(self):
        # nothing to serve: the merge happens in the workers' collective
        return


def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "")
    if role in ("server", "scheduler"):
        print("mxnet_tpu: role %r is obsolete (dist_sync is peer "
              "allreduce); exiting cleanly" % role, file=sys.stderr)
        sys.exit(0)
