"""Flat fused-RNN parameter-blob layout.

One walker for everything that touches the cuDNN-style packed parameter
vector (reference: src/operator/rnn-inl.h GetRnnParamSize +
python/mxnet/rnn/rnn_cell.py:600-640 FusedRNNCell._slice_weights): the
symbolic ``RNN`` op slices it at execution (ops/rnn.py), FusedRNNCell
packs/unpacks it by name, the parameter-shape rule sizes it, and the
FusedRNN initializer fills it region by region.

Layout: for each layer, for each direction — per-gate i2h weights
(H, in) then per-gate h2h weights (H, H); after ALL weights, the biases
in the same traversal order.  Layer 0 input width is the data width;
deeper layers see H * num_directions.
"""
from __future__ import annotations

GATES = {"rnn_relu": ("",), "rnn_tanh": ("",),
         "lstm": ("_i", "_f", "_c", "_o"), "gru": ("_r", "_z", "_o")}


def fused_rnn_regions(num_input, num_hidden, num_layers, mode,
                      bidirectional=False, prefix=""):
    """Yield (name, offset, shape, kind) for every slice of the blob.

    ``kind`` is one of i2h_weight/h2h_weight/i2h_bias/h2h_bias; ``name``
    follows the reference unpacked naming
    ``{prefix}{direction}{layer}_{i2h|h2h}{gate}_{weight|bias}``.
    """
    gates = GATES[mode]
    dirs = ("l", "r") if bidirectional else ("l",)
    h = num_hidden
    out = []
    off = 0
    for layer in range(num_layers):
        inp = num_input if layer == 0 else h * len(dirs)
        for d in dirs:
            for g in gates:
                out.append(("%s%s%d_i2h%s_weight" % (prefix, d, layer, g),
                            off, (h, inp), "i2h_weight"))
                off += h * inp
            for g in gates:
                out.append(("%s%s%d_h2h%s_weight" % (prefix, d, layer, g),
                            off, (h, h), "h2h_weight"))
                off += h * h
    for layer in range(num_layers):
        for d in dirs:
            for g in gates:
                out.append(("%s%s%d_i2h%s_bias" % (prefix, d, layer, g),
                            off, (h,), "i2h_bias"))
                off += h
            for g in gates:
                out.append(("%s%s%d_h2h%s_bias" % (prefix, d, layer, g),
                            off, (h,), "h2h_bias"))
                off += h
    return out, off


def fused_rnn_group_slices(num_input, num_hidden, num_layers, mode,
                           bidirectional=False):
    """Gate-stacked views of the blob, one record per (layer, direction):
    ``(i2h_w_off, i2h_w_shape, h2h_w_off, h2h_w_shape, i2h_b_off,
    h2h_b_off)`` with weight shapes ``(G*H, in)``/``(G*H, H)`` and biases
    ``(G*H,)``.  Valid because per-gate regions are contiguous in
    traversal order — this is what the executor (ops/rnn.py _rnn) slices,
    derived from the same walk as pack/unpack/init."""
    regions, _ = fused_rnn_regions(num_input, num_hidden, num_layers, mode,
                                   bidirectional)
    by_kind = {}
    for _, off, shape, kind in regions:
        by_kind.setdefault(kind, []).append((off, shape))
    g = len(GATES[mode])
    ndirs = 2 if bidirectional else 1
    out = []
    for grp in range(num_layers * ndirs):
        i2h = by_kind["i2h_weight"][grp * g:(grp + 1) * g]
        h2h = by_kind["h2h_weight"][grp * g:(grp + 1) * g]
        i2h_b = by_kind["i2h_bias"][grp * g:(grp + 1) * g]
        h2h_b = by_kind["h2h_bias"][grp * g:(grp + 1) * g]
        out.append((i2h[0][0], (g * num_hidden, i2h[0][1][1]),
                    h2h[0][0], (g * num_hidden, num_hidden),
                    i2h_b[0][0], h2h_b[0][0]))
    return out


def fused_rnn_param_size(num_input, num_hidden, num_layers, mode,
                         bidirectional=False):
    _, size = fused_rnn_regions(num_input, num_hidden, num_layers, mode,
                                bidirectional)
    return size


def fused_rnn_num_input(total_size, num_hidden, num_layers, mode,
                        bidirectional=False):
    """Invert fused_rnn_param_size for the data width (reference
    FusedRNNCell.unpack_weights derives num_input from the blob size)."""
    b = 2 if bidirectional else 1
    m = len(GATES[mode])
    h = num_hidden
    # total = b*m*h*(ni + h + 2) + (L-1)*b*m*h*(b*h + h + 2)
    ni = total_size // (b * m * h) - (num_layers - 1) * (b * h + h + 2) \
        - h - 2
    return ni
