"""Bucketing language-model data utilities (reference:
python/mxnet/rnn/io.py:30-211 — encode_sentences + BucketSentenceIter,
the feeder for BucketingModule LM training)."""
from __future__ import annotations

import bisect
import random

import numpy as np

from ..io import DataIter, DataBatch, DataDesc
from ..ndarray import array as nd_array

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Token-lists -> int-lists, growing ``vocab`` as needed (reference
    io.py:30).  Returns (encoded, vocab)."""
    idx = start_label
    new_vocab = vocab is None
    if new_vocab:
        vocab = {invalid_key: invalid_label}
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                assert new_vocab or unknown_token, \
                    "Unknown token %s" % word
                if idx == invalid_label:
                    idx += 1
                if unknown_token:
                    word = unknown_token
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Pads each sentence to its bucket length and serves per-bucket
    batches with ``bucket_key`` attached; label is the input shifted one
    step left (next-token LM).  Reference io.py:84."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32", layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            # auto-buckets: every length with at least a batch of sentences
            buckets = [i for i, j in
                       enumerate(np.bincount([len(s) for s in sentences]))
                       if j >= batch_size]
        buckets = sorted(buckets)
        assert buckets, "no buckets: pass buckets= or lower batch_size"

        self.data = [[] for _ in buckets]
        ndiscard = 0
        used = set()
        for sent in sentences:
            buck = bisect.bisect_left(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            used.add(buck)
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        buckets = [b for i, b in enumerate(buckets) if i in used]
        self.data = [np.asarray(d, dtype=dtype) for d in self.data if d]
        if ndiscard:
            print("WARNING: discarded %d sentences longer than the largest "
                  "bucket." % ndiscard)

        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.major_axis = layout.find("N")
        self.layout = layout
        self.default_bucket_key = max(buckets)

        shape = (batch_size, self.default_bucket_key) \
            if self.major_axis == 0 else \
            (self.default_bucket_key, batch_size)
        if self.major_axis not in (0, 1):
            raise ValueError("Invalid layout %s: Must be NT (batch major) "
                             "or TN (time major)" % layout)
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in
                             range(0, len(buck) - batch_size + 1,
                                   batch_size)])
        self.curr_idx = 0
        self.nddata = []
        self.ndlabel = []
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(nd_array(buck.astype(self.dtype)))
            self.ndlabel.append(nd_array(label.astype(self.dtype)))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        if self.major_axis == 1:
            data = data.T
            label = label.T
        batch = DataBatch(
            [data], [label], pad=0,
            provide_data=[DataDesc(self.data_name, data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, label.shape,
                                    layout=self.layout)])
        batch.bucket_key = self.buckets[i]
        return batch
